"""AmpOptimizer: master weights, unscale, overflow-skip — functionally.

The reference performs in-place surgery on torch optimizers
(apex/amp/_process_optimizer.py): clones fp16 params to fp32 masters and
swaps them into param_groups (:13-73), patches ``step`` to copy masters
back to the model (:286-296), and installs pre/post-backward hooks that the
``scale_loss`` context drives (:76-239).  Here the same observable behavior
is a pure wrapper: masters are optimizer *state*, unscale+overflow-check is
the fused multi_tensor_scale, and a skipped step is a ``lax.cond`` that
leaves (params, masters, inner state) untouched — the whole thing lives
inside jit with no host sync.
"""

from __future__ import annotations

import math
from typing import Any, Callable, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .scaler import LossScaler, ScalerState
from ..optimizers.base import Optimizer


def _axis_in_scope(name: str) -> bool:
    """True iff ``name`` is a currently-mapped collective axis — local
    copy of parallel.sync_batchnorm._axis_in_scope (imported inline
    would pull the parallel package into amp's import graph).  Public
    probe: ``lax.axis_index`` raises NameError for an unbound axis;
    pinned by tests/test_syncbn.py::test_axis_scope_probe."""
    try:
        jax.lax.axis_index(name)
        return True
    except NameError:
        return False
    except Exception:
        return True

__all__ = ["AmpOptState", "AmpOptimizer", "FlatMasters",
           "zero_optimizer_specs"]


def zero_optimizer_specs(optimizer: "AmpOptimizer", params: Any,
                         axis_name: str = "data") -> Any:
    """PartitionSpec tree for ``optimizer.init(params, zero_axis=...)``
    run inside shard_map — flat master/moment shards are ``P(axis)``
    (device-concat layout), scalars replicated.  Use as the out_specs of
    the mapped init and the in/out specs of the mapped step::

        ospecs = amp.zero_optimizer_specs(optimizer, params, "data")
        opt_state = jax.jit(jax.shard_map(
            lambda p: optimizer.init(p, zero_axis="data"), mesh=mesh,
            in_specs=(P(),), out_specs=ospecs, check_vma=False))(params)
    """
    from jax.sharding import PartitionSpec as P
    if not (optimizer.master_weights
            and getattr(optimizer.inner, "elementwise", False)):
        # same precondition init enforces — fail at the first API call
        # instead of inside a jitted trace later
        raise ValueError(
            "zero_axis requires master weights and an elementwise inner "
            "optimizer (the flat-buffer path)")
    layout = _FlatLayout(params)
    layout.zero_axis = axis_name

    def leaf_spec(l):
        return P() if getattr(l, "ndim", 0) == 0 else P(axis_name)

    inner_abs = jax.eval_shape(
        optimizer.inner.init,
        jax.ShapeDtypeStruct((max(layout.total, 1),), jnp.float32))
    inner_specs = jax.tree_util.tree_map(leaf_spec, inner_abs)
    scaler_abs = jax.eval_shape(optimizer.scaler.init_state)
    scaler_specs = tuple(
        jax.tree_util.tree_map(lambda _: P(), scaler_abs)
        for _ in range(optimizer.num_losses))
    return AmpOptState(inner=inner_specs,
                       masters=FlatMasters(P(axis_name), layout),
                       scalers=scaler_specs)


class AmpOptState(NamedTuple):
    inner: Any                     # wrapped optimizer's state
    masters: Any                   # FlatMasters | fp32 master pytree | None
    scalers: Tuple[ScalerState, ...]  # one per loss (num_losses)


def _to_fp32(tree):
    return jax.tree_util.tree_map(
        lambda p: p.astype(jnp.float32) if jnp.issubdtype(
            jnp.result_type(p), jnp.floating) else p, tree)


def _cast_like(tree, like):
    return jax.tree_util.tree_map(
        lambda x, l: x.astype(l.dtype) if jnp.issubdtype(
            jnp.result_type(l), jnp.floating) else x, tree, like)


class _FlatLayout:
    """Static description of a float-leaf flattening, computed once at
    ``AmpOptimizer.init``.  The reference flattens each param group once at
    construction (apex/optimizers/fp16_optimizer.py:57-70); round-1 apex_tpu
    instead re-packed the whole tree every step
    (round-2 VERDICT weak-item 2) — this layout makes pack/unpack a single
    concat / static-slice set that XLA folds into neighbouring ops."""

    def __init__(self, params):
        leaves, self.treedef = jax.tree_util.tree_flatten(params)
        self.shapes = tuple(tuple(l.shape) for l in leaves)
        self.dtypes = tuple(str(jnp.result_type(l)) for l in leaves)
        self.is_float = tuple(
            jnp.issubdtype(jnp.result_type(l), jnp.floating) for l in leaves)
        sizes, offsets, off = [], [], 0
        for shape, f in zip(self.shapes, self.is_float):
            n = int(math.prod(shape)) if f else 0
            sizes.append(n)
            offsets.append(off)
            off += n
        self.sizes = tuple(sizes)
        self.offsets = tuple(offsets)
        self.total = off
        halves = {d for d, f in zip(self.dtypes, self.is_float)
                  if f and d != "float32"}
        # the single non-fp32 float dtype (O2's cast_model_type), if any —
        # lets the fused Adam kernel emit the half model copy in-pass
        self.half_dtype = (jnp.dtype(halves.pop()) if len(halves) == 1
                           else None)

    # ZeRO-1: when set, the flat master/moment buffers hold only THIS
    # device's slice (sharded over the named data axis); the step
    # reduce-scatters grads and all-gathers the updated params
    zero_axis: Optional[str] = None

    # layouts are jit-cache keys via FlatMasters aux_data
    def _key(self):
        return (self.treedef, self.shapes, self.dtypes, self.zero_axis)

    def __eq__(self, other):
        return isinstance(other, _FlatLayout) and self._key() == other._key()

    def __hash__(self):
        return hash(self._key())

    def pack(self, tree) -> jax.Array:
        """Float leaves → one flat fp32 buffer (single concat)."""
        leaves = jax.tree_util.tree_leaves(tree)
        parts = [l.reshape(-1).astype(jnp.float32)
                 for l, f in zip(leaves, self.is_float) if f]
        if not parts:
            return jnp.zeros((0,), jnp.float32)
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

    def rebuild(self, flat32: jax.Array, half: Optional[jax.Array],
                like_leaves) -> Any:
        """Params tree from the updated flat fp32 buffer (+ optional half
        copy emitted by the kernel).  Non-float leaves pass through from
        ``like_leaves``; fp32 leaves slice from ``flat32``; half leaves
        slice from ``half`` when present (no extra cast pass)."""
        out = []
        for i, (shape, f) in enumerate(zip(self.shapes, self.is_float)):
            if not f:
                out.append(like_leaves[i])
                continue
            dt = jnp.dtype(self.dtypes[i])
            src = half if (half is not None and dt == half.dtype) else flat32
            piece = jax.lax.dynamic_slice_in_dim(
                src, self.offsets[i], self.sizes[i]).reshape(shape)
            if piece.dtype != dt:
                piece = piece.astype(dt)
            out.append(piece)
        return jax.tree_util.tree_unflatten(self.treedef, out)

    def unpack_masters(self, flat32: jax.Array) -> Any:
        """Masters as an fp32 tree (inspection / master_params parity).
        Non-float leaves have no master; they come back as None."""
        if self.zero_axis is not None:
            # the buffer holds only this device's shard: offsets past it
            # would clamp and silently return duplicated tail data
            raise RuntimeError(
                f"masters are ZeRO-sharded over axis {self.zero_axis!r}; "
                f"all_gather the buffer (axis=0, tiled=True) and slice "
                f"[:layout.total] before unpacking")
        out = []
        for i, (shape, f) in enumerate(zip(self.shapes, self.is_float)):
            if not f:
                out.append(None)
                continue
            out.append(jax.lax.dynamic_slice_in_dim(
                flat32, self.offsets[i], self.sizes[i]).reshape(shape))
        return jax.tree_util.tree_unflatten(self.treedef, out)


@jax.tree_util.register_pytree_node_class
class FlatMasters:
    """fp32 master weights as one persistent flat buffer + static layout.
    Being its own pytree node keeps the layout attached to the state (so a
    reused AmpOptimizer or a checkpoint round-trip stays self-describing)
    while jit sees a single array leaf."""

    def __init__(self, buf: jax.Array, layout: _FlatLayout):
        self.buf = buf
        self.layout = layout

    def tree_flatten(self):
        return (self.buf,), self.layout

    @classmethod
    def tree_unflatten(cls, layout, children):
        return cls(children[0], layout)

    def as_tree(self):
        return self.layout.unpack_masters(self.buf)


class AmpOptimizer(Optimizer):
    """Wraps a base Optimizer with loss scaling and optional fp32 masters."""

    def __init__(self, inner: Optimizer, scaler: LossScaler,
                 master_weights: bool, num_losses: int = 1):
        self.inner = inner
        self.scaler = scaler
        self.master_weights = bool(master_weights)
        self.num_losses = int(num_losses)
        # eager/stateful-mode fields (see amp/stateful.py)
        self._bound = None

    # -- functional API ----------------------------------------------------
    def init(self, params: Any, zero_axis: Optional[str] = None
             ) -> AmpOptState:
        """``zero_axis``: ZeRO stage-1 — shard the fp32 masters and the
        inner optimizer's moments across the named DATA-parallel mesh
        axis (each device owns ``ceil(N/dp)`` elements of the flat
        buffer).  Must run inside shard_map with the axis mapped (it
        degrades to the full replicated state outside one); requires an
        elementwise inner optimizer + master weights (the flat path).
        The matching step reduce-scatters the UN-reduced local grads —
        do NOT pre-allreduce them with DDP."""
        if zero_axis is not None and _axis_in_scope(zero_axis):
            if not (self.master_weights
                    and getattr(self.inner, "elementwise", False)):
                raise ValueError(
                    "zero_axis requires master weights and an "
                    "elementwise inner optimizer (the flat-buffer path)")
            layout = _FlatLayout(params)
            layout.zero_axis = zero_axis
            dp = jax.lax.axis_size(zero_axis)
            shard_n = -(-layout.total // dp)          # ceil
            full = jnp.pad(layout.pack(params),
                           (0, shard_n * dp - layout.total))
            idx = jax.lax.axis_index(zero_axis)
            shard = jax.lax.dynamic_slice_in_dim(full, idx * shard_n,
                                                 shard_n)
            masters = FlatMasters(shard, layout)
            inner_state = self.inner.init(shard)
            scalers = tuple(self.scaler.init_state()
                            for _ in range(self.num_losses))
            return AmpOptState(inner=inner_state, masters=masters,
                               scalers=scalers)
        if self.master_weights:
            if getattr(self.inner, "elementwise", False):
                # elementwise inner optimizers (SGD, FusedAdam) run on one
                # persistent flat fp32 buffer: no per-step tree pack/unpack
                layout = _FlatLayout(params)
                masters = FlatMasters(layout.pack(params), layout)
                inner_state = self.inner.init(masters.buf)
            else:
                # optimizers with per-tensor semantics (FusedLAMB trust
                # ratios) keep the master pytree
                masters = _to_fp32(params)
                inner_state = self.inner.init(masters)
        else:
            masters = None
            inner_state = self.inner.init(params)
        scalers = tuple(self.scaler.init_state()
                        for _ in range(self.num_losses))
        return AmpOptState(inner=inner_state, masters=masters,
                           scalers=scalers)

    def loss_scale(self, opt_state: AmpOptState, loss_id: int = 0):
        return opt_state.scalers[loss_id].loss_scale

    def step(self, params: Any = None, opt_state: AmpOptState = None,
             scaled_grads: Any = None, loss_id: int = 0,
             found_inf_extra: Optional[jax.Array] = None,
             found_inf_axes: Optional[Sequence[str]] = None,
             grad_health: Any = None
             ) -> Tuple[Any, AmpOptState, dict]:
        """Unscale grads, update the scaler, apply-or-skip the inner update.

        ``scaled_grads`` are gradients of ``loss * loss_scale`` w.r.t. the
        *model* params.  ``found_inf_extra`` lets callers merge additional
        overflow sources (e.g. a pre-computed grad norm).
        ``found_inf_axes``: mesh axes whose devices hold DISJOINT param
        shards (tensor/pipeline parallel) — the local overflow flag is
        pmax'd over them so every shard skips together and the loss
        scale stays in lockstep.  (A pure data axis doesn't need this:
        the pre-step gradient allreduce propagates inf to every
        replica.)  Axes not currently mapped are ignored, so the same
        step code runs inside and outside shard_map.
        Returns (new_params, new_opt_state, info).

        ``grad_health``: an enabled
        ``observability.numerics.NumericsMonitor`` built over the
        gradient tree — per-layer nonfinite/abs-max/norm/underflow
        stats (pure local jnp math on the pre-pack tree, at the
        scaler's CURRENT loss scale) come back as
        ``info["grad_health"]`` so a skipped step can name the culprit
        layer instead of just counting the skip.  ``None`` (or a
        disabled monitor) computes nothing and leaves the traced graph
        byte-identical — the key is simply absent from ``info``.

        Called with no arguments in eager mode (after amp.stateful.bind +
        scale_loss/backward), it steps the bound state like torch's
        ``optimizer.step()``.
        """
        if params is None:
            if self._bound is None:
                raise RuntimeError("step() without arguments requires a "
                                   "bound optimizer (amp.stateful.bind)")
            return self._bound.step()
        sstate = opt_state.scalers[loss_id]
        health_stats = None
        if grad_health is not None and getattr(grad_health, "enabled",
                                               True):
            # on the tree, BEFORE the flat-buffer pack: per-layer
            # boundaries only exist here, and the stats are what the
            # overflow attribution and underflow accounting read
            health_stats = grad_health.leaf_stats(scaled_grads,
                                                  sstate.loss_scale)
        flat = isinstance(opt_state.masters, FlatMasters)
        zaxis = (opt_state.masters.layout.zero_axis
                 if flat else None)
        zero = zaxis is not None and _axis_in_scope(zaxis)
        if zaxis is not None and not zero:
            # falling through to the plain flat path would apply
            # UN-reduced grads element-misaligned against the
            # device-concat shard buffer — silent corruption when the
            # sizes happen to line up, an opaque shape error when not
            raise RuntimeError(
                f"optimizer state is ZeRO-sharded over axis {zaxis!r} "
                f"but step() was called outside a shard_map mapping it")
        if flat:
            # fused-buffer hot path: one concat, one fused unscale, one
            # optimizer kernel, static slices back out
            scaled_grads = opt_state.masters.layout.pack(scaled_grads)
        if zero:
            # ZeRO-1: reduce-scatter the UN-reduced local grads — each
            # device receives the summed grads for exactly its master
            # shard (the psum+slice DDP would do, in one collective),
            # then averages like gradient_average
            layout = opt_state.masters.layout
            dp = jax.lax.axis_size(zaxis)
            shard_n = opt_state.masters.buf.shape[0]
            scaled_grads = jnp.pad(
                scaled_grads, (0, shard_n * dp - layout.total))
            scaled_grads = jax.lax.psum_scatter(
                scaled_grads, zaxis, scatter_dimension=0, tiled=True)
            scaled_grads = scaled_grads / dp
        grads32, found_inf = self.scaler.unscale(scaled_grads, sstate)
        if found_inf_extra is not None:
            found_inf = jnp.maximum(found_inf, found_inf_extra)
        if zero:
            # each device saw only its grad window: the skip decision
            # must be global or shards diverge
            found_inf = jax.lax.pmax(found_inf, zaxis)
        for ax in (found_inf_axes or ()):
            if _axis_in_scope(ax):
                found_inf = jax.lax.pmax(found_inf, ax)
        new_sstate = self.scaler.update(sstate, found_inf)
        scalers = tuple(new_sstate if i == loss_id else s
                        for i, s in enumerate(opt_state.scalers))

        if zero:
            def do_update(operand):
                p, masters, inner = operand
                layout = masters.layout
                new_buf, new_inner, half = self._flat_inner_step(
                    masters, inner, grads32)
                # params are replicated: gather every shard's update.
                # rebuild reads full32 only for fp32 float leaves — skip
                # that gather (the biggest collective here) when every
                # float leaf has the half dtype
                any_fp32 = any(f and d == "float32" for f, d in
                               zip(layout.is_float, layout.dtypes))
                full32 = (jax.lax.all_gather(
                    new_buf, zaxis, axis=0, tiled=True)[:layout.total]
                    if any_fp32 or half is None else None)
                full_half = (jax.lax.all_gather(
                    half, zaxis, axis=0, tiled=True)[:layout.total]
                    if half is not None else None)
                new_p = layout.rebuild(full32, full_half,
                                       jax.tree_util.tree_leaves(p))
                return new_p, FlatMasters(new_buf, layout), new_inner
        elif flat:
            def do_update(operand):
                p, masters, inner = operand
                new_buf, new_inner, half = self._flat_inner_step(
                    masters, inner, grads32)
                new_p = masters.layout.rebuild(
                    new_buf, half, jax.tree_util.tree_leaves(p))
                return new_p, FlatMasters(new_buf, masters.layout), new_inner
        elif opt_state.masters is not None:
            def do_update(operand):
                p, masters, inner = operand
                new_masters, new_inner = self.inner.update(
                    grads32, inner, masters)
                # master -> model copy (the reference's
                # _master_params_to_model_params, _process_optimizer.py:242-253)
                new_p = _cast_like(new_masters, p)
                return new_p, new_masters, new_inner
        else:
            def do_update(operand):
                p, masters, inner = operand
                new_p, new_inner = self.inner.update(
                    _cast_like(grads32, p), inner, p)
                return new_p, masters, new_inner

        def skip_update(operand):
            return operand

        new_params, new_masters, new_inner = jax.lax.cond(
            found_inf > 0, skip_update, do_update,
            (params, opt_state.masters, opt_state.inner))

        from ..optimizers.base import global_grad_norm
        # grad-norm gauge (observability): the unscaled fp32 grads are
        # already in hand (flat buffer on the fused path), so the norm is
        # one reduction; callers that drop it from the step's outputs get
        # it DCE'd — no cost unless consumed.  Under ZeRO each device
        # holds a disjoint grad window, so the squared sums psum to the
        # global norm (the pad elements are zero).
        if zero:
            grad_norm = jnp.sqrt(jax.lax.psum(
                jnp.sum(jnp.square(grads32)), zaxis))
        else:
            grad_norm = global_grad_norm(grads32)
        info = {"found_inf": found_inf,
                "loss_scale": new_sstate.loss_scale,
                "steps_skipped": new_sstate.steps_skipped,
                "grad_norm": grad_norm}
        if health_stats is not None:
            info["grad_health"] = health_stats
        return new_params, AmpOptState(inner=new_inner, masters=new_masters,
                                       scalers=scalers), info

    def _flat_inner_step(self, masters: FlatMasters, inner_state, flat_g32):
        """Inner update on the flat master buffer.  When the inner
        optimizer can emit the half model copy inside its kernel (FusedAdam
        output_params_dtype, reference fused_adam_cuda_kernel.cu:94-115)
        that saves the separate cast pass; otherwise one astype."""
        half_dtype = masters.layout.half_dtype
        if (half_dtype is not None
                and getattr(self.inner, "supports_output_params_dtype",
                            False)):
            new_buf, new_inner, half = self.inner.step(
                masters.buf, inner_state, flat_g32,
                output_params_dtype=half_dtype)
            return new_buf, new_inner, half
        new_buf, new_inner = self.inner.update(flat_g32, inner_state,
                                               masters.buf)
        half = (new_buf.astype(half_dtype) if half_dtype is not None
                else None)
        return new_buf, new_inner, half

    def masters_tree(self, opt_state: AmpOptState) -> Any:
        """Masters as a params-shaped fp32 tree, whatever the internal
        representation."""
        m = opt_state.masters
        return m.as_tree() if isinstance(m, FlatMasters) else m

    # -- checkpoint (the amp.state_dict gap called out in SURVEY §5) -------
    def state_dict(self, opt_state: AmpOptState) -> dict:
        return {"scalers": [s._asdict() for s in opt_state.scalers]}

    def load_state_dict(self, opt_state: AmpOptState, sd: dict) -> AmpOptState:
        scalers = tuple(ScalerState(**{k: jnp.asarray(v) for k, v in d.items()})
                        for d in sd["scalers"])
        return opt_state._replace(scalers=scalers)

    # -- stateful-mode conveniences (amp/stateful.py fills these in) -------
    @property
    def masters(self):
        if self._bound is None:
            return None
        return self._bound.opt_state.masters
