"""AmpOptimizer: master weights, unscale, overflow-skip — functionally.

The reference performs in-place surgery on torch optimizers
(apex/amp/_process_optimizer.py): clones fp16 params to fp32 masters and
swaps them into param_groups (:13-73), patches ``step`` to copy masters
back to the model (:286-296), and installs pre/post-backward hooks that the
``scale_loss`` context drives (:76-239).  Here the same observable behavior
is a pure wrapper: masters are optimizer *state*, unscale+overflow-check is
the fused multi_tensor_scale, and a skipped step is a ``lax.cond`` that
leaves (params, masters, inner state) untouched — the whole thing lives
inside jit with no host sync.
"""

from __future__ import annotations

import math
from typing import Any, Callable, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .scaler import LossScaler, ScalerState
from ..optimizers.base import Optimizer


def _axis_in_scope(name: str) -> bool:
    """True iff ``name`` is a currently-mapped collective axis — local
    copy of parallel.sync_batchnorm._axis_in_scope (imported inline
    would pull the parallel package into amp's import graph).  Public
    probe: ``lax.axis_index`` raises NameError for an unbound axis;
    pinned by tests/test_syncbn.py::test_axis_scope_probe."""
    try:
        jax.lax.axis_index(name)
        return True
    except NameError:
        return False
    except Exception:
        return True

__all__ = ["AmpOptState", "AmpOptimizer", "FlatMasters",
           "zero_optimizer_specs", "zero_gather_params",
           "zero_gather_checkpoint_policy"]


def _zero_slice_groups(axis_name: str, ici: int):
    """(ici_groups, dcn_groups) of the hierarchical fabric for the
    mapped axis — the same consecutive-block/same-offset split the DDP
    hierarchical allreduce uses (lazy import: the parallel package must
    not enter amp's import graph at module load)."""
    from ..parallel import topology as _topology
    world = jax.lax.axis_size(axis_name)
    return _topology.hierarchical_axis_groups(int(world), int(ici))


def _validate_zero_knobs(zero_stage: int, zero_ici_size, compress: bool):
    if zero_stage not in (1, 2, 3):
        raise ValueError(f"zero_stage must be 1, 2 or 3, got "
                         f"{zero_stage!r}")
    if zero_stage >= 2 and zero_ici_size is None:
        raise ValueError(
            f"ZeRO stage {zero_stage} shards over the ICI slice of the "
            f"hierarchical fabric; pass zero_ici_size= (devices per "
            f"slice)")
    if compress and zero_stage < 2:
        raise ValueError(
            "zero_compress_bf16 compresses the DCN hop of the stage-2/3 "
            "grad reduction; stage 1 shards over the full axis and has "
            "no DCN hop to shrink")


def zero_optimizer_specs(optimizer: "AmpOptimizer", params: Any,
                         axis_name: str = "data",
                         zero_stage: int = 1,
                         zero_ici_size: Optional[int] = None,
                         zero_compress_bf16: bool = False) -> Any:
    """PartitionSpec tree for ``optimizer.init(params, zero_axis=...)``
    run inside shard_map — flat master/moment shards are ``P(axis)``
    (device-concat layout), scalars replicated.  Use as the out_specs of
    the mapped init and the in/out specs of the mapped step::

        ospecs = amp.zero_optimizer_specs(optimizer, params, "data")
        opt_state = jax.jit(jax.shard_map(
            lambda p: optimizer.init(p, zero_axis="data"), mesh=mesh,
            in_specs=(P(),), out_specs=ospecs, check_vma=False))(params)

    The ZeRO knobs must MATCH the ``init`` call exactly: the layout is
    the FlatMasters pytree's aux data, so a spec tree built with
    different knobs is a different treedef and shard_map rejects it.
    For stages 2/3 the buffer is the ICI-slice concat replicated across
    slices, so the global view is still ``P(axis)`` over the mapped
    axis only when every slice holds identical bytes — which the
    stage-2/3 step maintains (DCN-reduced shards are bitwise equal);
    the spec stays ``P(axis)`` for the world-concat layout of stage 1
    and ``P()`` is wrong for all stages (the buffer is never
    replicated per device).  Stage 2/3 specs remain ``P(axis)``: jax
    materializes the device-concat global, slices repeat across DCN.
    """
    from jax.sharding import PartitionSpec as P
    if not (optimizer.master_weights
            and getattr(optimizer.inner, "elementwise", False)):
        # same precondition init enforces — fail at the first API call
        # instead of inside a jitted trace later
        raise ValueError(
            "zero_axis requires master weights and an elementwise inner "
            "optimizer (the flat-buffer path)")
    _validate_zero_knobs(zero_stage, zero_ici_size, zero_compress_bf16)
    layout = _FlatLayout(params)
    layout.zero_axis = axis_name
    layout.zero_stage = int(zero_stage)
    layout.zero_ici = (int(zero_ici_size) if zero_ici_size is not None
                       else None)
    layout.zero_compress = bool(zero_compress_bf16)

    def leaf_spec(l):
        return P() if getattr(l, "ndim", 0) == 0 else P(axis_name)

    inner_abs = jax.eval_shape(
        optimizer.inner.init,
        jax.ShapeDtypeStruct((max(layout.total, 1),), jnp.float32))
    inner_specs = jax.tree_util.tree_map(leaf_spec, inner_abs)
    scaler_abs = jax.eval_shape(optimizer.scaler.init_state)
    scaler_specs = tuple(
        jax.tree_util.tree_map(lambda _: P(), scaler_abs)
        for _ in range(optimizer.num_losses))
    return AmpOptState(inner=inner_specs,
                       masters=FlatMasters(P(axis_name), layout),
                       scalers=scaler_specs)


# checkpoint_name tag on the ZeRO-3 gathered flat parameter buffer —
# the policy below rematerializes exactly this value in the backward
ZERO3_GATHER_NAME = "zero3_gathered_params"


# the gather -> rebuild chain of zero_gather_params, by primitive: the
# remat policy must mark EVERY eqn on it unsaveable, because partial
# eval cuts the replay at the first saveable ancestor — a name tag on
# the leaves alone is useless when the producing slice/reshape/convert
# outputs are unnamed saveable aliases one eqn upstream
_ZERO3_REPLAY_PRIMS = frozenset(
    ("all_gather", "slice", "dynamic_slice", "reshape",
     "convert_element_type", "custom_vjp_call", "custom_vjp_call_jaxpr"))


def zero_gather_checkpoint_policy():
    """Rematerialization policy for a ZeRO-3 forward: save every
    residual EXCEPT the just-in-time gathered parameters, which the
    backward re-gathers from the master shard (one extra in-slice
    all_gather on the wire — the ZeRO-3 trade: the full fp32 model
    never stays live across the backward).  Activations stay saved;
    only the gather/rebuild chain (and any other pure data-movement
    slice/reshape/cast the model does) is recomputed.  Use as
    ``jax.checkpoint(loss_fn, policy=zero_gather_checkpoint_policy())``
    around a loss that calls :func:`zero_gather_params`."""
    from jax._src.ad_checkpoint import name_p

    def policy(prim, *_, **params):
        if prim is name_p:
            return params["name"] != ZERO3_GATHER_NAME
        return prim.name not in _ZERO3_REPLAY_PRIMS
    return policy


def _zero3_gather_tables(layout: "_FlatLayout", ici: int):
    """Static index tables for the ZeRO-3 mixed-dtype gather.

    The wire-heavy gather runs at the model's half dtype (the values
    the forward needs are ``half(master)`` anyway), but leaves that
    stay fp32 (BN affine under O2) must arrive bit-exact — a bf16
    round-trip would diverge from the replicated-param stages.  Those
    "exact" elements are scattered through the flat buffer and the
    shard cut does not align with leaf boundaries, so each device
    contributes its local exact elements through a per-device index
    row (padded to the max count ``M`` so the all_gather stays
    uniform).  Returns ``(idx [ici, max(M,1)] int32 local-shard
    indices, rebuild [n32] int32 indices into the gathered
    [ici*max(M,1)] aux buffer, n32, M)`` — all plain numpy, computed
    identically by :func:`zero_gather_params` and the comm plan so
    graph and plan cannot desync on the aux payload."""
    import numpy as np
    padded = -(-layout.total // ici) * ici
    shard = padded // ici
    half = (str(layout.half_dtype) if layout.half_dtype is not None
            else None)
    pos = []
    for dt, f, off, n in zip(layout.dtypes, layout.is_float,
                             layout.offsets, layout.sizes):
        if f and dt != half:
            pos.extend(range(off, off + n))
    per = [[p - d * shard for p in pos if d * shard <= p < (d + 1) * shard]
           for d in range(ici)]
    m_max = max((len(p) for p in per), default=0)
    idx = np.zeros((ici, max(m_max, 1)), np.int32)
    rebuild = np.zeros(len(pos), np.int32)
    k = 0
    for d, p in enumerate(per):
        idx[d, :len(p)] = p
        # offsets ascend, so concatenating the per-device partitions in
        # device order walks the exact elements in layout order
        for slot in range(len(p)):
            rebuild[k] = d * max(m_max, 1) + slot
            k += 1
    return idx, rebuild, len(pos), m_max


def zero_gather_params(masters: "FlatMasters", axis_name: Optional[str]
                       = None) -> Any:
    """ZeRO-3 just-in-time parameter materialization: all_gather the
    master shard within its ICI slice, slice off the layout pad, and
    rebuild the params tree at the model dtypes.

    The gather runs at the model's HALF dtype when the layout has one
    (O2): the forward only ever consumes ``half(master)``, so casting
    the shard before the collective halves both the wire bytes and the
    gathered buffer that XLA must hold live — the fp32 full model never
    exists.  Leaves that stay fp32 (BN affine) ride a second tiny
    all_gather of the exact elements (see :func:`_zero3_gather_tables`)
    so their values match the replicated-param stages bit for bit.
    All-fp32 layouts (no half dtype) fall back to one fp32 gather.

    The backward is a hand-written VJP, not the autodiff transpose:
    transposing 60+ per-leaf ``slice``/``reshape``/``cast`` chains
    pads every leaf cotangent back to the FULL flat length and
    ``add_any``s the padded buffers — XLA materializes several
    whole-model fp32 temporaries.  The custom rule packs the leaf
    cotangents with ONE concatenate (each element belongs to exactly
    one leaf, so the values are bitwise those of the transpose) and
    feeds the in-slice ``psum_scatter`` — which is exactly the flat
    grad shard ``AmpOptimizer.step`` expects: call this at the top of
    the loss function, differentiate w.r.t. ``masters`` (a pytree
    whose only leaf is the shard), and pass the cotangent straight in
    as ``scaled_grads``.

    The gathered values are tagged ``checkpoint_name(...,
    ZERO3_GATHER_NAME)``: wrap the loss function in
    ``jax.checkpoint(f, policy=zero_gather_checkpoint_policy())`` and
    the full parameter set is NOT a residual — the backward RE-GATHERS
    the slice params just in time (everything else — activations —
    stays saved) instead of holding ``total`` fp32 elements live
    across the whole backward."""
    from jax.ad_checkpoint import checkpoint_name
    layout = masters.layout
    if layout.zero_axis is None or layout.zero_stage != 3:
        raise RuntimeError(
            "zero_gather_params requires a ZeRO-3 layout (init with "
            "zero_stage=3); stages 1/2 gather inside the step itself")
    axis = axis_name if axis_name is not None else layout.zero_axis
    ici_groups, _ = _zero_slice_groups(axis, layout.zero_ici)
    padded = -(-layout.total // layout.zero_ici) * layout.zero_ici
    half = layout.half_dtype
    if half is not None:
        idx_np, rebuild_np, n32, _ = _zero3_gather_tables(
            layout, layout.zero_ici)
        # concrete device constants (constvars in the jaxpr) — a plain
        # numpy capture would stage per-dispatch device_put transfers
        with jax.ensure_compile_time_eval():
            idx_t = jnp.asarray(idx_np)
            rebuild_t = jnp.asarray(rebuild_np)

    @jax.custom_vjp
    def gather(buf):
        # the tag lands on every value derived from the gather that
        # the backward would otherwise keep as a residual: the flat
        # gathered buffer AND the reshaped/cast leaves (conv
        # dgrad/wgrad read the leaves, not the buffer)
        if half is None:
            full = jax.lax.all_gather(
                buf, axis, axis=0, tiled=True,
                axis_index_groups=ici_groups)[:layout.total]
            full = checkpoint_name(full, ZERO3_GATHER_NAME)
            leaves = []
            for shape, dt, off, n in zip(layout.shapes, layout.dtypes,
                                         layout.offsets, layout.sizes):
                piece = jax.lax.slice_in_dim(full, off, off + n)
                piece = piece.reshape(shape)
                if str(piece.dtype) != dt:
                    piece = piece.astype(jnp.dtype(dt))
                leaves.append(checkpoint_name(piece, ZERO3_GATHER_NAME))
            return tuple(leaves)
        fullh = jax.lax.all_gather(
            buf.astype(half), axis, axis=0, tiled=True,
            axis_index_groups=ici_groups)[:layout.total]
        fullh = checkpoint_name(fullh, ZERO3_GATHER_NAME)
        exact = None
        if n32:
            row = jnp.take(idx_t,
                           jax.lax.axis_index(axis) % layout.zero_ici,
                           axis=0)
            aux = jnp.take(buf, row)
            g32 = jax.lax.all_gather(aux, axis, axis=0, tiled=True,
                                     axis_index_groups=ici_groups)
            exact = jnp.take(g32, rebuild_t)
        leaves, ex_off = [], 0
        for shape, dt, f, off, n in zip(layout.shapes, layout.dtypes,
                                        layout.is_float, layout.offsets,
                                        layout.sizes):
            if f and dt == str(half):
                piece = jax.lax.slice_in_dim(fullh, off, off + n)
                piece = piece.reshape(shape)
            else:
                piece = jax.lax.slice_in_dim(exact, ex_off, ex_off + n)
                ex_off += n
                piece = piece.reshape(shape).astype(jnp.dtype(dt))
            leaves.append(checkpoint_name(piece, ZERO3_GATHER_NAME))
        return tuple(leaves)

    def gather_fwd(buf):
        return gather(buf), None

    def gather_bwd(_, cts):
        # commit each cotangent to its leaf dtype before widening: XLA's
        # excess-precision pass would otherwise elide the f16 round-trip
        # (cotangent -> f16 -> f32) and hand the optimizer higher-precision
        # grads than the replicated-param (ZeRO-1/2) path sees, breaking
        # bitwise master parity across stages
        cts = jax.lax.optimization_barrier(cts)
        flat = jnp.concatenate(
            [ct.astype(jnp.float32).reshape(-1) for ct in cts])
        if padded != layout.total:
            flat = jnp.pad(flat, (0, padded - layout.total))
        shard = jax.lax.psum_scatter(
            flat, axis, scatter_dimension=0, tiled=True,
            axis_index_groups=ici_groups)
        return (shard,)

    gather.defvjp(gather_fwd, gather_bwd)
    return jax.tree_util.tree_unflatten(layout.treedef,
                                        list(gather(masters.buf)))


class AmpOptState(NamedTuple):
    inner: Any                     # wrapped optimizer's state
    masters: Any                   # FlatMasters | fp32 master pytree | None
    scalers: Tuple[ScalerState, ...]  # one per loss (num_losses)


def _to_fp32(tree):
    return jax.tree_util.tree_map(
        lambda p: p.astype(jnp.float32) if jnp.issubdtype(
            jnp.result_type(p), jnp.floating) else p, tree)


def _cast_like(tree, like):
    return jax.tree_util.tree_map(
        lambda x, l: x.astype(l.dtype) if jnp.issubdtype(
            jnp.result_type(l), jnp.floating) else x, tree, like)


class _FlatLayout:
    """Static description of a float-leaf flattening, computed once at
    ``AmpOptimizer.init``.  The reference flattens each param group once at
    construction (apex/optimizers/fp16_optimizer.py:57-70); round-1 apex_tpu
    instead re-packed the whole tree every step
    (round-2 VERDICT weak-item 2) — this layout makes pack/unpack a single
    concat / static-slice set that XLA folds into neighbouring ops."""

    def __init__(self, params):
        leaves, self.treedef = jax.tree_util.tree_flatten(params)
        self.shapes = tuple(tuple(l.shape) for l in leaves)
        self.dtypes = tuple(str(jnp.result_type(l)) for l in leaves)
        self.is_float = tuple(
            jnp.issubdtype(jnp.result_type(l), jnp.floating) for l in leaves)
        sizes, offsets, off = [], [], 0
        for shape, f in zip(self.shapes, self.is_float):
            n = int(math.prod(shape)) if f else 0
            sizes.append(n)
            offsets.append(off)
            off += n
        self.sizes = tuple(sizes)
        self.offsets = tuple(offsets)
        self.total = off
        halves = {d for d, f in zip(self.dtypes, self.is_float)
                  if f and d != "float32"}
        # the single non-fp32 float dtype (O2's cast_model_type), if any —
        # lets the fused Adam kernel emit the half model copy in-pass
        self.half_dtype = (jnp.dtype(halves.pop()) if len(halves) == 1
                           else None)

    # ZeRO: when zero_axis is set, the flat master/moment buffers hold
    # only THIS device's slice (sharded over the named data axis); the
    # step reduce-scatters grads and all-gathers the updated params.
    #   stage 1 — shard over the FULL axis (world-concat layout)
    #   stage 2 — shard over the ICI slice of the hierarchical fabric
    #             (zero_ici devices); state replicated across slices,
    #             grads DCN-reduced on the 1/ici shard, params
    #             re-gathered within the slice only
    #   stage 3 — like 2, but params are NEVER gathered back by the
    #             step: the fp32 master shard IS the parameter store
    #             and the forward regathers just-in-time
    #             (zero_gather_params)
    zero_axis: Optional[str] = None
    zero_stage: int = 1
    zero_ici: Optional[int] = None
    zero_compress: bool = False       # bf16 DCN hop on the grad reduce

    # layouts are jit-cache keys via FlatMasters aux_data
    def _key(self):
        return (self.treedef, self.shapes, self.dtypes, self.zero_axis,
                self.zero_stage, self.zero_ici, self.zero_compress)

    def __eq__(self, other):
        return isinstance(other, _FlatLayout) and self._key() == other._key()

    def __hash__(self):
        return hash(self._key())

    def pack(self, tree) -> jax.Array:
        """Float leaves → one flat fp32 buffer (single concat)."""
        leaves = jax.tree_util.tree_leaves(tree)
        parts = [l.reshape(-1).astype(jnp.float32)
                 for l, f in zip(leaves, self.is_float) if f]
        if not parts:
            return jnp.zeros((0,), jnp.float32)
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

    def rebuild(self, flat32: jax.Array, half: Optional[jax.Array],
                like_leaves) -> Any:
        """Params tree from the updated flat fp32 buffer (+ optional half
        copy emitted by the kernel).  Non-float leaves pass through from
        ``like_leaves``; fp32 leaves slice from ``flat32``; half leaves
        slice from ``half`` when present (no extra cast pass)."""
        out = []
        for i, (shape, f) in enumerate(zip(self.shapes, self.is_float)):
            if not f:
                out.append(like_leaves[i])
                continue
            dt = jnp.dtype(self.dtypes[i])
            src = half if (half is not None and dt == half.dtype) else flat32
            piece = jax.lax.dynamic_slice_in_dim(
                src, self.offsets[i], self.sizes[i]).reshape(shape)
            if piece.dtype != dt:
                piece = piece.astype(dt)
            out.append(piece)
        return jax.tree_util.tree_unflatten(self.treedef, out)

    def unpack_masters(self, flat32: jax.Array) -> Any:
        """Masters as an fp32 tree (inspection / master_params parity).
        Non-float leaves have no master; they come back as None."""
        if self.zero_axis is not None:
            # the buffer holds only this device's shard: offsets past it
            # would clamp and silently return duplicated tail data
            raise RuntimeError(
                f"masters are ZeRO-sharded over axis {self.zero_axis!r}; "
                f"all_gather the buffer (axis=0, tiled=True) and slice "
                f"[:layout.total] before unpacking")
        out = []
        for i, (shape, f) in enumerate(zip(self.shapes, self.is_float)):
            if not f:
                out.append(None)
                continue
            out.append(jax.lax.dynamic_slice_in_dim(
                flat32, self.offsets[i], self.sizes[i]).reshape(shape))
        return jax.tree_util.tree_unflatten(self.treedef, out)


@jax.tree_util.register_pytree_node_class
class FlatMasters:
    """fp32 master weights as one persistent flat buffer + static layout.
    Being its own pytree node keeps the layout attached to the state (so a
    reused AmpOptimizer or a checkpoint round-trip stays self-describing)
    while jit sees a single array leaf."""

    def __init__(self, buf: jax.Array, layout: _FlatLayout):
        self.buf = buf
        self.layout = layout

    def tree_flatten(self):
        return (self.buf,), self.layout

    @classmethod
    def tree_unflatten(cls, layout, children):
        return cls(children[0], layout)

    def as_tree(self):
        return self.layout.unpack_masters(self.buf)


class AmpOptimizer(Optimizer):
    """Wraps a base Optimizer with loss scaling and optional fp32 masters."""

    def __init__(self, inner: Optimizer, scaler: LossScaler,
                 master_weights: bool, num_losses: int = 1):
        self.inner = inner
        self.scaler = scaler
        self.master_weights = bool(master_weights)
        self.num_losses = int(num_losses)
        # eager/stateful-mode fields (see amp/stateful.py)
        self._bound = None

    # -- functional API ----------------------------------------------------
    def init(self, params: Any, zero_axis: Optional[str] = None,
             zero_stage: int = 1, zero_ici_size: Optional[int] = None,
             zero_compress_bf16: bool = False) -> AmpOptState:
        """``zero_axis``: ZeRO — shard the fp32 masters and the inner
        optimizer's moments across the named DATA-parallel mesh axis.
        ``zero_stage`` picks how far the sharding goes:

        * 1 (default) — shard over the FULL axis: each device owns
          ``ceil(N/world)`` elements; the step reduce-scatters the
          un-reduced grads and all-gathers the updated params.
        * 2 — shard over the ICI slice (``zero_ici_size`` devices) of
          the hierarchical fabric: state is replicated across slices,
          grads are psum_scatter'd within the slice then DCN-reduced on
          the 1/ici shard, and the updated params are gathered back
          within the slice only (the DCN never carries params).
        * 3 — like 2 for grads, but the step never gathers params
          back: the fp32 master shard IS the parameter store, the
          forward regathers just-in-time via :func:`zero_gather_params`
          and the step receives the flat 1-D grad shard its transpose
          produces.  Requires every param leaf to be floating point.

        ``zero_compress_bf16`` (stages 2/3) quantizes only the DCN hop
        of the grad reduction to bf16 — same contract as DDP's
        ``allreduce_compress_bf16`` (fp32 accumulate, half wire).

        Must run inside shard_map with the axis mapped (it degrades to
        the full replicated state outside one); requires an elementwise
        inner optimizer + master weights (the flat path).  The matching
        step reduces the grads itself — do NOT pre-allreduce them with
        DDP."""
        if zero_axis is not None and _axis_in_scope(zero_axis):
            if not (self.master_weights
                    and getattr(self.inner, "elementwise", False)):
                raise ValueError(
                    "zero_axis requires master weights and an "
                    "elementwise inner optimizer (the flat-buffer path)")
            _validate_zero_knobs(zero_stage, zero_ici_size,
                                 zero_compress_bf16)
            layout = _FlatLayout(params)
            layout.zero_axis = zero_axis
            layout.zero_stage = int(zero_stage)
            layout.zero_ici = (int(zero_ici_size)
                               if zero_ici_size is not None else None)
            layout.zero_compress = bool(zero_compress_bf16)
            if zero_stage == 3 and not all(layout.is_float):
                raise ValueError(
                    "ZeRO-3 rebuilds every param from the flat fp32 "
                    "master shard; non-float leaves have no master "
                    "storage to regather from")
            dp = jax.lax.axis_size(zero_axis)
            if zero_stage >= 2:
                # validates world % ici == 0 (static) and pins the
                # slice geometry the step will reuse
                _zero_slice_groups(zero_axis, layout.zero_ici)
                shard_count = layout.zero_ici
                idx = jax.lax.axis_index(zero_axis) % shard_count
            else:
                shard_count = dp
                idx = jax.lax.axis_index(zero_axis)
            shard_n = -(-layout.total // shard_count)          # ceil
            full = jnp.pad(layout.pack(params),
                           (0, shard_n * shard_count - layout.total))
            shard = jax.lax.dynamic_slice_in_dim(full, idx * shard_n,
                                                 shard_n)
            masters = FlatMasters(shard, layout)
            inner_state = self.inner.init(shard)
            scalers = tuple(self.scaler.init_state()
                            for _ in range(self.num_losses))
            return AmpOptState(inner=inner_state, masters=masters,
                               scalers=scalers)
        if self.master_weights:
            if getattr(self.inner, "elementwise", False):
                # elementwise inner optimizers (SGD, FusedAdam) run on one
                # persistent flat fp32 buffer: no per-step tree pack/unpack
                layout = _FlatLayout(params)
                masters = FlatMasters(layout.pack(params), layout)
                inner_state = self.inner.init(masters.buf)
            else:
                # optimizers with per-tensor semantics (FusedLAMB trust
                # ratios) keep the master pytree
                masters = _to_fp32(params)
                inner_state = self.inner.init(masters)
        else:
            masters = None
            inner_state = self.inner.init(params)
        scalers = tuple(self.scaler.init_state()
                        for _ in range(self.num_losses))
        return AmpOptState(inner=inner_state, masters=masters,
                           scalers=scalers)

    def loss_scale(self, opt_state: AmpOptState, loss_id: int = 0):
        return opt_state.scalers[loss_id].loss_scale

    def step(self, params: Any = None, opt_state: AmpOptState = None,
             scaled_grads: Any = None, loss_id: int = 0,
             found_inf_extra: Optional[jax.Array] = None,
             found_inf_axes: Optional[Sequence[str]] = None,
             grad_health: Any = None
             ) -> Tuple[Any, AmpOptState, dict]:
        """Unscale grads, update the scaler, apply-or-skip the inner update.

        ``scaled_grads`` are gradients of ``loss * loss_scale`` w.r.t. the
        *model* params.  ``found_inf_extra`` lets callers merge additional
        overflow sources (e.g. a pre-computed grad norm).
        ``found_inf_axes``: mesh axes whose devices hold DISJOINT param
        shards (tensor/pipeline parallel) — the local overflow flag is
        pmax'd over them so every shard skips together and the loss
        scale stays in lockstep.  (A pure data axis doesn't need this:
        the pre-step gradient allreduce propagates inf to every
        replica.)  Axes not currently mapped are ignored, so the same
        step code runs inside and outside shard_map.
        Returns (new_params, new_opt_state, info).

        ``grad_health``: an enabled
        ``observability.numerics.NumericsMonitor`` built over the
        gradient tree — per-layer nonfinite/abs-max/norm/underflow
        stats (pure local jnp math on the pre-pack tree, at the
        scaler's CURRENT loss scale) come back as
        ``info["grad_health"]`` so a skipped step can name the culprit
        layer instead of just counting the skip.  ``None`` (or a
        disabled monitor) computes nothing and leaves the traced graph
        byte-identical — the key is simply absent from ``info``.

        Called with no arguments in eager mode (after amp.stateful.bind +
        scale_loss/backward), it steps the bound state like torch's
        ``optimizer.step()``.
        """
        if params is None:
            if self._bound is None:
                raise RuntimeError("step() without arguments requires a "
                                   "bound optimizer (amp.stateful.bind)")
            return self._bound.step()
        sstate = opt_state.scalers[loss_id]
        health_stats = None
        if grad_health is not None and getattr(grad_health, "enabled",
                                               True):
            # on the tree, BEFORE the flat-buffer pack: per-layer
            # boundaries only exist here, and the stats are what the
            # overflow attribution and underflow accounting read
            health_stats = grad_health.leaf_stats(scaled_grads,
                                                  sstate.loss_scale)
        flat = isinstance(opt_state.masters, FlatMasters)
        zaxis = (opt_state.masters.layout.zero_axis
                 if flat else None)
        zero = zaxis is not None and _axis_in_scope(zaxis)
        if zaxis is not None and not zero:
            # falling through to the plain flat path would apply
            # UN-reduced grads element-misaligned against the
            # device-concat shard buffer — silent corruption when the
            # sizes happen to line up, an opaque shape error when not
            raise RuntimeError(
                f"optimizer state is ZeRO-sharded over axis {zaxis!r} "
                f"but step() was called outside a shard_map mapping it")
        zstage = (opt_state.masters.layout.zero_stage if zero else 1)
        zero_groups = (_zero_slice_groups(
            zaxis, opt_state.masters.layout.zero_ici)
            if zero and zstage >= 2 else None)
        if zstage == 3 and zero:
            # the gather transpose hands back the flat in-slice-summed
            # grad SHARD (possibly still wrapped in the FlatMasters
            # pytree scaled_grad differentiated through)
            if isinstance(scaled_grads, FlatMasters):
                scaled_grads = scaled_grads.buf
            if (getattr(scaled_grads, "ndim", None) != 1
                    or scaled_grads.shape
                    != opt_state.masters.buf.shape):
                raise ValueError(
                    f"ZeRO-3 step expects the flat grad shard the "
                    f"zero_gather_params transpose produces "
                    f"(shape {opt_state.masters.buf.shape}), got "
                    f"{getattr(scaled_grads, 'shape', type(scaled_grads))}")
        elif flat:
            # fused-buffer hot path: one concat, one fused unscale, one
            # optimizer kernel, static slices back out
            scaled_grads = opt_state.masters.layout.pack(scaled_grads)
        if zero:
            layout = opt_state.masters.layout
            dp = jax.lax.axis_size(zaxis)
            shard_n = opt_state.masters.buf.shape[0]
            if zstage >= 2:
                # ZeRO-2/3: two-level reduce mirroring the DDP
                # hierarchical path — psum_scatter within the ICI slice
                # lands the 1/ici shard, the DCN hop reduces only that
                # shard (optionally as a bf16 all_gather + fp32 local
                # sum), and unlike DDP there is no gather-back: the
                # shard is exactly what the local optimizer state needs
                ici_groups, dcn_groups = zero_groups
                if zstage == 2:
                    scaled_grads = jnp.pad(
                        scaled_grads,
                        (0, shard_n * layout.zero_ici - layout.total))
                    scaled_grads = jax.lax.psum_scatter(
                        scaled_grads, zaxis, scatter_dimension=0,
                        axis_index_groups=ici_groups, tiled=True)
                # stage 3 grads arrive already in-slice summed (the
                # all_gather transpose is exactly that psum_scatter)
                if layout.zero_compress:
                    q = scaled_grads.astype(jnp.bfloat16)
                    wire = jax.lax.all_gather(
                        q, zaxis, axis_index_groups=dcn_groups)
                    scaled_grads = jnp.sum(
                        wire.astype(jnp.float32), axis=0)
                else:
                    scaled_grads = jax.lax.psum(
                        scaled_grads, zaxis,
                        axis_index_groups=dcn_groups)
            else:
                # ZeRO-1: reduce-scatter the UN-reduced local grads —
                # each device receives the summed grads for exactly its
                # master shard (the psum+slice DDP would do, in one
                # collective), then averages like gradient_average
                scaled_grads = jnp.pad(
                    scaled_grads, (0, shard_n * dp - layout.total))
                scaled_grads = jax.lax.psum_scatter(
                    scaled_grads, zaxis, scatter_dimension=0, tiled=True)
            scaled_grads = scaled_grads / dp
        grads32, found_inf = self.scaler.unscale(scaled_grads, sstate)
        if found_inf_extra is not None:
            found_inf = jnp.maximum(found_inf, found_inf_extra)
        if zero:
            # each device saw only its grad window: the skip decision
            # must be global or shards diverge
            found_inf = jax.lax.pmax(found_inf, zaxis)
        for ax in (found_inf_axes or ()):
            if _axis_in_scope(ax):
                found_inf = jax.lax.pmax(found_inf, ax)
        new_sstate = self.scaler.update(sstate, found_inf)
        scalers = tuple(new_sstate if i == loss_id else s
                        for i, s in enumerate(opt_state.scalers))

        if zero and zstage == 3:
            def do_update(operand):
                p, masters, inner = operand
                # the master shard IS the parameter store: update it in
                # place, no half copy, no gather-back — the next
                # forward's zero_gather_params reads the new shard
                new_buf, new_inner = self.inner.update(
                    grads32, inner, masters.buf)
                return p, FlatMasters(new_buf, masters.layout), new_inner
        elif zero:
            gather_groups = zero_groups[0] if zstage == 2 else None

            def do_update(operand):
                p, masters, inner = operand
                layout = masters.layout
                new_buf, new_inner, half = self._flat_inner_step(
                    masters, inner, grads32)
                # params are replicated: gather every shard's update
                # (stage 2: within the ICI slice only — cross-slice
                # shards are bitwise equal after the DCN grad reduce).
                # rebuild reads full32 only for fp32 float leaves — skip
                # that gather (the biggest collective here) when every
                # float leaf has the half dtype
                any_fp32 = any(f and d == "float32" for f, d in
                               zip(layout.is_float, layout.dtypes))
                full32 = (jax.lax.all_gather(
                    new_buf, zaxis, axis=0, tiled=True,
                    axis_index_groups=gather_groups)[:layout.total]
                    if any_fp32 or half is None else None)
                full_half = (jax.lax.all_gather(
                    half, zaxis, axis=0, tiled=True,
                    axis_index_groups=gather_groups)[:layout.total]
                    if half is not None else None)
                new_p = layout.rebuild(full32, full_half,
                                       jax.tree_util.tree_leaves(p))
                return new_p, FlatMasters(new_buf, layout), new_inner
        elif flat:
            def do_update(operand):
                p, masters, inner = operand
                new_buf, new_inner, half = self._flat_inner_step(
                    masters, inner, grads32)
                new_p = masters.layout.rebuild(
                    new_buf, half, jax.tree_util.tree_leaves(p))
                return new_p, FlatMasters(new_buf, masters.layout), new_inner
        elif opt_state.masters is not None:
            def do_update(operand):
                p, masters, inner = operand
                new_masters, new_inner = self.inner.update(
                    grads32, inner, masters)
                # master -> model copy (the reference's
                # _master_params_to_model_params, _process_optimizer.py:242-253)
                new_p = _cast_like(new_masters, p)
                return new_p, new_masters, new_inner
        else:
            def do_update(operand):
                p, masters, inner = operand
                new_p, new_inner = self.inner.update(
                    _cast_like(grads32, p), inner, p)
                return new_p, masters, new_inner

        def skip_update(operand):
            return operand

        new_params, new_masters, new_inner = jax.lax.cond(
            found_inf > 0, skip_update, do_update,
            (params, opt_state.masters, opt_state.inner))

        from ..optimizers.base import global_grad_norm
        # grad-norm gauge (observability): the unscaled fp32 grads are
        # already in hand (flat buffer on the fused path), so the norm is
        # one reduction; callers that drop it from the step's outputs get
        # it DCE'd — no cost unless consumed.  Under ZeRO each device
        # holds a disjoint grad window, so the squared sums psum to the
        # global norm (the pad elements are zero).
        if zero and zstage >= 2:
            # windows are disjoint within the slice but REPLICATED
            # across slices (post-DCN grads are identical): a full-axis
            # psum would overcount by dcn_size
            grad_norm = jnp.sqrt(jax.lax.psum(
                jnp.sum(jnp.square(grads32)), zaxis,
                axis_index_groups=zero_groups[0]))
        elif zero:
            grad_norm = jnp.sqrt(jax.lax.psum(
                jnp.sum(jnp.square(grads32)), zaxis))
        else:
            grad_norm = global_grad_norm(grads32)
        info = {"found_inf": found_inf,
                "loss_scale": new_sstate.loss_scale,
                "steps_skipped": new_sstate.steps_skipped,
                "grad_norm": grad_norm}
        if health_stats is not None:
            info["grad_health"] = health_stats
        return new_params, AmpOptState(inner=new_inner, masters=new_masters,
                                       scalers=scalers), info

    def _flat_inner_step(self, masters: FlatMasters, inner_state, flat_g32):
        """Inner update on the flat master buffer.  When the inner
        optimizer can emit the half model copy inside its kernel (FusedAdam
        output_params_dtype, reference fused_adam_cuda_kernel.cu:94-115)
        that saves the separate cast pass; otherwise one astype."""
        half_dtype = masters.layout.half_dtype
        if (half_dtype is not None
                and getattr(self.inner, "supports_output_params_dtype",
                            False)):
            new_buf, new_inner, half = self.inner.step(
                masters.buf, inner_state, flat_g32,
                output_params_dtype=half_dtype)
            return new_buf, new_inner, half
        new_buf, new_inner = self.inner.update(flat_g32, inner_state,
                                               masters.buf)
        half = (new_buf.astype(half_dtype) if half_dtype is not None
                else None)
        return new_buf, new_inner, half

    def masters_tree(self, opt_state: AmpOptState) -> Any:
        """Masters as a params-shaped fp32 tree, whatever the internal
        representation."""
        m = opt_state.masters
        return m.as_tree() if isinstance(m, FlatMasters) else m

    # -- checkpoint (the amp.state_dict gap called out in SURVEY §5) -------
    def state_dict(self, opt_state: AmpOptState) -> dict:
        return {"scalers": [s._asdict() for s in opt_state.scalers]}

    def load_state_dict(self, opt_state: AmpOptState, sd: dict) -> AmpOptState:
        scalers = tuple(ScalerState(**{k: jnp.asarray(v) for k, v in d.items()})
                        for d in sd["scalers"])
        return opt_state._replace(scalers=scalers)

    # -- stateful-mode conveniences (amp/stateful.py fills these in) -------
    @property
    def masters(self):
        if self._bound is None:
            return None
        return self._bound.opt_state.masters
