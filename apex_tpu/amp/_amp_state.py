"""Cross-module amp singleton + rank-aware printing.

Mirrors apex/amp/_amp_state.py:17-52: a module-level state object holding
the active opt properties and verbosity, and ``maybe_print`` that only
prints on rank 0 (here: ``jax.process_index() == 0``) unless
``allow_incoherent_verbosity`` is set.
"""

from __future__ import annotations

import jax


class AmpState:
    def __init__(self):
        self.hard_override = False
        self.allow_incoherent_verbosity = False
        self.verbosity = 1
        self.opt_properties = None
        self.handle = None


_amp_state = AmpState()


def master_params(optimizer):
    """Generator over the fp32 master params of an amp-wrapped optimizer
    (reference: _amp_state.py:61-70). Accepts the stateful AmpOptimizer."""
    masters = getattr(optimizer, "masters", None)
    if masters is None:
        raise AttributeError(
            "master_params requires an optimizer returned by amp.initialize")
    from ._process_optimizer import FlatMasters
    if isinstance(masters, FlatMasters):
        masters = masters.as_tree()   # per-tensor views of the flat buffer
    yield from jax.tree_util.tree_leaves(masters)


def maybe_print(msg: str, rank0_only: bool = True) -> None:
    if _amp_state.verbosity > 0:
        try:
            rank = jax.process_index()
        except Exception:
            rank = 0
        if (not rank0_only) or _amp_state.allow_incoherent_verbosity or rank == 0:
            print(msg)


def warn_or_err(msg: str) -> None:
    if _amp_state.hard_override:
        maybe_print("Warning: " + msg)
    else:
        raise RuntimeError(msg + "\nIf you're sure you know what you're "
                           "doing, supply hard_override=True to amp.initialize.")
