"""amp frontend: the opt-level system and ``amp.initialize``.

Faithful to the reference's shape (apex/amp/frontend.py): a ``Properties``
option struct with per-key validation in ``__setattr__`` (:50-96), O0-O3
preset objects (:101-190), an ``opt_levels`` registry (:187-190), and an
``initialize()`` that applies the preset then user overrides (:194-357).

TPU extension: ``half_dtype`` selects bfloat16 (TPU-native; default) or
float16 (bitwise parity with the reference's semantics, incl. dynamic loss
scaling).  Under bfloat16 the presets default loss_scale to 1.0 because
bf16 shares fp32's exponent range and cannot overflow where fp16 does.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from ._amp_state import _amp_state, maybe_print, warn_or_err

__all__ = ["Properties", "O0", "O1", "O2", "O3", "opt_levels", "initialize",
           "compute_dtype", "scaler_state", "current_loss_scale",
           "steps_skipped", "amp_stats", "record_scaler"]

_HALF_DTYPES = {"float16": jnp.float16, "bfloat16": jnp.bfloat16,
                "fp16": jnp.float16, "bf16": jnp.bfloat16}


class Properties:
    """Options struct with validation; mirrors frontend.py:6-96."""

    def __init__(self):
        self.options = {
            "enabled": False,
            "opt_level": None,
            "cast_model_type": None,
            "patch_torch_functions": False,
            "keep_batchnorm_fp32": None,
            "master_weights": None,
            "loss_scale": 1.0,
            "cast_model_outputs": None,
            "num_losses": 1,
            "verbosity": 1,
            "min_loss_scale": None,
            "max_loss_scale": 2. ** 24,
            "half_dtype": "bfloat16",
        }

    def _update_options_dict(self, new_options: dict):
        for k, v in new_options.items():
            if k in self.options:
                setattr(self, k, v)
            else:
                raise ValueError(f"Tried to set unexpected option {k}")

    def __getattr__(self, name: str):
        if "options" in self.__dict__ and name in self.options:
            return self.options[name]
        raise AttributeError(name)

    def __setattr__(self, name: str, value: Any):
        if "options" not in self.__dict__:
            super().__setattr__(name, value)
            return
        if name not in self.options:
            super().__setattr__(name, value)
            return
        # string forms accepted for argparse interop (frontend.py:74-92)
        if name == "cast_model_type":
            if self.opt_level == "O1" and value is not None:
                if value is not False and value != jnp.float32:
                    warn_or_err("O1 inserts casts around ops, so the model "
                                "should not be cast. cast_model_type was "
                                f"{value}")
            self.options[name] = _coerce_dtype(value)
        elif name == "cast_model_outputs":
            self.options[name] = _coerce_dtype(value)
        elif name in ("patch_torch_functions", "keep_batchnorm_fp32",
                      "master_weights"):
            self.options[name] = _coerce_bool(name, value)
        elif name == "loss_scale":
            if value == "dynamic":
                self.options[name] = "dynamic"
            elif value is None:
                self.options[name] = None
            else:
                self.options[name] = float(value)
        elif name == "half_dtype":
            if isinstance(value, str):
                if value not in _HALF_DTYPES:
                    raise ValueError(f"half_dtype must be one of "
                                     f"{sorted(_HALF_DTYPES)}, got {value}")
                self.options[name] = "float16" if _HALF_DTYPES[value] == \
                    jnp.float16 else "bfloat16"
            else:
                dt = jnp.dtype(value)
                if dt not in (jnp.dtype(jnp.float16), jnp.dtype(jnp.bfloat16)):
                    raise ValueError(f"half_dtype must be fp16/bf16, got {dt}")
                self.options[name] = dt.name
        else:
            self.options[name] = value

    @property
    def half_jnp_dtype(self):
        return _HALF_DTYPES[self.options["half_dtype"]]

    def __repr__(self):
        return "\n".join(f"{k:24}: {v}" for k, v in self.options.items())


def _coerce_dtype(value):
    if value is None or value is False:
        return None if value is None else False
    if isinstance(value, str):
        table = {"torch.float16": jnp.float16, "torch.float32": jnp.float32,
                 "float16": jnp.float16, "float32": jnp.float32,
                 "bfloat16": jnp.bfloat16, "fp16": jnp.float16,
                 "fp32": jnp.float32, "bf16": jnp.bfloat16, "half": "half"}
        if value in table:
            return table[value]
        raise ValueError(f"Unknown dtype string {value!r}")
    return jnp.dtype(value).type if value is not None else None


def _coerce_bool(name, value):
    if isinstance(value, str):
        if value == "True":
            return True
        if value == "False":
            return False
        raise ValueError(f"{name} must be True/False/None, got {value!r}")
    return value


class OptLevel:
    brief = ""
    more = ""

    def __call__(self, properties: Properties) -> Properties:
        raise NotImplementedError


class O3(OptLevel):
    """Pure half precision — 'speed of light' ceiling (frontend.py:101-116)."""
    brief = "O3: Pure half precision (the 'speed of light' ceiling)."

    def __call__(self, properties):
        properties.enabled = True
        properties.opt_level = "O3"
        properties.cast_model_type = "half"
        properties.patch_torch_functions = False
        properties.keep_batchnorm_fp32 = False
        properties.master_weights = False
        properties.loss_scale = 1.0
        return properties


class O2(OptLevel):
    """Half model + fp32 masters + fp32 batchnorm (frontend.py:118-143)."""
    brief = "O2: half-precision model with fp32 master weights and batchnorm."

    def __call__(self, properties):
        properties.enabled = True
        properties.opt_level = "O2"
        properties.cast_model_type = "half"
        properties.patch_torch_functions = False
        properties.keep_batchnorm_fp32 = True
        properties.master_weights = True
        # bf16 can't overflow where fp16 does; dynamic scaling only for fp16
        properties.loss_scale = "dynamic"
        return properties


class O1(OptLevel):
    """Op-classification cast insertion (frontend.py:145-163)."""
    brief = "O1: insert casts at op boundaries per whitelist/blacklist."

    def __call__(self, properties):
        properties.enabled = True
        properties.opt_level = "O1"
        properties.cast_model_type = None
        properties.patch_torch_functions = True
        properties.keep_batchnorm_fp32 = None
        properties.master_weights = None
        properties.loss_scale = "dynamic"
        return properties


class O0(OptLevel):
    """Pure fp32 baseline (frontend.py:165-185)."""
    brief = "O0: pure fp32 (accuracy baseline)."

    def __call__(self, properties):
        properties.enabled = True
        properties.opt_level = "O0"
        properties.cast_model_type = jnp.float32
        properties.patch_torch_functions = False
        properties.keep_batchnorm_fp32 = None
        properties.master_weights = False
        properties.loss_scale = 1.0
        return properties


opt_levels = {"O3": O3(), "O2": O2(), "O1": O1(), "O0": O0()}


def compute_dtype(opt_level: str, half_dtype: str = "bfloat16"):
    """The dtype the O-level policy puts on MXU operands (conv/matmul
    lhs+rhs, fwd and bwd): fp32 at O0, the half dtype at O1 (op-boundary
    casts whitelist conv/matmul), O2, and O3.  This is the single source
    of truth ``apex_tpu.analysis``'s amp-dtype rule checks traced train
    steps against — fp32 accumulation lives in
    ``preferred_element_type``, never in operand upcasts."""
    if opt_level not in opt_levels:
        raise ValueError(f"unknown opt_level {opt_level!r}")
    if opt_level == "O0":
        return jnp.float32
    return _HALF_DTYPES[half_dtype]


def initialize(model, optimizers=None, enabled: bool = True,
               opt_level: str = "O1", cast_model_type=None,
               patch_torch_functions=None, keep_batchnorm_fp32=None,
               master_weights=None, loss_scale=None,
               cast_model_outputs=None, num_losses: int = 1,
               verbosity: int = 1, min_loss_scale=None,
               max_loss_scale=2. ** 24, half_dtype=None,
               hard_override: bool = False):
    """3-line amp enablement — same shape as apex (frontend.py:194-357).

    ``model`` is an apex_tpu.nn.Module (or an (init, apply) pair wrapped in
    one); ``optimizers`` an apex_tpu Optimizer or list of them.  Returns
    ``(AmpModel, AmpOptimizer)`` (lists preserved as given).
    """
    from ._initialize import _initialize

    _amp_state.hard_override = hard_override
    _amp_state.verbosity = verbosity

    if not enabled:
        from ._initialize import AmpModel, AmpOptimizer
        props = Properties()
        props.options["half_dtype"] = "bfloat16" if half_dtype is None else half_dtype
        return _initialize(model, optimizers, props, disabled=True)

    if opt_level not in opt_levels:
        raise RuntimeError(
            f"Unexpected optimization level {opt_level}. Options are 'O0', "
            "'O1', 'O2', 'O3'. Note that in `O0`, `O1`, etc., the prefix O "
            "is the letter O, not the number zero.")

    props = Properties()
    if half_dtype is not None:
        props.half_dtype = half_dtype
    props = opt_levels[opt_level](props)
    maybe_print(f"Selected optimization level {opt_level}: "
                f"{opt_levels[opt_level].brief}", True)
    maybe_print("Defaults for this optimization level are:", True)
    for k, v in props.options.items():
        maybe_print(f"{k:24}: {v}", True)

    overrides = dict(cast_model_type=cast_model_type,
                     patch_torch_functions=patch_torch_functions,
                     keep_batchnorm_fp32=keep_batchnorm_fp32,
                     master_weights=master_weights, loss_scale=loss_scale,
                     cast_model_outputs=cast_model_outputs,
                     num_losses=num_losses, min_loss_scale=min_loss_scale,
                     max_loss_scale=max_loss_scale)
    maybe_print("Processing user overrides (additional kwargs that are not "
                "None)...", True)
    for k, v in overrides.items():
        if v is not None:
            setattr(props, k, v)
    # resolve 'half' placeholder to the configured half dtype
    if props.options["cast_model_type"] == "half":
        props.options["cast_model_type"] = props.half_jnp_dtype
    if props.options["cast_model_outputs"] == "half":
        props.options["cast_model_outputs"] = props.half_jnp_dtype
    # bf16 never needs dynamic scaling unless the user insists: it shares
    # fp32's exponent range, so the overflow the scaler guards against
    # cannot occur.  Applies to any preset that defaulted to "dynamic".
    if (loss_scale is None and props.options["loss_scale"] == "dynamic"
            and props.half_jnp_dtype == jnp.bfloat16):
        props.options["loss_scale"] = 1.0
    maybe_print("After processing overrides, optimization options are:", True)
    for k, v in props.options.items():
        maybe_print(f"{k:24}: {v}", True)

    _amp_state.opt_properties = props
    return _initialize(model, optimizers, props)


# -- scaler introspection (the reference's amp_state surface) -------------
#
# The scaler's counters (steps_skipped, current loss scale) are plain
# device scalars inside AmpOptState — users should not have to dig into
# ScalerState tuples.  These accessors accept any of: an AmpOptState, a
# stateful BoundOptimizer (amp.stateful.bind), or an amp-initialized
# AmpOptimizer that has been bound.  Each call is one explicit host
# fetch — never call them inside the jitted step.

def _resolve_opt_state(opt):
    from ._process_optimizer import AmpOptState
    if isinstance(opt, AmpOptState):
        return opt
    # stateful forms: BoundOptimizer, or AmpOptimizer with ._bound
    state = getattr(opt, "opt_state", None)
    if isinstance(state, AmpOptState):
        return state
    bound = getattr(opt, "_bound", None)
    if bound is not None and isinstance(
            getattr(bound, "opt_state", None), AmpOptState):
        return bound.opt_state
    raise TypeError(
        f"expected an AmpOptState, a bound optimizer, or an "
        f"amp-initialized optimizer with bound state; got {type(opt)!r}")


def scaler_state(opt, loss_id: int = 0):
    """The raw :class:`ScalerState` for ``loss_id`` (device arrays)."""
    return _resolve_opt_state(opt).scalers[loss_id]


def current_loss_scale(opt, loss_id: int = 0) -> float:
    """Current loss scale as a python float (one host fetch)."""
    return float(scaler_state(opt, loss_id).loss_scale)


def steps_skipped(opt, loss_id: int = 0) -> int:
    """Total overflow-skipped steps as a python int (one host fetch)."""
    return int(scaler_state(opt, loss_id).steps_skipped)


def amp_stats(opt) -> dict:
    """All-scaler snapshot: per-loss loss scale / clean-step streak /
    skip totals, in one host fetch of the scaler tuple."""
    import jax
    scalers = jax.device_get(_resolve_opt_state(opt).scalers)
    per_loss = [{"loss_scale": float(s.loss_scale),
                 "unskipped": int(s.unskipped),
                 "steps_skipped": int(s.steps_skipped)} for s in scalers]
    return {"num_losses": len(per_loss),
            "loss_scale": per_loss[0]["loss_scale"],
            "steps_skipped": sum(p["steps_skipped"] for p in per_loss),
            "per_loss": per_loss}


def record_scaler(opt, registry=None, step: Optional[int] = None,
                  emit_event: bool = False, prefix: str = "amp_",
                  numerics: Optional[dict] = None,
                  supervisor=None) -> dict:
    """Fold the scaler snapshot into an observability registry: gauge
    ``amp_loss_scale``, counter ``amp_steps_skipped_total``.  With
    ``emit_event=True`` also appends a loss-scale timeline point to the
    default span recorder's JSONL event log (tag it with ``step`` to
    reconstruct the timeline offline).

    ``numerics``: a flushed ``observability.numerics.NumericsMonitor``
    summary (``nm.flush(tele)``) for the SAME optimizer's gradients —
    a detected skip's flight-ring event then carries the culprit
    bucket/layer (``culprit`` / ``culprit_nonfinite``), not just the
    skip count (overflow attribution, PR 9).

    ``supervisor``: a running
    :class:`~apex_tpu.observability.RunSupervisor` — the scaler
    snapshot lands on its ``/statusz`` page (``observe_scaler``) next
    to the run verdict, the amp-side supervisor signal tap (the
    gradient-health side rides ``observe_step(numerics=...)``).

    One optimizer per (registry, ``prefix``): the gauge/counter are
    plain totals, so two optimizers recorded through the same pair
    would overwrite each other (and the counter-delta skip detection
    below would see phantom transitions) — give each its own
    ``prefix=`` or registry."""
    from ..observability import get_registry, event, flightrec
    stats = amp_stats(opt)
    reg = registry if registry is not None else get_registry()
    reg.gauge(prefix + "loss_scale").set(stats["loss_scale"])
    skip_counter = reg.counter(prefix + "steps_skipped_total")
    prev_skips = skip_counter.value
    skip_counter.set_total(stats["steps_skipped"])
    # prefix identifies the optimizer in shared sinks (the docstring's
    # one-optimizer-per-(registry, prefix) rule) — without it a ring
    # dump with two optimizers couldn't say WHICH one overflowed
    ev = {"loss_scale": stats["loss_scale"],
          "steps_skipped": stats["steps_skipped"],
          "prefix": prefix}
    if numerics is not None and numerics.get("culprit") is not None:
        ev["culprit"] = numerics["culprit"]
        ev["culprit_nonfinite"] = numerics.get("culprit_nonfinite")
    if step is not None:
        ev["step"] = int(step)
    if stats["steps_skipped"] > prev_skips:
        # flight-recorder trail: a scaler skip is a rare, diagnostic
        # transition (overflow → step dropped, scale halved) — exactly
        # what a post-mortem ring dump should show next to any
        # failover/breaker events of the same window.  Dedup is the
        # per-registry counter delta: recording the same optimizer
        # against a FRESH registry re-reports its cumulative total
        # once (a truthful, spurious-timed event) — accepted, because
        # any process-global gate on the ring's last totals would
        # silently SUPPRESS a second optimizer's first skips, and a
        # post-mortem missing real transitions is worse than one
        # carrying a duplicate.
        flightrec.record("scaler_skip", **ev)
    if emit_event:
        event("amp_loss_scale", **ev)
    if supervisor is not None:
        supervisor.observe_scaler(stats)
    return stats
