"""scale_loss and gradient helpers.

The reference's ``with amp.scale_loss(loss, optimizer)`` (apex/amp/
handle.py:15-157) scales the loss on entry, and on exit unscales grads,
checks overflow, and patches ``optimizer.step`` into a one-shot skip.
JAX has no autograd tape, so apex_tpu offers the same protocol in two
forms:

1. **Functional (the jit/performance path)** — :func:`scaled_grad` computes
   grads of ``loss * loss_scale``; ``AmpOptimizer.step`` unscales, updates
   the scale, and `lax.cond`-skips — all device-resident.

2. **Eager (API-parity path)** — ``with amp.scale_loss(loss_fn, optimizer)
   as scaled_loss: scaled_loss.backward()`` against a *bound* stateful
   optimizer (see amp.stateful.bind), matching the reference's call shape
   for scripts and tests.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from . import policy as _policy
from ._amp_state import _amp_state, maybe_print
from ._process_optimizer import AmpOptimizer, AmpOptState

__all__ = ["scale_loss", "scaled_grad", "scaled_grad_accum",
           "disable_casts"]

disable_casts = _policy.disable_casts


def scaled_grad(loss_fn: Callable, params: Any, opt_state: AmpOptState,
                *args, loss_id: int = 0, has_aux: bool = False, **kwargs):
    """value_and_grad of ``loss * loss_scale``.

    Returns ``(loss, scaled_grads)`` or ``(loss, aux, scaled_grads)``; pass
    ``scaled_grads`` straight to ``AmpOptimizer.step`` which unscales them.
    The *unscaled* loss is returned for logging, like the reference yields
    the scaled loss only for backward (handle.py:117).
    """
    scale = opt_state.scalers[loss_id].loss_scale

    def scaled_fn(p):
        res = loss_fn(p, *args, **kwargs)
        if has_aux:
            loss, aux = res
            return loss.astype(jnp.float32) * scale, aux
        return res.astype(jnp.float32) * scale

    if has_aux:
        (scaled_loss, aux), grads = jax.value_and_grad(
            scaled_fn, has_aux=True)(params)
        return scaled_loss / scale, aux, grads
    scaled_loss, grads = jax.value_and_grad(scaled_fn)(params)
    return scaled_loss / scale, grads


def scaled_grad_accum(loss_fn: Callable, params: Any,
                      opt_state: AmpOptState, batches: Any,
                      loss_id: int = 0, average: bool = True):
    """Gradient accumulation inside jit: K micro-batch backward passes,
    ONE optimizer step.

    ``loss_fn(params, microbatch) -> loss``; ``batches`` is a pytree
    whose leaves carry a leading K axis.  Runs a ``lax.scan`` over the
    micro-batches summing the SCALED gradients (peak memory = one
    micro-batch's activations + one grad tree), and returns
    ``(mean_loss, scaled_grads)`` to pass straight to
    ``AmpOptimizer.step`` — the single unscale there preserves the
    reference's accumulation semantics (``delay_unscale=True`` across
    backwards, ``unscale_with_stashed`` once at step time,
    handle.py:117-137).  ``average=True`` divides by K so the update
    matches one big batch of the concatenated micro-batches (mean-loss
    convention); ``False`` leaves the raw sum.
    """
    scale = opt_state.scalers[loss_id].loss_scale
    K = jax.tree_util.tree_leaves(batches)[0].shape[0]

    def one(p, mb):
        return jax.value_and_grad(
            lambda pp: loss_fn(pp, mb).astype(jnp.float32) * scale)(p)

    def body(carry, mb):
        loss_sum, acc = carry
        scaled_loss, g = one(params, mb)
        # fp32 accumulator: summing K half-precision grad trees would
        # lose a few ulps per add (the reference stashes fp32 too)
        acc = jax.tree_util.tree_map(
            lambda a, gg: a + gg.astype(a.dtype), acc, g)
        return (loss_sum + scaled_loss, acc), None

    # value_and_grad rejects non-float params, so every leaf gets a
    # grad and the fp32 accumulator is always the right dtype
    zeros = jax.tree_util.tree_map(
        lambda l: jnp.zeros(l.shape, jnp.float32), params)
    (loss_sum, grads), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), zeros), batches)
    if average:
        grads = jax.tree_util.tree_map(lambda g: g / K, grads)
        return loss_sum / scale / K, grads
    # sum convention: loss and grads agree (the caller's objective is
    # the SUM of micro-batch losses)
    return loss_sum / scale, grads


class _ScaledLoss:
    """What the eager ``scale_loss`` yields: float()-able, backward()-able."""

    def __init__(self, bound, loss_fn: Callable, loss_id: int):
        self._bound = bound
        self._loss_fn = loss_fn
        self._loss_id = loss_id
        self.value: Optional[jax.Array] = None

    def backward(self) -> None:
        self._bound._backward(self._loss_fn, self._loss_id)

    def __float__(self) -> float:
        if self.value is None:
            self.value = self._bound._eval_scaled_loss(
                self._loss_fn, self._loss_id)
        return float(self.value)

    def item(self) -> float:
        return float(self)


@contextlib.contextmanager
def scale_loss(loss: Any, optimizer: AmpOptimizer, loss_id: int = 0,
               model=None, delay_unscale: bool = False,
               delay_overflow_check: bool = False):
    """Eager-mode context manager with the reference's shape
    (handle.py:15-157).

    ``loss`` is a callable ``loss_fn(params) -> scalar`` (JAX is tape-free,
    so the loss must be re-expressible as a function of params); the
    optimizer must have been bound to params via
    ``amp.stateful.bind(optimizer, params)`` or be the optimizer half of a
    bound pair.  On exit, gradients stashed by ``scaled_loss.backward()``
    are unscaled, the scale is updated, and an overflowed step will be
    skipped by the next ``optimizer.step()`` — announcing the scale change
    like the reference (handle.py:142-144).
    """
    if isinstance(optimizer, (list, tuple)):
        raise NotImplementedError(
            "pass a single optimizer per scale_loss context")
    bound = optimizer._bound
    if bound is None:
        raise RuntimeError(
            "Eager scale_loss needs a bound optimizer: call "
            "apex_tpu.amp.stateful.bind(optimizer, params) first, or use "
            "the functional path (amp.scaled_grad + optimizer.step).")
    if not callable(loss):
        raise TypeError(
            "In apex_tpu, amp.scale_loss takes a callable loss_fn(params) "
            "(JAX has no autograd tape to replay a computed loss).")
    sl = _ScaledLoss(bound, loss, loss_id)
    yield sl
    bound._post_backward(loss_id,
                         delay_unscale=delay_unscale,
                         delay_overflow_check=delay_overflow_check)
