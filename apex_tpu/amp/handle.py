"""scale_loss and gradient helpers.

The reference's ``with amp.scale_loss(loss, optimizer)`` (apex/amp/
handle.py:15-157) scales the loss on entry, and on exit unscales grads,
checks overflow, and patches ``optimizer.step`` into a one-shot skip.
JAX has no autograd tape, so apex_tpu offers the same protocol in two
forms:

1. **Functional (the jit/performance path)** — :func:`scaled_grad` computes
   grads of ``loss * loss_scale``; ``AmpOptimizer.step`` unscales, updates
   the scale, and `lax.cond`-skips — all device-resident.

2. **Eager (API-parity path)** — ``with amp.scale_loss(loss_fn, optimizer)
   as scaled_loss: scaled_loss.backward()`` against a *bound* stateful
   optimizer (see amp.stateful.bind), matching the reference's call shape
   for scripts and tests.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from . import policy as _policy
from ._amp_state import _amp_state, maybe_print
from ._process_optimizer import AmpOptimizer, AmpOptState

__all__ = ["scale_loss", "scaled_grad", "disable_casts"]

disable_casts = _policy.disable_casts


def scaled_grad(loss_fn: Callable, params: Any, opt_state: AmpOptState,
                *args, loss_id: int = 0, has_aux: bool = False, **kwargs):
    """value_and_grad of ``loss * loss_scale``.

    Returns ``(loss, scaled_grads)`` or ``(loss, aux, scaled_grads)``; pass
    ``scaled_grads`` straight to ``AmpOptimizer.step`` which unscales them.
    The *unscaled* loss is returned for logging, like the reference yields
    the scaled loss only for backward (handle.py:117).
    """
    scale = opt_state.scalers[loss_id].loss_scale

    def scaled_fn(p):
        res = loss_fn(p, *args, **kwargs)
        if has_aux:
            loss, aux = res
            return loss.astype(jnp.float32) * scale, aux
        return res.astype(jnp.float32) * scale

    if has_aux:
        (scaled_loss, aux), grads = jax.value_and_grad(
            scaled_fn, has_aux=True)(params)
        return scaled_loss / scale, aux, grads
    scaled_loss, grads = jax.value_and_grad(scaled_fn)(params)
    return scaled_loss / scale, grads


class _ScaledLoss:
    """What the eager ``scale_loss`` yields: float()-able, backward()-able."""

    def __init__(self, bound, loss_fn: Callable, loss_id: int):
        self._bound = bound
        self._loss_fn = loss_fn
        self._loss_id = loss_id
        self.value: Optional[jax.Array] = None

    def backward(self) -> None:
        self._bound._backward(self._loss_fn, self._loss_id)

    def __float__(self) -> float:
        if self.value is None:
            self.value = self._bound._eval_scaled_loss(
                self._loss_fn, self._loss_id)
        return float(self.value)

    def item(self) -> float:
        return float(self)


@contextlib.contextmanager
def scale_loss(loss: Any, optimizer: AmpOptimizer, loss_id: int = 0,
               model=None, delay_unscale: bool = False,
               delay_overflow_check: bool = False):
    """Eager-mode context manager with the reference's shape
    (handle.py:15-157).

    ``loss`` is a callable ``loss_fn(params) -> scalar`` (JAX is tape-free,
    so the loss must be re-expressible as a function of params); the
    optimizer must have been bound to params via
    ``amp.stateful.bind(optimizer, params)`` or be the optimizer half of a
    bound pair.  On exit, gradients stashed by ``scaled_loss.backward()``
    are unscaled, the scale is updated, and an overflowed step will be
    skipped by the next ``optimizer.step()`` — announcing the scale change
    like the reference (handle.py:142-144).
    """
    if isinstance(optimizer, (list, tuple)):
        raise NotImplementedError(
            "pass a single optimizer per scale_loss context")
    bound = optimizer._bound
    if bound is None:
        raise RuntimeError(
            "Eager scale_loss needs a bound optimizer: call "
            "apex_tpu.amp.stateful.bind(optimizer, params) first, or use "
            "the functional path (amp.scaled_grad + optimizer.step).")
    if not callable(loss):
        raise TypeError(
            "In apex_tpu, amp.scale_loss takes a callable loss_fn(params) "
            "(JAX has no autograd tape to replay a computed loss).")
    sl = _ScaledLoss(bound, loss, loss_id)
    yield sl
    bound._post_backward(loss_id,
                         delay_unscale=delay_unscale,
                         delay_overflow_check=delay_overflow_check)
