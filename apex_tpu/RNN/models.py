"""RNN factories (reference: apex/RNN/models.py:19-52)."""

from __future__ import annotations

from typing import Optional

from .cells import CELLS
from .RNNBackend import RNNCell, bidirectionalRNN, stackedRNN

__all__ = ["LSTM", "GRU", "ReLU", "Tanh", "mLSTM"]


def _toRNNBackend(cell: str, input_size: int, hidden_size: int,
                  num_layers: int = 1, bias: bool = True,
                  batch_first: bool = False, dropout: float = 0.0,
                  bidirectional: bool = False,
                  output_size: Optional[int] = None):
    if batch_first:
        raise NotImplementedError(
            "batch_first is not supported (reference models.py:10-16); "
            "inputs are seq-major (T, B, F)")
    fn, gate_multiplier, n_states = CELLS[cell]
    proto = RNNCell(gate_multiplier, input_size, hidden_size, cell,
                    n_states, bias, output_size)
    if bidirectional:
        return bidirectionalRNN(proto, num_layers, dropout)
    return stackedRNN(proto, num_layers, dropout)


def LSTM(input_size, hidden_size, num_layers=1, bias=True, batch_first=False,
         dropout=0.0, bidirectional=False, output_size=None):
    return _toRNNBackend("LSTM", input_size, hidden_size, num_layers, bias,
                         batch_first, dropout, bidirectional, output_size)


def GRU(input_size, hidden_size, num_layers=1, bias=True, batch_first=False,
        dropout=0.0, bidirectional=False, output_size=None):
    return _toRNNBackend("GRU", input_size, hidden_size, num_layers, bias,
                         batch_first, dropout, bidirectional, output_size)


def ReLU(input_size, hidden_size, num_layers=1, bias=True, batch_first=False,
         dropout=0.0, bidirectional=False, output_size=None):
    return _toRNNBackend("ReLU", input_size, hidden_size, num_layers, bias,
                         batch_first, dropout, bidirectional, output_size)


def Tanh(input_size, hidden_size, num_layers=1, bias=True, batch_first=False,
         dropout=0.0, bidirectional=False, output_size=None):
    return _toRNNBackend("Tanh", input_size, hidden_size, num_layers, bias,
                         batch_first, dropout, bidirectional, output_size)


def mLSTM(input_size, hidden_size, num_layers=1, bias=True, batch_first=False,
          dropout=0.0, bidirectional=False, output_size=None):
    return _toRNNBackend("mLSTM", input_size, hidden_size, num_layers, bias,
                         batch_first, dropout, bidirectional, output_size)
