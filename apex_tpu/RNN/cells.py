"""Cell step functions (reference: apex/RNN/cells.py).

Each cell is a pure function ``cell(params, hidden, x) -> (new_hidden, out)``
operating on one time step; gate matmuls route through the policy-aware
F.linear so amp O1 casts them like the reference's RNN interception
(apex/amp/wrap.py:226-335).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..nn import functional as F


def lstm_cell(params, hidden, x):
    """Standard LSTM; gate order (i, f, g, o) like torch."""
    h, c = hidden
    gates = F.linear(x, params["w_ih"], params.get("b_ih")) + \
        F.linear(h, params["w_hh"], params.get("b_hh"))
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c32 = f * c.astype(g.dtype) + i * g
    h_new = o * jnp.tanh(c32)
    return (h_new, c32.astype(c.dtype)), h_new


def gru_cell(params, hidden, x):
    (h,) = hidden
    gi = F.linear(x, params["w_ih"], params.get("b_ih"))
    gh = F.linear(h, params["w_hh"], params.get("b_hh"))
    ir, iz, in_ = jnp.split(gi, 3, axis=-1)
    hr, hz, hn = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(ir + hr)
    z = jax.nn.sigmoid(iz + hz)
    n = jnp.tanh(in_ + r * hn)
    h_new = (1 - z) * n + z * h
    return (h_new,), h_new


def relu_cell(params, hidden, x):
    (h,) = hidden
    h_new = F.relu(F.linear(x, params["w_ih"], params.get("b_ih")) +
                   F.linear(h, params["w_hh"], params.get("b_hh")))
    return (h_new,), h_new


def tanh_cell(params, hidden, x):
    (h,) = hidden
    h_new = jnp.tanh(F.linear(x, params["w_ih"], params.get("b_ih")) +
                     F.linear(h, params["w_hh"], params.get("b_hh")))
    return (h_new,), h_new


def mlstm_cell(params, hidden, x):
    """Multiplicative LSTM (Krause et al.; reference cells.py:55-83):
    m = (W_mx x) * (W_mh h), then LSTM gates driven by (x, m)."""
    h, c = hidden
    m = F.linear(x, params["w_mx"]) * F.linear(h, params["w_mh"])
    gates = F.linear(x, params["w_ih"], params.get("b_ih")) + \
        F.linear(m, params["w_hh"], params.get("b_hh"))
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c_new = f * c.astype(g.dtype) + i * g
    h_new = o * jnp.tanh(c_new)
    return (h_new, c_new.astype(c.dtype)), h_new


CELLS = {
    "LSTM": (lstm_cell, 4, 2),      # (fn, gate_multiplier, n_hidden_states)
    "GRU": (gru_cell, 3, 1),
    "ReLU": (relu_cell, 1, 1),
    "Tanh": (tanh_cell, 1, 1),
    "mLSTM": (mlstm_cell, 4, 2),
}
