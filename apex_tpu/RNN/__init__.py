"""apex_tpu.RNN — recurrent stack (reference: apex/RNN).

The reference is a pure-Python unrolled loop over time steps
(RNNBackend.py:122-195) built on deprecated torch internals.  The TPU-native
form is ``lax.scan`` over the time axis — one compiled loop body, weights
resident in VMEM across steps — with the same factory surface
(apex/RNN/models.py:19-52): LSTM, GRU, ReLU, Tanh, mLSTM.
"""

from .models import LSTM, GRU, ReLU, Tanh, mLSTM
from .RNNBackend import RNNCell, stackedRNN, bidirectionalRNN
from . import cells
