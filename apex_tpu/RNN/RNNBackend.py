"""RNN stack machinery (reference: apex/RNN/RNNBackend.py).

``RNNCell`` holds per-layer weights (gate_multiplier × hidden gates, like
RNNBackend.py:232-365 incl. the optional output projection);
``stackedRNN`` runs layers sequentially with each layer a single
``lax.scan`` over time (the reference's Python loop, :122-195, compiled);
``bidirectionalRNN`` (:25-86) runs forward/reverse scans and concatenates.

Inputs are seq-major (T, B, F) like the reference.  Hidden state is
returned functionally instead of stored on the module
(detach/reset_hidden become no-ops handled by the caller).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .cells import CELLS
from ..nn.module import Module, ModuleList

__all__ = ["RNNCell", "stackedRNN", "bidirectionalRNN"]


class RNNCell(Module):
    """One recurrent layer's weights + step function."""

    def __init__(self, gate_multiplier: int, input_size: int,
                 hidden_size: int, cell: str, n_hidden_states: int = 2,
                 bias: bool = True, output_size: Optional[int] = None):
        super().__init__()
        self.gate_multiplier = gate_multiplier
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.cell = cell
        self.n_hidden_states = n_hidden_states
        self.bias = bias
        self.output_size = output_size if output_size is not None else \
            hidden_size

    def create_params(self, key):
        ks = jax.random.split(key, 7)
        gh = self.gate_multiplier * self.hidden_size
        bound = 1.0 / math.sqrt(self.hidden_size)
        u = lambda k, shape: jax.random.uniform(
            k, shape, jnp.float32, -bound, bound)
        p = {"w_ih": u(ks[0], (gh, self.input_size)),
             "w_hh": u(ks[1], (gh, self.output_size))}
        if self.bias:
            p["b_ih"] = u(ks[2], (gh,))
            p["b_hh"] = u(ks[3], (gh,))
        if self.cell == "mLSTM":
            p["w_mx"] = u(ks[4], (self.output_size, self.input_size))
            p["w_mh"] = u(ks[5], (self.output_size, self.output_size))
        if self.output_size != self.hidden_size:
            # optional output projection (reference RNNBackend.py:318-328:
            # hidden[0] is projected to output_size and fed back recurrently).
            # GRU's update gate mixes h elementwise with gate-space tensors,
            # so a projected recurrent state is ill-defined there.
            if self.cell == "GRU":
                raise NotImplementedError(
                    "output_size projection is not defined for GRU")
            p["w_ho"] = u(ks[6], (self.output_size, self.hidden_size))
        return p

    def init_hidden(self, batch: int, dtype=jnp.float32):
        # hidden[0] (the recurrent output) is output_size; deeper states
        # (e.g. the LSTM cell state) stay hidden_size
        shapes = [(batch, self.output_size)] + \
            [(batch, self.hidden_size)] * (self.n_hidden_states - 1)
        return tuple(jnp.zeros(s, dtype) for s in shapes)

    def forward(self, params, x, hidden=None):
        """x: (T, B, F). Returns (out (T, B, H), final_hidden)."""
        from ..nn import functional as F
        fn = CELLS[self.cell][0]
        if hidden is None:
            hidden = self.init_hidden(x.shape[1], x.dtype)

        def step(h, xt):
            new_h, out = fn(params, h, xt)
            if "w_ho" in params:
                out = F.linear(out, params["w_ho"])
                new_h = (out,) + tuple(new_h[1:])
            return new_h, out

        final, outs = lax.scan(step, hidden, x)
        return outs, final


class stackedRNN(Module):
    """Sequential layer stack with optional inter-layer dropout
    (reference :122-195)."""

    def __init__(self, inputRNN: RNNCell, num_layers: int = 1,
                 dropout: float = 0.0):
        super().__init__()
        self.num_layers = num_layers
        self.dropout = dropout
        cells = [inputRNN]
        for _ in range(num_layers - 1):
            cells.append(RNNCell(inputRNN.gate_multiplier,
                                 inputRNN.output_size, inputRNN.hidden_size,
                                 inputRNN.cell, inputRNN.n_hidden_states,
                                 inputRNN.bias, inputRNN.output_size))
        self.rnns = ModuleList(cells)

    def forward(self, params, x, hidden=None):
        from ..nn.module import current_context
        from ..nn import functional as F
        ctx = current_context()
        hiddens = []
        for i, cell in enumerate(self.rnns):
            h_in = hidden[i] if hidden is not None else None
            x, h_out = cell(params["rnns"][str(i)], x, h_in)
            hiddens.append(h_out)
            if (self.dropout and i < self.num_layers - 1 and ctx is not None
                    and ctx.train):
                x = F.dropout(x, self.dropout, ctx.make_rng())
        return x, hiddens


class bidirectionalRNN(Module):
    """Forward + reversed-scan layer with feature concat (reference :25-86)."""

    def __init__(self, inputRNN: RNNCell, num_layers: int = 1,
                 dropout: float = 0.0):
        super().__init__()
        self.fwd = stackedRNN(inputRNN, num_layers, dropout)
        bwd_proto = RNNCell(inputRNN.gate_multiplier, inputRNN.input_size,
                            inputRNN.hidden_size, inputRNN.cell,
                            inputRNN.n_hidden_states, inputRNN.bias,
                            inputRNN.output_size)
        self.bwd = stackedRNN(bwd_proto, num_layers, dropout)

    def forward(self, params, x, hidden=None):
        fwd_out, fwd_h = self.fwd(params["fwd"], x,
                                  hidden[0] if hidden else None)
        rev = jnp.flip(x, axis=0)
        bwd_out, bwd_h = self.bwd(params["bwd"], rev,
                                  hidden[1] if hidden else None)
        out = jnp.concatenate([fwd_out, jnp.flip(bwd_out, axis=0)], axis=-1)
        return out, (fwd_h, bwd_h)
