"""apex_tpu.models — model zoo for examples and benchmarks."""

from .resnet import (ResNet, BasicBlock, Bottleneck, resnet18, resnet34,
                     resnet50, resnet101, resnet152, stem_weight_to_s2d,
                     convert_stem_to_s2d)
from .bert import (BertConfig, BertModel, BertForPretraining, bert_base,
                   bert_large)
from .dcgan import Generator, Discriminator, dcgan
from .gpt import GPTConfig, GPT, gpt2_small, gpt2_medium
from .llama import LlamaConfig, Llama, RMSNorm, llama_params_to_tp
from .mixtral import MixtralConfig, Mixtral
from .speculative import generate_speculative
from .beam import beam_search
from .t5 import T5Config, T5
