"""Mixtral-style sparse-MoE decoder: the Llama backbone with each
dense SwiGLU MLP replaced by a top-2-routed bank of SwiGLU experts.

The reference toolkit predates MoE entirely (SURVEY.md §2.3 lists EP as
absent); this family completes the beyond-reference parallelism story
at the model level: attention is Llama's GQA+RoPE stack
(models/llama.py), the FFN is ``parallel.expert_parallel
.ExpertParallelMLP`` (GShard dispatch, two all_to_alls per layer when
an ``ep_axis`` mesh axis is in scope), and the router's load-balancing
auxiliary loss (Switch eq. 4) rides ``loss`` with
``router_aux_loss_coef`` — HF Mixtral's config names are kept so a
checkpoint converter can map 1:1.

Decoding inherits Llama's fixed-buffer KV-cached loop unchanged: the
MoE runs its normal forward on the (B, 1, hidden) decode slice (top-2
of B tokens, capacity ceil(cf*B/E)).

Training with expert parallelism shards tokens AND experts over the
same mesh axis (DeepSpeed-MoE style); expert-sharded grads stay local
while everything else is data-parallel — use
``expert_parallel.allreduce_replicated_grads`` (or
``partition_specs``-aware state specs) instead of a blanket psum.
"""

from __future__ import annotations

from ..nn import module as nn
from ..parallel.expert_parallel import ExpertParallelMLP
from .llama import Llama, LlamaBlock, LlamaConfig

__all__ = ["MixtralConfig", "Mixtral"]


class MixtralConfig(LlamaConfig):
    def __init__(self, num_local_experts=8, num_experts_per_tok=2,
                 router_aux_loss_coef=0.02, capacity_factor=2.0,
                 ep_axis=None, **kw):
        super().__init__(**kw)
        if self.tp_axis is not None:
            raise NotImplementedError(
                "Mixtral composes MoE with dp/sp/ep; tensor parallelism "
                "inside experts is not wired — shard experts (ep_axis) "
                "instead")
        self.num_local_experts = num_local_experts
        self.num_experts_per_tok = num_experts_per_tok
        self.router_aux_loss_coef = router_aux_loss_coef
        self.capacity_factor = capacity_factor
        self.ep_axis = ep_axis


class MixtralBlock(LlamaBlock):
    def __init__(self, cfg: MixtralConfig):
        super().__init__(cfg)
        self.mlp = ExpertParallelMLP(
            cfg.hidden_size, cfg.intermediate_size,
            cfg.num_local_experts,
            capacity_factor=cfg.capacity_factor,
            top_k=cfg.num_experts_per_tok,
            expert_type="swiglu",
            axis_name=cfg.ep_axis or "expert")

    def forward(self, p, x, mask=None):
        x = x + self.self_attn(p["self_attn"],
                               self.input_layernorm(
                                   p["input_layernorm"], x), mask)
        h, aux = self.mlp(p["mlp"], self.post_attention_layernorm(
            p["post_attention_layernorm"], x), return_aux_loss=True)
        return x + h, aux
    # decode() inherits: ExpertParallelMLP's default forward returns
    # just the output, matching LlamaBlock.decode's self.mlp(...) call


class Mixtral(Llama):
    block_cls = MixtralBlock
