"""Llama-family causal language model (RMSNorm + RoPE + SwiGLU + GQA).

The reference toolkit predates decoder-only LMs entirely; GPT-2
(models/gpt.py) covers the learned-position/LayerNorm generation, and
this module covers the modern generation every serving stack expects:
RMS pre-normalization, rotary position embeddings, SwiGLU MLPs,
grouped-query attention with the compact KV cache, and the fused
chunked LM-head loss (nn.fused_xent).  Output parity against the
HuggingFace torch implementation — including greedy generation token
for token — is pinned in tests/test_llama.py; ``utils.hf_interop
.llama_from_hf`` converts checkpoints.

TPU shape discipline matches GPT: fixed-buffer generation (one compiled
program for any prompt length), flash attention on the training path
via dot_product_attention's dispatch, int8 weight/KV-cache quantization
(apex_tpu.quantization) drops in unchanged.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .. import nn
from ..nn import functional as F
from ..parallel.sync_batchnorm import _axis_in_scope as _sp_in_scope
from ..transformer.attention import dot_product_attention

__all__ = ["LlamaConfig", "Llama", "RMSNorm"]


class LlamaConfig:
    def __init__(self, vocab_size=32000, hidden_size=4096,
                 intermediate_size=11008, num_hidden_layers=32,
                 num_attention_heads=32, num_key_value_heads=None,
                 max_position_embeddings=2048, rms_norm_eps=1e-6,
                 rope_theta=10000.0, tie_word_embeddings=False,
                 head_chunk=8192, sp_axis=None, tp_axis=None,
                 remat=None, sliding_window=None, attention_bias=False,
                 head_dim=None, mlp_act="silu", rms_unit_offset=False,
                 embed_scale=False, norm_type="rmsnorm",
                 parallel_residual=False, rotary_pct=1.0,
                 mlp_type="swiglu", attention_out_bias=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.num_key_value_heads = (num_key_value_heads
                                    if num_key_value_heads is not None
                                    else num_attention_heads)
        if (self.num_key_value_heads < 1
                or num_attention_heads % self.num_key_value_heads):
            raise ValueError(
                f"num_key_value_heads={self.num_key_value_heads} must be "
                f"a positive divisor of num_attention_heads="
                f"{num_attention_heads}")
        if hidden_size % num_attention_heads:
            raise ValueError("hidden_size must divide into heads")
        self.max_position_embeddings = max_position_embeddings
        self.rms_norm_eps = rms_norm_eps
        self.rope_theta = rope_theta
        self.tie_word_embeddings = tie_word_embeddings
        self.head_chunk = head_chunk
        # sequence parallelism: tokens sharded over this mesh axis; the
        # causal attention runs as ring attention (K/V blocks rotate
        # over ICI) and RoPE uses GLOBAL positions, so
        # max_position_embeddings bounds the GLOBAL sequence (the GPT
        # sp contract, models/gpt.py)
        self.sp_axis = sp_axis
        # tensor parallelism: Megatron attention/MLP sharding over this
        # axis (parallel.ParallelSelfAttention with num_kv_heads +
        # rope_theta; SwiGLU as column/column/row).  Embeddings, norms,
        # and the LM head stay replicated — the row-parallel psum leaves
        # x replicated, so the fused head loss is unchanged.
        self.tp_axis = tp_axis
        if tp_axis is not None and sp_axis is not None:
            raise NotImplementedError(
                "combined tp+sp Llama is not wired; pick one")
        # per-block rematerialization: None | "nothing" | "dots"
        # (models/_remat.py) — the long-context HBM lever
        from ._remat import _MODES
        if remat not in _MODES:
            raise ValueError(f"remat={remat!r} not in {_MODES}")
        self.remat = remat
        # Mistral-style sliding-window attention: key j visible to
        # query i iff i - W < j <= i.  Training takes the dense
        # (banded-mask) path — the flash kernel streams key-padding
        # masks, not bands; decode applies the window in its cache
        # read.  The KV cache stays full-length (HF's rolling buffer
        # is a memory optimization, not a semantics change).
        if sliding_window is not None:
            if sliding_window < 1:
                raise ValueError(f"sliding_window={sliding_window} "
                                 f"must be >= 1")
            if sp_axis is not None or tp_axis is not None:
                raise NotImplementedError(
                    "sliding_window composes with dp only; the ring/"
                    "Megatron attention paths are full-window")
        self.sliding_window = sliding_window
        # Qwen2-style Q/K/V projection biases (o_proj stays bias-free)
        if attention_bias and tp_axis is not None:
            raise NotImplementedError(
                "attention_bias under tensor parallelism is not wired "
                "(ParallelSelfAttention biases all projections incl. "
                "out)")
        self.attention_bias = attention_bias
        # Gemma-family knobs: per-head dim decoupled from hidden_size
        # (gemma-7b: 16 heads x 256 > 3072), GeGLU MLP activation,
        # (1 + w) RMSNorm scaling, sqrt(hidden) embedding scale
        self.head_dim = (head_dim if head_dim is not None
                         else hidden_size // num_attention_heads)
        if head_dim is not None and tp_axis is not None:
            raise NotImplementedError(
                "custom head_dim under tensor parallelism is not wired")
        if mlp_act not in ("silu", "gelu_tanh"):
            raise ValueError(f"mlp_act={mlp_act!r} not in "
                             f"('silu', 'gelu_tanh')")
        self.mlp_act = mlp_act
        self.rms_unit_offset = rms_unit_offset
        self.embed_scale = embed_scale
        # GPT-NeoX/Pythia knobs: LayerNorm blocks, parallel residual
        # (x + attn(ln1 x) + mlp(ln2 x)), partial rotary (first
        # rotary_pct of each head's dims), biased 2-layer GeLU MLP
        if norm_type not in ("rmsnorm", "layernorm"):
            raise ValueError(f"norm_type={norm_type!r} not in "
                             f"('rmsnorm', 'layernorm')")
        self.norm_type = norm_type
        self.parallel_residual = parallel_residual
        if not 0.0 < rotary_pct <= 1.0:
            raise ValueError(f"rotary_pct={rotary_pct} not in (0, 1]")
        self.rotary_pct = rotary_pct
        if mlp_type not in ("swiglu", "gelu_mlp"):
            raise ValueError(f"mlp_type={mlp_type!r} not in "
                             f"('swiglu', 'gelu_mlp')")
        if mlp_type != "swiglu" and tp_axis is not None:
            raise NotImplementedError(
                "gelu_mlp under tensor parallelism is not wired")
        self.mlp_type = mlp_type
        self.attention_out_bias = attention_out_bias


class RMSNorm(nn.Module):
    """x * rsqrt(mean(x^2) + eps) * w — stats in fp32 (the norm is on
    amp's fp32 side, like LayerNorm), output in the input dtype."""

    def __init__(self, dim: int, eps: float = 1e-6,
                 unit_offset: bool = False):
        super().__init__()
        self.dim = dim
        self.eps = eps
        # Gemma convention: scale by (1 + w), checkpoint stores w
        self.unit_offset = unit_offset

    def create_params(self, key):
        return {"weight": jnp.ones((self.dim,), jnp.float32)}

    def forward(self, p, x):
        xf = x.astype(jnp.float32)
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
        y = xf * lax.rsqrt(var + self.eps)
        w = p["weight"].astype(jnp.float32)
        if self.unit_offset:
            w = 1.0 + w
        return (y * w).astype(x.dtype)


def _rope_cos_sin(pos, head_dim, theta, dtype):
    """HF-llama convention: inv_freq over the first D/2 dims, cos/sin
    tiled twice (rotate-half pairing, NOT interleaved)."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, jnp.float32)
                           / head_dim))
    ang = pos.astype(jnp.float32)[..., None] * inv      # (..., T, D/2)
    emb = jnp.concatenate([ang, ang], axis=-1)
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _rotate_half(x):
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def apply_rope(q, k, pos, theta):
    """q: (B, H, T, D), k: (B, Hkv, T, D), pos: (B, T) or (T,)."""
    cos, sin = _rope_cos_sin(jnp.asarray(pos), q.shape[-1], theta,
                             jnp.float32)
    while cos.ndim < q.ndim:                  # -> broadcast over heads
        cos, sin = cos[..., None, :, :], sin[..., None, :, :]

    def rot(x):
        xf = x.astype(jnp.float32)
        return (xf * cos + _rotate_half(xf) * sin).astype(x.dtype)

    return rot(q), rot(k)


class LlamaAttention(nn.Module):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.H = cfg.num_attention_heads
        self.Hkv = cfg.num_key_value_heads
        self.D = cfg.head_dim
        self.theta = cfg.rope_theta
        self.sp = cfg.sp_axis
        self.tp = cfg.tp_axis is not None
        self.window = getattr(cfg, "sliding_window", None)
        # partial rotary (GPT-NeoX): first rot_dim dims rotate, the
        # rest pass through
        self.rot_dim = int(getattr(cfg, "rotary_pct", 1.0) * self.D)
        E = cfg.hidden_size
        if self.tp:
            from ..parallel.tensor_parallel import ParallelSelfAttention
            self.core = ParallelSelfAttention(
                E, self.H, bias=False, causal=True,
                axis_name=cfg.tp_axis, num_kv_heads=self.Hkv,
                rope_theta=cfg.rope_theta)
        else:
            ab = getattr(cfg, "attention_bias", False)
            self.q_proj = nn.Linear(E, self.H * self.D, bias=ab)
            self.k_proj = nn.Linear(E, self.Hkv * self.D, bias=ab)
            self.v_proj = nn.Linear(E, self.Hkv * self.D, bias=ab)
            self.o_proj = nn.Linear(
                self.H * self.D, E,
                bias=getattr(cfg, "attention_out_bias", False))

    def _rope(self, q, k, pos):
        if self.rot_dim >= self.D:
            return apply_rope(q, k, pos, self.theta)
        rd = self.rot_dim
        q1, k1 = apply_rope(q[..., :rd], k[..., :rd], pos, self.theta)
        return (jnp.concatenate([q1, q[..., rd:]], axis=-1),
                jnp.concatenate([k1, k[..., rd:]], axis=-1))

    def _qkv(self, p, x, B, T):
        q = self.q_proj(p["q_proj"], x).reshape(B, T, self.H, self.D)
        k = self.k_proj(p["k_proj"], x).reshape(B, T, self.Hkv, self.D)
        v = self.v_proj(p["v_proj"], x).reshape(B, T, self.Hkv, self.D)
        return (jnp.moveaxis(q, 2, 1), jnp.moveaxis(k, 2, 1),
                jnp.moveaxis(v, 2, 1))

    def forward(self, p, x, mask=None):
        B, T, E = x.shape
        if self.tp:
            return self.core(p["core"], x, mask)
        q, k, v = self._qkv(p, x, B, T)
        in_sp = self.sp is not None and _sp_in_scope(self.sp)
        pos = jnp.arange(T)
        if in_sp:
            # GLOBAL positions for this device's token shard
            pos = lax.axis_index(self.sp) * T + pos
        q, k = self._rope(q, k, pos)
        if self.Hkv != self.H:
            rep = self.H // self.Hkv
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        if in_sp:
            from ..transformer.ring_attention import ring_attention
            ctx = ring_attention(q, k, v, axis_name=self.sp, causal=True)
        else:
            mask = self._with_band(mask, T)
            ctx = dot_product_attention(q, k, v, mask, causal=True,
                                        dropout_rate=0.0)
        ctx = jnp.moveaxis(ctx, 1, 2).reshape(
            B, T, self.H * self.D)
        return self.o_proj(p["o_proj"], ctx)

    def _with_band(self, mask, T):
        """AND the sliding-window band (key j visible to query i iff
        j > i - W; the causal half lives in causal=True) into ``mask``."""
        if self.window is None:
            return mask
        band = (jnp.arange(T)[None, :]
                > jnp.arange(T)[:, None] - self.window)[None, None]
        return band if mask is None else (mask & band)

    def prefill(self, p, x):
        """Full-sequence attention that also returns the COMPACT
        post-RoPE K/V for cache seeding: ``(out, k, v)`` with k/v
        (B, Hkv, T, D) — one MXU-friendly pass instead of T sequential
        ``decode`` steps (values identical to what decode would have
        written position by position)."""
        B, T, E = x.shape
        q, k, v = self._qkv(p, x, B, T)
        q, k = self._rope(q, k, jnp.arange(T))
        kc, vc = k, v
        if self.Hkv != self.H:
            rep = self.H // self.Hkv
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        ctx = dot_product_attention(q, k, v, self._with_band(None, T),
                                    causal=True, dropout_rate=0.0)
        ctx = jnp.moveaxis(ctx, 1, 2).reshape(
            B, T, self.H * self.D)
        return self.o_proj(p["o_proj"], ctx), kc, vc

    def decode_chunk(self, p, x, pos, cache):
        """L-token cached step at PER-ROW positions: ``x`` (B, L, E)
        holds each row's tokens for positions ``[pos[b], pos[b]+L)``;
        writes the chunk's post-RoPE K/V there and attends each chunk
        query to cache keys <= its own position (within the sliding
        window if set).  This is the speculative-verify workhorse: one
        MXU pass scores gamma+1 proposals against the live cache.
        int8 caches quantize the chunk per position (the same
        amax/127 sidecar math as the single-token path)."""
        B, L, E = x.shape
        S = cache["k"].shape[2]
        rolling = self.window is not None and S == self.window
        if rolling and L > 1:
            # a chunk that wraps the ring overwrites slots still inside
            # EARLIER chunk queries' windows (slot (p' mod W) for a
            # later p' held p' - W, which is >= p - W + 1 for any
            # earlier in-chunk query p) — exactness would need per-query
            # cache snapshots.  L == 1 (the serving engine's tick) has
            # no such aliasing and is wired below.
            raise NotImplementedError(
                "decode_chunk over a rolling cache supports only "
                "L == 1 (engine ticks); use full-width caches for "
                "chunked verify/prefill")
        q, k, v = self._qkv(p, x, B, L)
        posL = pos[:, None] + jnp.arange(L)                 # (B, L)
        q, k = self._rope(q, k, posL)
        wpos = (pos % S) if rolling else pos                # write slot

        def put(buf, val):
            # per-row offsets: vmap a dynamic_update_slice over batch
            return jax.vmap(
                lambda b, vv, p0: lax.dynamic_update_slice(
                    b, vv.astype(b.dtype), (0, p0, 0)))(buf, val, wpos)

        cache = dict(cache)
        if cache["k"].dtype == jnp.int8:
            from ._cache import quantize_kv
            for name, val in (("k", k), ("v", v)):
                ints, scale = quantize_kv(val)
                cache[name] = put(cache[name], ints)
                cache[f"{name}_scale"] = put(cache[f"{name}_scale"],
                                             scale)
            kf = (cache["k"].astype(jnp.float32)
                  * cache["k_scale"].astype(jnp.float32))
            vf = (cache["v"].astype(jnp.float32)
                  * cache["v_scale"].astype(jnp.float32))
        else:
            cache["k"] = put(cache["k"], k)
            cache["v"] = put(cache["v"], v)
            kf = cache["k"].astype(jnp.float32)
            vf = cache["v"].astype(jnp.float32)
        G = self.H // self.Hkv
        qg = q.reshape(B, self.Hkv, G, L, self.D)
        scores = jnp.einsum("bkgld,bksd->bkgls",
                            qg.astype(jnp.float32), kf)
        scores = scores * (1.0 / (self.D ** 0.5))
        kpos = jnp.arange(S)[None, None, None, None, :]
        qpos = posL[:, None, None, :, None]
        if rolling:
            # slot s holds absolute position q - ((q - s) mod W) per
            # row (the step path's reconstruction, vectorized over B):
            # always <= q and > q - W, so only p_s >= 0 needs checking
            p_s = qpos - ((qpos - kpos) % S)
            valid = p_s >= 0
        else:
            valid = kpos <= qpos
            if self.window is not None:
                valid = valid & (kpos > qpos - self.window)
        scores = jnp.where(valid, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bkgls,bksd->bkgld", probs, vf).astype(x.dtype)
        ctx = jnp.transpose(ctx, (0, 3, 1, 2, 4)).reshape(
            B, L, self.H * self.D)
        return self.o_proj(p["o_proj"], ctx), cache

    def decode(self, p, x, pos, cache):
        """One-token step; ``cache`` {"k","v"} (B, Hkv, S, D) (+int8
        scale sidecars) — RoPE applied at ``pos`` before the write, so
        cached keys are already rotated (the standard layout)."""
        if self.tp:
            raise NotImplementedError(
                "KV-cache decode is single-device; run the TP model "
                "through forward() or shard the batch instead")
        B, _, E = x.shape
        S = cache["k"].shape[2]
        q, k, v = self._qkv(p, x, B, 1)
        q, k = self._rope(q, k, jnp.full((1,), pos))
        q, k, v = q[:, :, 0], k[:, :, 0], v[:, :, 0]
        q8 = cache["k"].dtype == jnp.int8
        # rolling buffer: a cache exactly window-wide stores position p
        # in slot p % W (Mistral's layout) — W entries instead of the
        # full sequence; the slot's absolute position is reconstructed
        # below for the validity mask
        rolling = self.window is not None and S == self.window
        wpos = (pos % S) if rolling else pos

        def put(buf, val):
            return lax.dynamic_update_slice_in_dim(
                buf, val[:, :, None, :].astype(buf.dtype), wpos, axis=2)

        cache = dict(cache)
        if q8:
            from ._cache import quantize_kv
            for name, val in (("k", k), ("v", v)):
                ints, scale = quantize_kv(val)
                cache[name] = put(cache[name], ints)
                cache[f"{name}_scale"] = put(cache[f"{name}_scale"], scale)
            kf = (cache["k"].astype(jnp.float32)
                  * cache["k_scale"].astype(jnp.float32))
            vf = (cache["v"].astype(jnp.float32)
                  * cache["v_scale"].astype(jnp.float32))
        else:
            cache["k"] = put(cache["k"], k)
            cache["v"] = put(cache["v"], v)
            kf = cache["k"].astype(jnp.float32)
            vf = cache["v"].astype(jnp.float32)
        G = self.H // self.Hkv
        qg = q.reshape(B, self.Hkv, G, self.D)
        scores = jnp.einsum("bkgd,bksd->bkgs", qg.astype(jnp.float32), kf)
        scores = scores * (1.0 / (self.D ** 0.5))
        if rolling:
            # slot s holds absolute position pos - ((pos - s) mod W)
            s_idx = jnp.arange(S)
            p_s = pos - ((pos - s_idx) % S)
            valid = (p_s >= 0)[None, None, None, :]
        else:
            valid = jnp.arange(S)[None, None, None, :] <= pos
            if self.window is not None:
                valid = valid & (jnp.arange(S)[None, None, None, :]
                                 > pos - self.window)
        scores = jnp.where(valid, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bkgs,bksd->bkgd", probs, vf).astype(x.dtype)
        return self.o_proj(
            p["o_proj"], ctx.reshape(B, 1, self.H * self.D)), cache


class LlamaMLP(nn.Module):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.tp_axis = cfg.tp_axis
        self.act = getattr(cfg, "mlp_act", "silu")
        if cfg.tp_axis is not None:
            from ..parallel.tensor_parallel import (ColumnParallelLinear,
                                                    RowParallelLinear)
            # SwiGLU Megatron-style: gate/up column-parallel (one f at
            # entry, shared by both), down row-parallel (one psum)
            self.gate_proj = ColumnParallelLinear(
                cfg.hidden_size, cfg.intermediate_size, bias=False,
                input_grad_reduce=False, axis_name=cfg.tp_axis)
            self.up_proj = ColumnParallelLinear(
                cfg.hidden_size, cfg.intermediate_size, bias=False,
                input_grad_reduce=False, axis_name=cfg.tp_axis)
            self.down_proj = RowParallelLinear(
                cfg.intermediate_size, cfg.hidden_size, bias=False,
                axis_name=cfg.tp_axis)
        else:
            self.gate_proj = nn.Linear(cfg.hidden_size,
                                       cfg.intermediate_size, bias=False)
            self.up_proj = nn.Linear(cfg.hidden_size,
                                     cfg.intermediate_size, bias=False)
            self.down_proj = nn.Linear(cfg.intermediate_size,
                                       cfg.hidden_size, bias=False)

    def forward(self, p, x):
        if self.tp_axis is not None:
            from ..parallel.tensor_parallel import copy_to_model_parallel
            x = copy_to_model_parallel(x, self.tp_axis)
        act = F.silu if self.act == "silu" else F.gelu
        return self.down_proj(
            p["down_proj"],
            act(self.gate_proj(p["gate_proj"], x))
            * self.up_proj(p["up_proj"], x))


class GeluMLP(nn.Module):
    """GPT-NeoX 2-layer MLP: dense_h_to_4h -> exact gelu ->
    dense_4h_to_h, biases throughout (param names match the HF
    checkpoint keys for converter transparency)."""

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.dense_h_to_4h = nn.Linear(cfg.hidden_size,
                                       cfg.intermediate_size, bias=True)
        self.dense_4h_to_h = nn.Linear(cfg.intermediate_size,
                                       cfg.hidden_size, bias=True)

    def forward(self, p, x):
        return self.dense_4h_to_h(
            p["dense_4h_to_h"],
            F.gelu_exact(self.dense_h_to_4h(p["dense_h_to_4h"], x)))


def _make_norm(cfg):
    if getattr(cfg, "norm_type", "rmsnorm") == "layernorm":
        from ..normalization import FusedLayerNorm
        return FusedLayerNorm(cfg.hidden_size, eps=cfg.rms_norm_eps)
    return RMSNorm(cfg.hidden_size, cfg.rms_norm_eps,
                   getattr(cfg, "rms_unit_offset", False))


class LlamaBlock(nn.Module):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.input_layernorm = _make_norm(cfg)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = _make_norm(cfg)
        self.mlp = (GeluMLP(cfg)
                    if getattr(cfg, "mlp_type", "swiglu") == "gelu_mlp"
                    else LlamaMLP(cfg))
        self.parallel_residual = getattr(cfg, "parallel_residual",
                                         False)

    def forward(self, p, x, mask=None):
        a = self.self_attn(p["self_attn"],
                           self.input_layernorm(
                               p["input_layernorm"], x), mask)
        if self.parallel_residual:      # NeoX: both norms see x
            return x + a + self.mlp(
                p["mlp"], self.post_attention_layernorm(
                    p["post_attention_layernorm"], x))
        x = x + a
        return x + self.mlp(p["mlp"], self.post_attention_layernorm(
            p["post_attention_layernorm"], x))

    def decode(self, p, x, pos, cache):
        a, cache = self.self_attn.decode(
            p["self_attn"], self.input_layernorm(p["input_layernorm"], x),
            pos, cache)
        if self.parallel_residual:
            return x + a + self.mlp(
                p["mlp"], self.post_attention_layernorm(
                    p["post_attention_layernorm"], x)), cache
        x = x + a
        return x + self.mlp(p["mlp"], self.post_attention_layernorm(
            p["post_attention_layernorm"], x)), cache

    def prefill(self, p, x):
        a, k, v = self.self_attn.prefill(
            p["self_attn"], self.input_layernorm(p["input_layernorm"], x))
        if self.parallel_residual:
            return x + a + self.mlp(
                p["mlp"], self.post_attention_layernorm(
                    p["post_attention_layernorm"], x)), k, v
        x = x + a
        return x + self.mlp(p["mlp"], self.post_attention_layernorm(
            p["post_attention_layernorm"], x)), k, v

    def decode_chunk(self, p, x, pos, cache):
        a, cache = self.self_attn.decode_chunk(
            p["self_attn"], self.input_layernorm(p["input_layernorm"], x),
            pos, cache)
        if self.parallel_residual:
            return x + a + self.mlp(
                p["mlp"], self.post_attention_layernorm(
                    p["post_attention_layernorm"], x)), cache
        x = x + a
        return x + self.mlp(p["mlp"], self.post_attention_layernorm(
            p["post_attention_layernorm"], x)), cache


class Llama(nn.Module):
    block_cls = LlamaBlock      # hook for MoE (Mixtral) variants

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        # Llama's initializer_range=0.02 (scratch-training sanity with
        # tied heads; HF-loaded checkpoints overwrite it anyway)
        self.embed_tokens = nn.Embedding(cfg.vocab_size,
                                         cfg.hidden_size,
                                         init_std=0.02)
        self.layers = nn.ModuleList(
            [self.block_cls(cfg) for _ in range(cfg.num_hidden_layers)])
        self.norm = _make_norm(cfg)
        if not cfg.tie_word_embeddings:
            self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                     bias=False)

    def _table(self, p):
        return (p["embed_tokens"]["weight"]
                if self.cfg.tie_word_embeddings
                else p["lm_head"]["weight"])

    def _backbone(self, p, input_ids, mask=None):
        B, T = input_ids.shape
        sp = self.cfg.sp_axis
        if sp is not None and _sp_in_scope(sp):
            if mask is not None:
                raise NotImplementedError(
                    "attention_mask under sequence parallelism is not "
                    "wired; pack/pad outside the sp axis instead")
            if T * lax.axis_size(sp) > self.cfg.max_position_embeddings:
                raise ValueError(
                    f"global sequence {T}x{lax.axis_size(sp)} exceeds "
                    f"max_position_embeddings "
                    f"{self.cfg.max_position_embeddings}")
        elif T > self.cfg.max_position_embeddings:
            raise ValueError(f"sequence length {T} exceeds "
                             f"max_position_embeddings "
                             f"{self.cfg.max_position_embeddings}")
        x = self.embed_tokens(p["embed_tokens"], input_ids)
        if self.cfg.embed_scale:
            x = x * jnp.asarray(self.cfg.hidden_size ** 0.5, x.dtype)
        m = None
        if mask is not None:
            m = mask[:, None, None, :].astype(bool)
        aux = 0.0
        from ._remat import wrap_block
        for i in range(self.cfg.num_hidden_layers):
            fn = wrap_block(
                lambda pp, xx, blk=self.layers[i]: blk(pp, xx, m),
                self.cfg.remat)
            out = fn(p["layers"][str(i)], x)
            if isinstance(out, tuple):      # MoE block: (x, aux loss)
                x, a = out
                aux = aux + a
            else:
                x = out
        return (self.norm(p["norm"], x),
                aux / self.cfg.num_hidden_layers)

    def forward(self, p, input_ids, attention_mask=None):
        x, _ = self._backbone(p, input_ids, attention_mask)
        table = self._table(p)
        return F.matmul(x, table.T.astype(x.dtype))

    def loss(self, p, input_ids, attention_mask=None, ignore_index=-100):
        """Next-token cross-entropy via the fused chunked head
        (nn.fused_xent) — same contract as GPT.loss, including the
        cross-shard label shift under ``sp_axis``."""
        sp = self.cfg.sp_axis
        if sp is not None and _sp_in_scope(sp):
            if attention_mask is not None:
                raise NotImplementedError(
                    "attention_mask under sequence parallelism is not "
                    "wired; pack/pad outside the sp axis instead")
            B, T = input_ids.shape
            spn = lax.axis_size(sp)
            idx = lax.axis_index(sp)
            x, aux = self._backbone(p, input_ids)
            nxt_first = lax.ppermute(
                input_ids[:, :1], sp,
                [(i, (i - 1) % spn) for i in range(spn)])
            labels = jnp.concatenate([input_ids[:, 1:], nxt_first], 1)
            is_last = (idx == spn - 1)
            labels = labels.at[:, -1].set(
                jnp.where(is_last, ignore_index, labels[:, -1]))
            valid = labels != ignore_index
            safe = jnp.where(valid, labels, 0)
            nll = self._nll(p, x, safe)
            num = lax.psum(jnp.sum(nll * valid), sp)
            den = lax.psum(jnp.sum(valid.astype(jnp.float32)), sp)
            return num / jnp.maximum(den, 1.0) + self._aux_term(aux, sp)
        labels = input_ids[:, 1:]
        if attention_mask is not None:
            labels = jnp.where(attention_mask[:, 1:] != 0, labels,
                               ignore_index)
        x, aux = self._backbone(p, input_ids, attention_mask)
        x = x[:, :-1]
        valid = labels != ignore_index
        safe = jnp.where(valid, labels, 0)
        nll = self._nll(p, x, safe)
        return (jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1)
                + self._aux_term(aux, None))

    def _aux_term(self, aux, sp):
        """Router load-balance contribution; 0 for dense families."""
        coef = getattr(self.cfg, "router_aux_loss_coef", 0.0)
        if not coef:
            return 0.0
        if sp is not None:
            aux = lax.pmean(aux, sp)
        return coef * aux

    def _nll(self, p, x, safe_labels):
        """Per-position nll (B, T') through the head — fused chunked
        path by default (GPT._head_nll's contract)."""
        table = self._table(p)
        from ..quantization import QTensor
        if isinstance(table, QTensor):
            table = table.dequant(x.dtype)
        B, T, D = x.shape
        if self.cfg.head_chunk:
            from ..nn.fused_xent import linear_cross_entropy
            return linear_cross_entropy(
                x.reshape(B * T, D), table, safe_labels.reshape(-1),
                int(self.cfg.head_chunk)).reshape(B, T)
        logits = F.matmul(x, table.T.astype(x.dtype))
        logp = F.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, safe_labels[..., None],
                                    axis=-1)[..., 0]

    # -- KV-cached decoding (mirrors GPT's fixed-buffer discipline) -----
    def init_cache(self, batch_size: int, dtype=jnp.float32,
                   rolling: bool = False):
        """``rolling=True`` (requires ``sliding_window``) allocates
        window-wide buffers — position p lives in slot p % W, so cache
        memory is O(window), not O(sequence); decode detects the layout
        from the buffer width."""
        cfg = self.cfg
        if rolling and cfg.sliding_window is None:
            raise ValueError("rolling cache requires sliding_window")
        width = (cfg.sliding_window if rolling
                 else cfg.max_position_embeddings)
        shape = (batch_size, cfg.num_key_value_heads,
                 width, cfg.head_dim)

        # one allocation PER LAYER — a zeros buffer shared across
        # layers would be donated num_hidden_layers times by the
        # serving engine's cache mutators (XLA rejects double donation)
        def layer():
            out = {"k": jnp.zeros(shape, dtype),
                   "v": jnp.zeros(shape, dtype)}
            if dtype == jnp.int8:
                sshape = shape[:3] + (1,)
                out["k_scale"] = jnp.zeros(sshape, jnp.float32)
                out["v_scale"] = jnp.zeros(sshape, jnp.float32)
            return out

        return {str(i): layer()
                for i in range(cfg.num_hidden_layers)}

    def _decode_hidden(self, p, token, pos, cache):
        """Blocks-only decode step — the LM head is separate so prefill
        steps can skip the full-vocab matmul (GPT's contract)."""
        new_cache = {}
        x = self.embed_tokens(p["embed_tokens"], token[:, None])
        if self.cfg.embed_scale:
            x = x * jnp.asarray(self.cfg.hidden_size ** 0.5, x.dtype)
        for i in range(self.cfg.num_hidden_layers):
            li = str(i)
            x, new_cache[li] = self.layers[i].decode(
                p["layers"][li], x, pos, cache[li])
        return self.norm(p["norm"], x), new_cache

    def decode_step(self, p, token, pos, cache):
        x, new_cache = self._decode_hidden(p, token, pos, cache)
        table = self._table(p)
        return F.matmul(x, table.T.astype(x.dtype))[:, 0], new_cache

    def prefill_cache(self, p, input_ids, cache=None, cache_dtype=None):
        """Seed every layer's KV cache with ONE full-buffer forward
        (models/_cache.py semantics; identical values to walking the
        positions with decode)."""
        from ._cache import seed_layer
        B, S = input_ids.shape
        if cache is None:
            if cache_dtype is None:
                cache_dtype = self._table(p).dtype
            cache = self.init_cache(B, dtype=cache_dtype)
        x = self.embed_tokens(p["embed_tokens"], input_ids)
        if self.cfg.embed_scale:
            x = x * jnp.asarray(self.cfg.hidden_size ** 0.5, x.dtype)
        for i in range(self.cfg.num_hidden_layers):
            li = str(i)
            x, k, v = self.layers[i].prefill(p["layers"][li], x)
            cache[li] = seed_layer(cache[li], k, v)
        return cache

    def decode_chunk(self, p, tokens, pos, cache):
        """Cached multi-token step at per-row positions: ``tokens``
        (B, L) for positions ``[pos[b], pos[b]+L)`` -> (final hidden
        (B, L, E), updated cache).  The head stays separate (same
        contract as _decode_hidden)."""
        x = self.embed_tokens(p["embed_tokens"], tokens)
        if self.cfg.embed_scale:
            x = x * jnp.asarray(self.cfg.hidden_size ** 0.5, x.dtype)
        new_cache = {}
        for i in range(self.cfg.num_hidden_layers):
            li = str(i)
            x, new_cache[li] = self.layers[i].decode_chunk(
                p["layers"][li], x, pos, cache[li])
        return self.norm(p["norm"], x), new_cache

    def generate_cached(self, p, input_ids, prompt_len,
                        max_new_tokens: int, temperature: float = 0.0,
                        rng: Optional[jax.Array] = None,
                        cache_dtype=None,
                        top_k: Optional[int] = None,
                        top_p: Optional[float] = None,
                        prefill_mode: str = "chunked",
                        rolling_cache: bool = False,
                        min_p: Optional[float] = None,
                        repetition_penalty: float = 1.0):
        """Fixed-buffer KV-cached greedy/sampled generation; one
        compiled program for any prompt length, prefill steps skipping
        the full-vocab head via ``lax.cond`` (GPT.generate_cached's
        contract; token-for-token vs HF greedy in tests).
        ``top_k``/``top_p`` filter sampled steps (models/sampling.py).

        ``prefill_mode="chunked"`` (default) seeds the KV cache with
        ONE full-buffer forward (models/_cache.py) and starts the
        sequential loop at the earliest prompt end — prefill rides the
        MXU instead of min(prompt_len) dependent steps.  ``"step"``
        restores the walk-every-position loop.

        ``rolling_cache=True`` (sliding-window models) allocates
        window-wide cache buffers (O(window) memory); the loop walks
        every position ("step" prefill — slots fill as it goes), and
        each step attends only the window's W entries."""
        from . import sampling
        if prefill_mode not in ("chunked", "step"):
            raise ValueError(f"prefill_mode {prefill_mode!r} not in "
                             f"('chunked', 'step')")
        if rolling_cache:
            prefill_mode = "step"     # slots fill as the loop walks
        B, S = input_ids.shape
        prompt_len = jnp.broadcast_to(jnp.asarray(prompt_len), (B,))
        if temperature > 0.0 and rng is None:
            raise ValueError("sampling (temperature > 0) needs rng=")
        final_len = jnp.minimum(prompt_len + max_new_tokens, S)
        first_gen = jnp.min(prompt_len)
        if cache_dtype is None:
            cache_dtype = self._table(p).dtype
        cache = self.init_cache(B, dtype=cache_dtype,
                                rolling=rolling_cache)
        key = rng if rng is not None else jax.random.PRNGKey(0)
        start = 0
        if prefill_mode == "chunked":
            cache = self.prefill_cache(p, input_ids, cache)
            # entries at positions >= first_gen - 1 are rewritten by
            # the loop before any later position reads them
            start = jnp.maximum(first_gen - 1, 0)

        def body(i, carry):
            ids, cache, key = carry
            x, cache = self._decode_hidden(p, ids[:, i], i, cache)

            def live(args):
                x, key = args
                table = self._table(p)
                logits = F.matmul(x, table.T.astype(x.dtype))[:, 0]
                if repetition_penalty != 1.0:
                    logits = sampling.apply_repetition_penalty(
                        logits, ids, jnp.maximum(prompt_len, i + 1),
                        repetition_penalty)
                if temperature > 0.0:
                    key, sub = jax.random.split(key)
                    nxt = sampling.sample_token(sub, logits, temperature,
                                                top_k=top_k, top_p=top_p,
                                                min_p=min_p)
                else:
                    nxt = jnp.argmax(logits, axis=-1)
                return nxt.astype(ids.dtype), key

            def prefill(args):
                _, key = args
                return jnp.zeros((B,), ids.dtype), key

            nxt, key = lax.cond(i + 1 >= first_gen, live, prefill,
                                (x, key))
            should = (i + 1 >= prompt_len) & (i + 1 < final_len)
            col = jnp.where(should, nxt, ids[:, i + 1])
            ids = lax.dynamic_update_slice_in_dim(
                ids, col[:, None], i + 1, axis=1)
            return ids, cache, key

        ids, _, _ = lax.fori_loop(start, jnp.max(final_len) - 1, body,
                                  (input_ids, cache, key))
        return ids, final_len


def llama_params_to_tp(params):
    """Rename a non-TP Llama param tree to the ``tp_axis`` structure.

    Under ``tp_axis`` attention is implemented by
    ``parallel.tensor_parallel.ParallelSelfAttention``, whose param tree
    is ``self_attn.core.{q,k,v,out}`` rather than the HF-style
    ``self_attn.{q_proj,k_proj,v_proj,o_proj}``; the MLP keeps its
    names (only the sharding layout changes).  Use this to feed
    ``utils.hf_interop.llama_from_hf`` output — or any checkpoint
    trained without tp_axis — into ``Llama(LlamaConfig(tp_axis=...))``.
    Weights stay full-size; sharding is applied by
    ``parallel.tensor_parallel.partition_specs`` + shard_map.
    """
    out = dict(params)
    out["layers"] = {}
    for i, blk in params["layers"].items():
        blk = dict(blk)
        at = blk.pop("self_attn")
        blk["self_attn"] = {"core": {
            "q": {"weight": at["q_proj"]["weight"]},
            "k": {"weight": at["k_proj"]["weight"]},
            "v": {"weight": at["v_proj"]["weight"]},
            "out": {"weight": at["o_proj"]["weight"]},
        }}
        out["layers"][i] = blk
    return out
