"""ResNet family for the imagenet example and benchmarks.

The reference's examples/tests train torchvision ResNet-50
(examples/imagenet/main_amp.py:150, tests/L1/common/main_amp.py); apex_tpu
ships its own definition on apex_tpu.nn so amp's param casting, SyncBatchNorm
conversion, and the policy-aware conv/linear ops all apply.  Structure
matches torchvision's v1 ResNet (stride-2 in the bottleneck's 3x3, like
the torchvision the reference era used).
"""

from __future__ import annotations

from typing import List, Optional, Type

import jax.numpy as jnp

from .. import nn
from ..nn import functional as F

__all__ = ["ResNet", "BasicBlock", "Bottleneck", "resnet18", "resnet34",
           "resnet50", "resnet101", "resnet152", "stem_weight_to_s2d",
           "convert_stem_to_s2d"]


def conv3x3(cin, cout, stride=1, data_format="NCHW"):
    return nn.Conv2d(cin, cout, 3, stride=stride, padding=1, bias=False,
                     data_format=data_format)


def conv1x1(cin, cout, stride=1, data_format="NCHW"):
    return nn.Conv2d(cin, cout, 1, stride=stride, bias=False,
                     data_format=data_format)


def _bn(planes, data_format):
    from ..nn.functional import _check_data_format
    _check_data_format(data_format)
    return nn.BatchNorm2d(
        planes, channel_axis=(1 if data_format == "NCHW" else -1))


class BasicBlock(nn.Module):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 data_format="NCHW"):
        super().__init__()
        self.conv1 = conv3x3(inplanes, planes, stride, data_format)
        self.bn1 = _bn(planes, data_format)
        self.conv2 = conv3x3(planes, planes, data_format=data_format)
        self.bn2 = _bn(planes, data_format)
        self.downsample = downsample

    def forward(self, p, x):
        identity = x
        out = F.relu(self.bn1(p["bn1"], self.conv1(p["conv1"], x)))
        out = self.bn2(p["bn2"], self.conv2(p["conv2"], out))
        if self.downsample is not None:
            identity = self.downsample(p["downsample"], x)
        return F.relu(out + identity)


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 data_format="NCHW"):
        super().__init__()
        self.conv1 = conv1x1(inplanes, planes, data_format=data_format)
        self.bn1 = _bn(planes, data_format)
        self.conv2 = conv3x3(planes, planes, stride, data_format)
        self.bn2 = _bn(planes, data_format)
        self.conv3 = conv1x1(planes, planes * self.expansion,
                             data_format=data_format)
        self.bn3 = _bn(planes * self.expansion, data_format)
        self.downsample = downsample

    def forward(self, p, x):
        identity = x
        out = F.relu(self.bn1(p["bn1"], self.conv1(p["conv1"], x)))
        out = F.relu(self.bn2(p["bn2"], self.conv2(p["conv2"], out)))
        out = self.bn3(p["bn3"], self.conv3(p["conv3"], out))
        if self.downsample is not None:
            identity = self.downsample(p["downsample"], x)
        return F.relu(out + identity)


class ResNet(nn.Module):
    """``channels_last=True`` runs every internal activation in NHWC —
    the layout whose channel dim sits on the TPU's 128-lane minor axis —
    while keeping the public contract unchanged: inputs are accepted in
    torch's NCHW (transposed once at entry) and the param tree (OIHW
    conv weights, (C,) batch-norm params) is identical in both modes, so
    checkpoints, amp casting, and optimizer state are layout-agnostic.

    ``input_format="NHWC"`` (requires ``channels_last=True``) declares
    that callers feed NHWC batches — e.g. a
    ``DataLoader(data_format="NHWC")`` — so even the entry transpose
    disappears and the pipeline is transpose-free end to end.

    ``stem="space_to_depth"`` replaces the 7x7/s2 cin=3 stem conv with
    the MLPerf-TPU-style exact rewrite: a 2x2 space-to-depth on the
    input (3 -> 12 channels, 224 -> 112 spatial) followed by a 4x4
    stride-1 conv.  Identical function (see ``stem_weight_to_s2d`` for
    the exact kernel embedding; parity pinned in
    tests/test_models.py), but the conv reads a dense stride-1 window
    instead of a strided gather over a 3-channel input — the MXU-
    friendliest form of the one conv in the network whose contraction
    dim (cin*kh*kw) XLA cannot tile cleanly.  Adoption for the bench
    headline is measurement-gated like the NHWC/scan decisions
    (docs/benchmarks.md).
    """

    def __init__(self, block: Type, layers: List[int],
                 num_classes: int = 1000, channels_last: bool = False,
                 input_format: str = "NCHW", stem: str = "conv7"):
        super().__init__()
        if input_format not in ("NCHW", "NHWC"):
            raise ValueError(f"input_format must be NCHW or NHWC, "
                             f"got {input_format!r}")
        if input_format == "NHWC" and not channels_last:
            raise ValueError("input_format='NHWC' requires "
                             "channels_last=True")
        if stem not in ("conv7", "space_to_depth"):
            raise ValueError(f"stem must be 'conv7' or 'space_to_depth', "
                             f"got {stem!r}")
        self.inplanes = 64
        self.channels_last = channels_last
        self.input_format = input_format
        self.stem = stem
        df = self.data_format = "NHWC" if channels_last else "NCHW"
        if stem == "space_to_depth":
            # out(i) needs s2d rows i-2..i+1  (u = 2*pk + a - 1, see
            # stem_weight_to_s2d) -> asymmetric pad (lo 2, hi 1)
            self.conv1 = nn.Conv2d(12, 64, 4, stride=1,
                                   padding=((2, 1), (2, 1)), bias=False,
                                   data_format=df)
        else:
            self.conv1 = nn.Conv2d(3, 64, 7, stride=2, padding=3,
                                   bias=False, data_format=df)
        self.bn1 = _bn(64, df)
        self.maxpool = nn.MaxPool2d(3, stride=2, padding=1, data_format=df)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        self.avgpool = nn.AdaptiveAvgPool2d(1, data_format=df)
        self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        df = self.data_format
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential([
                conv1x1(self.inplanes, planes * block.expansion, stride,
                        data_format=df),
                _bn(planes * block.expansion, df)])
        layers = [block(self.inplanes, planes, stride, downsample,
                        data_format=df)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, data_format=df))
        return nn.Sequential(layers)

    def forward(self, p, x):
        if self.channels_last and self.input_format == "NCHW":
            x = jnp.transpose(x, (0, 2, 3, 1))
        if self.stem == "space_to_depth":
            x = F.space_to_depth(x, 2, self.data_format)
        x = F.relu(self.bn1(p["bn1"], self.conv1(p["conv1"], x)))
        x = self.maxpool({}, x)
        x = self.layer1(p["layer1"], x)
        x = self.layer2(p["layer2"], x)
        x = self.layer3(p["layer3"], x)
        x = self.layer4(p["layer4"], x)
        x = self.avgpool({}, x)
        x = x.reshape(x.shape[0], -1)
        return self.fc(p["fc"], x)


def stem_weight_to_s2d(w7: jnp.ndarray) -> jnp.ndarray:
    """Exactly embed a (64, 3, 7, 7) OIHW stem-conv weight into the
    (64, 12, 4, 4) weight of the space-to-depth stem.

    Derivation: the original output is ``sum_u w7[u] * x[2i + u - 3]``
    (stride 2, pad 3).  After 2x2 space-to-depth, position ``i + pk - 2``
    of the padded s2d input holds rows ``2i + 2*pk - 4 + a`` of x, so
    matching terms gives ``u = 2*pk + a - 1`` (same for v/qk/bb);
    ``u = -1`` (pk=0, a=0) falls outside the 7-tap kernel and stays
    zero — 147 of the 192 slots are populated, the rest pad the
    contraction to a dense multiple of 8.  The s2d channel index is
    ``a*(2*C) + bb*C + c``, matching ``F.space_to_depth``."""
    O, C, KH, KW = w7.shape
    if (KH, KW) != (7, 7):
        raise ValueError(f"expected a 7x7 stem kernel, got {(KH, KW)}")
    w4 = jnp.zeros((O, 4 * C, 4, 4), w7.dtype)
    for a in range(2):
        for bb in range(2):
            for pk in range(4):
                u = 2 * pk + a - 1
                if not 0 <= u < 7:
                    continue
                for qk in range(4):
                    v = 2 * qk + bb - 1
                    if not 0 <= v < 7:
                        continue
                    cidx = a * (2 * C) + bb * C
                    w4 = w4.at[:, cidx:cidx + C, pk, qk].set(
                        w7[:, :, u, v])
    return w4


def convert_stem_to_s2d(params):
    """Param-tree converter: a checkpoint trained with the conv7 stem
    loads into a ``stem="space_to_depth"`` model with identical
    function.  Only ``conv1/weight`` changes shape; BN and every later
    layer are untouched (arrays shared, the two mutated dict levels
    copied)."""
    params = dict(params)
    params["conv1"] = dict(params["conv1"])
    params["conv1"]["weight"] = stem_weight_to_s2d(
        params["conv1"]["weight"])
    return params


def resnet18(num_classes=1000, channels_last=False, input_format="NCHW",
             stem="conv7"):
    return ResNet(BasicBlock, [2, 2, 2, 2], num_classes, channels_last,
                  input_format, stem)


def resnet34(num_classes=1000, channels_last=False, input_format="NCHW",
             stem="conv7"):
    return ResNet(BasicBlock, [3, 4, 6, 3], num_classes, channels_last,
                  input_format, stem)


def resnet50(num_classes=1000, channels_last=False, input_format="NCHW",
             stem="conv7"):
    return ResNet(Bottleneck, [3, 4, 6, 3], num_classes, channels_last,
                  input_format, stem)


def resnet101(num_classes=1000, channels_last=False, input_format="NCHW",
              stem="conv7"):
    return ResNet(Bottleneck, [3, 4, 23, 3], num_classes, channels_last,
                  input_format, stem)


def resnet152(num_classes=1000, channels_last=False, input_format="NCHW",
              stem="conv7"):
    return ResNet(Bottleneck, [3, 8, 36, 3], num_classes, channels_last,
                  input_format, stem)
