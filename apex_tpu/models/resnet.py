"""ResNet family for the imagenet example and benchmarks.

The reference's examples/tests train torchvision ResNet-50
(examples/imagenet/main_amp.py:150, tests/L1/common/main_amp.py); apex_tpu
ships its own definition on apex_tpu.nn so amp's param casting, SyncBatchNorm
conversion, and the policy-aware conv/linear ops all apply.  Structure
matches torchvision's v1 ResNet (stride-2 in the bottleneck's 3x3, like
the torchvision the reference era used).
"""

from __future__ import annotations

from typing import List, Optional, Type

import jax.numpy as jnp

from .. import nn
from ..nn import functional as F

__all__ = ["ResNet", "BasicBlock", "Bottleneck", "resnet18", "resnet34",
           "resnet50", "resnet101", "resnet152"]


def conv3x3(cin, cout, stride=1, data_format="NCHW"):
    return nn.Conv2d(cin, cout, 3, stride=stride, padding=1, bias=False,
                     data_format=data_format)


def conv1x1(cin, cout, stride=1, data_format="NCHW"):
    return nn.Conv2d(cin, cout, 1, stride=stride, bias=False,
                     data_format=data_format)


def _bn(planes, data_format):
    from ..nn.functional import _check_data_format
    _check_data_format(data_format)
    return nn.BatchNorm2d(
        planes, channel_axis=(1 if data_format == "NCHW" else -1))


class BasicBlock(nn.Module):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 data_format="NCHW"):
        super().__init__()
        self.conv1 = conv3x3(inplanes, planes, stride, data_format)
        self.bn1 = _bn(planes, data_format)
        self.conv2 = conv3x3(planes, planes, data_format=data_format)
        self.bn2 = _bn(planes, data_format)
        self.downsample = downsample

    def forward(self, p, x):
        identity = x
        out = F.relu(self.bn1(p["bn1"], self.conv1(p["conv1"], x)))
        out = self.bn2(p["bn2"], self.conv2(p["conv2"], out))
        if self.downsample is not None:
            identity = self.downsample(p["downsample"], x)
        return F.relu(out + identity)


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 data_format="NCHW"):
        super().__init__()
        self.conv1 = conv1x1(inplanes, planes, data_format=data_format)
        self.bn1 = _bn(planes, data_format)
        self.conv2 = conv3x3(planes, planes, stride, data_format)
        self.bn2 = _bn(planes, data_format)
        self.conv3 = conv1x1(planes, planes * self.expansion,
                             data_format=data_format)
        self.bn3 = _bn(planes * self.expansion, data_format)
        self.downsample = downsample

    def forward(self, p, x):
        identity = x
        out = F.relu(self.bn1(p["bn1"], self.conv1(p["conv1"], x)))
        out = F.relu(self.bn2(p["bn2"], self.conv2(p["conv2"], out)))
        out = self.bn3(p["bn3"], self.conv3(p["conv3"], out))
        if self.downsample is not None:
            identity = self.downsample(p["downsample"], x)
        return F.relu(out + identity)


class ResNet(nn.Module):
    """``channels_last=True`` runs every internal activation in NHWC —
    the layout whose channel dim sits on the TPU's 128-lane minor axis —
    while keeping the public contract unchanged: inputs are accepted in
    torch's NCHW (transposed once at entry) and the param tree (OIHW
    conv weights, (C,) batch-norm params) is identical in both modes, so
    checkpoints, amp casting, and optimizer state are layout-agnostic.

    ``input_format="NHWC"`` (requires ``channels_last=True``) declares
    that callers feed NHWC batches — e.g. a
    ``DataLoader(data_format="NHWC")`` — so even the entry transpose
    disappears and the pipeline is transpose-free end to end.
    """

    def __init__(self, block: Type, layers: List[int],
                 num_classes: int = 1000, channels_last: bool = False,
                 input_format: str = "NCHW"):
        super().__init__()
        if input_format not in ("NCHW", "NHWC"):
            raise ValueError(f"input_format must be NCHW or NHWC, "
                             f"got {input_format!r}")
        if input_format == "NHWC" and not channels_last:
            raise ValueError("input_format='NHWC' requires "
                             "channels_last=True")
        self.inplanes = 64
        self.channels_last = channels_last
        self.input_format = input_format
        df = self.data_format = "NHWC" if channels_last else "NCHW"
        self.conv1 = nn.Conv2d(3, 64, 7, stride=2, padding=3, bias=False,
                               data_format=df)
        self.bn1 = _bn(64, df)
        self.maxpool = nn.MaxPool2d(3, stride=2, padding=1, data_format=df)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        self.avgpool = nn.AdaptiveAvgPool2d(1, data_format=df)
        self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        df = self.data_format
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential([
                conv1x1(self.inplanes, planes * block.expansion, stride,
                        data_format=df),
                _bn(planes * block.expansion, df)])
        layers = [block(self.inplanes, planes, stride, downsample,
                        data_format=df)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, data_format=df))
        return nn.Sequential(layers)

    def forward(self, p, x):
        if self.channels_last and self.input_format == "NCHW":
            x = jnp.transpose(x, (0, 2, 3, 1))
        x = F.relu(self.bn1(p["bn1"], self.conv1(p["conv1"], x)))
        x = self.maxpool({}, x)
        x = self.layer1(p["layer1"], x)
        x = self.layer2(p["layer2"], x)
        x = self.layer3(p["layer3"], x)
        x = self.layer4(p["layer4"], x)
        x = self.avgpool({}, x)
        x = x.reshape(x.shape[0], -1)
        return self.fc(p["fc"], x)


def resnet18(num_classes=1000, channels_last=False, input_format="NCHW"):
    return ResNet(BasicBlock, [2, 2, 2, 2], num_classes, channels_last,
                  input_format)


def resnet34(num_classes=1000, channels_last=False, input_format="NCHW"):
    return ResNet(BasicBlock, [3, 4, 6, 3], num_classes, channels_last,
                  input_format)


def resnet50(num_classes=1000, channels_last=False, input_format="NCHW"):
    return ResNet(Bottleneck, [3, 4, 6, 3], num_classes, channels_last,
                  input_format)


def resnet101(num_classes=1000, channels_last=False, input_format="NCHW"):
    return ResNet(Bottleneck, [3, 4, 23, 3], num_classes, channels_last,
                  input_format)


def resnet152(num_classes=1000, channels_last=False, input_format="NCHW"):
    return ResNet(Bottleneck, [3, 8, 36, 3], num_classes, channels_last,
                  input_format)
