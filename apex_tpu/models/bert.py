"""BERT encoder for the FusedLayerNorm/FusedAdam/FusedLAMB benchmark
configs (BASELINE.md configs #4-5: BERT-base fine-tune, BERT-large
large-batch pretrain).

Built on apex_tpu primitives end-to-end: FusedLayerNorm
(apex_tpu.normalization), policy-aware matmuls (amp O1/O2 apply), and the
MultiheadAttention core from apex_tpu.transformer.  Sequence-parallel
long-context variants swap the attention core for
transformer.ring_attention over an 'sp' mesh axis.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..normalization import FusedLayerNorm
from ..parallel.sync_batchnorm import _axis_in_scope as _sp_in_scope
from ..transformer.attention import dot_product_attention

__all__ = ["BertConfig", "BertModel", "BertForPretraining", "bert_base",
           "bert_large"]


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, max_position_embeddings=512,
                 type_vocab_size=2, hidden_dropout_prob=0.1,
                 attention_probs_dropout_prob=0.1, layer_norm_eps=1e-12,
                 tp_axis=None, hidden_act="gelu_tanh", sp_axis=None,
                 head_chunk=8192):
        # head_chunk: vocab chunk size for the fused MLM-head loss
        # (nn.fused_xent — the (B*T, V) logits are never materialized);
        # None/0 restores the dense logits + fp32 log_softmax path.
        # Ignored under tp_axis (loss() routes to the vocab-parallel
        # cross-entropy; tp+sp combined is rejected below).
        self.head_chunk = head_chunk
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.layer_norm_eps = layer_norm_eps
        # "gelu_tanh" (the TPU-friendly default) or "gelu_exact" (erf —
        # HuggingFace BERT's default, for checkpoint-parity use)
        if hidden_act not in ("gelu_tanh", "gelu_exact"):
            raise ValueError(f"hidden_act must be 'gelu_tanh' or "
                             f"'gelu_exact', got {hidden_act!r}")
        self.hidden_act = hidden_act
        # tensor-parallel mesh axis: when set, attention/MLP/vocab
        # embedding/MLM head shard over it (Megatron layout, beyond the
        # reference) — jit with shard_map and
        # parallel.tensor_parallel.partition_specs(model)
        self.tp_axis = tp_axis
        # sequence parallelism: tokens shard over this axis,
        # bidirectional ring attention (padding masks ride the ring's
        # rotating kv_mask); max_position_embeddings bounds the GLOBAL
        # length
        self.sp_axis = sp_axis
        if tp_axis is not None and sp_axis is not None:
            raise NotImplementedError(
                "combined tp+sp BERT is not wired; pick one")


def bert_base():
    return BertConfig()


def bert_large():
    return BertConfig(hidden_size=1024, num_hidden_layers=24,
                      num_attention_heads=16, intermediate_size=4096)


class BertSelfAttention(nn.Module):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.num_heads = cfg.num_attention_heads
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.attention_probs_dropout_prob = cfg.attention_probs_dropout_prob
        self.sp = cfg.sp_axis
        self.tp = cfg.tp_axis is not None
        if self.tp:
            from ..parallel.tensor_parallel import ParallelSelfAttention
            # head-sharded q/k/v + row-parallel out (hidden dropout
            # stays out here to keep BERT's placement: after out-proj)
            self.core = ParallelSelfAttention(
                cfg.hidden_size, cfg.num_attention_heads, dropout=0.0,
                attn_dropout=cfg.attention_probs_dropout_prob,
                axis_name=cfg.tp_axis)
        else:
            self.qkv = nn.Linear(cfg.hidden_size, 3 * cfg.hidden_size)
            self.out = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, p, x, mask=None, kv_mask=None):
        B, T, E = x.shape
        if self.tp:
            return self.drop(p.get("drop", {}), self.core(p["core"], x,
                                                          mask))
        qkv = self.qkv(p["qkv"], x).reshape(B, T, 3, self.num_heads,
                                            self.head_dim)
        q, k, v = (jnp.moveaxis(qkv[:, :, i], 2, 1) for i in range(3))
        if self.sp is not None and _sp_in_scope(self.sp):
            if mask is not None:
                raise ValueError(
                    "dense `mask` is ignored under sequence parallelism"
                    " — pass the (B, T_local) validity slice as kv_mask")
            from ..transformer.ring_attention import ring_attention
            from ..nn.module import current_context
            actx = current_context()
            rng = None
            if (self.attention_probs_dropout_prob > 0.0
                    and actx is not None and actx.train):
                rng = actx.make_rng()
            ctx = ring_attention(
                q, k, v, axis_name=self.sp, causal=False,
                kv_mask=kv_mask,
                dropout_rate=(self.attention_probs_dropout_prob
                              if rng is not None else 0.0),
                dropout_rng=rng)
        else:
            ctx = dot_product_attention(
                q, k, v, mask,
                dropout_rate=self.attention_probs_dropout_prob)
        ctx = jnp.moveaxis(ctx, 1, 2).reshape(B, T, E)
        return self.drop(p.get("drop", {}), self.out(p["out"], ctx))


class BertLayer(nn.Module):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.attention = BertSelfAttention(cfg)
        self.attention_ln = FusedLayerNorm(cfg.hidden_size,
                                           eps=cfg.layer_norm_eps)
        self.tp = cfg.tp_axis is not None
        if self.tp:
            from ..parallel.tensor_parallel import ParallelMLP
            # column(intermediate) -> gelu -> row(hidden): one psum;
            # the activation honors hidden_act (checkpoint parity)
            self.mlp = ParallelMLP(cfg.hidden_size, cfg.intermediate_size,
                                   activation=("gelu_exact"
                                               if cfg.hidden_act
                                               == "gelu_exact"
                                               else "gelu"),
                                   axis_name=cfg.tp_axis)
        else:
            self.intermediate = nn.Linear(cfg.hidden_size,
                                          cfg.intermediate_size)
            self.output = nn.Linear(cfg.intermediate_size, cfg.hidden_size)
        self.output_ln = FusedLayerNorm(cfg.hidden_size,
                                        eps=cfg.layer_norm_eps)
        self.drop = nn.Dropout(cfg.hidden_dropout_prob)
        self.gelu_approx = cfg.hidden_act != "gelu_exact"

    def forward(self, p, x, mask=None, kv_mask=None):
        a = self.attention(p["attention"], x, mask, kv_mask=kv_mask)
        x = self.attention_ln(p["attention_ln"], x + a)
        if self.tp:
            h = self.drop(p.get("drop", {}), self.mlp(p["mlp"], x))
        else:
            h = F.gelu(self.intermediate(p["intermediate"], x),
                       approximate=self.gelu_approx)
            h = self.drop(p.get("drop", {}), self.output(p["output"], h))
        return self.output_ln(p["output_ln"], x + h)


class BertModel(nn.Module):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        if cfg.tp_axis is not None:
            from ..parallel.tensor_parallel import VocabParallelEmbedding
            self.word_embeddings = VocabParallelEmbedding(
                cfg.vocab_size, cfg.hidden_size, axis_name=cfg.tp_axis,
                init_std=0.02)
        else:
            # BERT's initializer_range=0.02
            self.word_embeddings = nn.Embedding(cfg.vocab_size,
                                                cfg.hidden_size,
                                                init_std=0.02)
        self.position_embeddings = nn.Embedding(cfg.max_position_embeddings,
                                                cfg.hidden_size,
                                                init_std=0.02)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size,
                                                  init_std=0.02)
        self.embeddings_ln = FusedLayerNorm(cfg.hidden_size,
                                            eps=cfg.layer_norm_eps)
        self.layer = nn.ModuleList([BertLayer(cfg)
                                    for _ in range(cfg.num_hidden_layers)])
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, p, input_ids, token_type_ids=None,
                attention_mask=None):
        from jax import lax
        B, T = input_ids.shape
        sp = self.cfg.sp_axis
        in_sp = sp is not None and _sp_in_scope(sp)
        if in_sp:
            spn = lax.axis_size(sp)
            if T * spn > self.cfg.max_position_embeddings:
                raise ValueError(
                    f"global sequence {T}x{spn} exceeds "
                    f"max_position_embeddings "
                    f"{self.cfg.max_position_embeddings}")
            pos = lax.axis_index(sp) * T + jnp.arange(T)[None, :]
        else:
            if T > self.cfg.max_position_embeddings:
                # jnp.take would silently clamp out-of-range positions
                raise ValueError(
                    f"sequence length {T} exceeds "
                    f"max_position_embeddings "
                    f"{self.cfg.max_position_embeddings}")
            pos = jnp.arange(T)[None, :]
        emb = self.word_embeddings(p["word_embeddings"], input_ids)
        emb = emb + self.position_embeddings(p["position_embeddings"], pos)
        if token_type_ids is not None:
            emb = emb + self.token_type_embeddings(
                p["token_type_embeddings"], token_type_ids)
        x = self.embeddings_ln(p["embeddings_ln"], emb)
        mask = kv_mask = None
        if attention_mask is not None:
            if in_sp:
                # the (B, T_local) validity slice rides the ring
                # alongside its K/V block
                kv_mask = attention_mask.astype(bool)
            else:
                mask = attention_mask[:, None, None, :].astype(bool)
        for i in range(self.cfg.num_hidden_layers):
            x = self.layer[i](p["layer"][str(i)], x, mask,
                              kv_mask=kv_mask)
        if in_sp:
            # the [CLS] hidden state lives on shard 0: broadcast with a
            # PLAIN psum (one nonzero term).  Deliberately not the
            # identity-backward g-collective: the plain transpose makes
            # the NSP path's encoder grads spn-scaled exactly like the
            # psum'd MLM loss, so ONE convention — pmean grads over the
            # sp axis — is correct for the whole pretraining loss.
            cls = jnp.where(lax.axis_index(sp) == 0, x[:, 0], 0.0)
            cls = lax.psum(cls, sp)
        else:
            cls = x[:, 0]
        pooled = F.tanh(self.pooler(p["pooler"], cls))
        return x, pooled


class BertForPretraining(nn.Module):
    """MLM + NSP heads, the BERT-large pretrain benchmark target."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.bert = BertModel(cfg)
        self.mlm_dense = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.mlm_ln = FusedLayerNorm(cfg.hidden_size, eps=cfg.layer_norm_eps)
        self.nsp = nn.Linear(cfg.hidden_size, 2)

    def forward(self, p, input_ids, token_type_ids=None,
                attention_mask=None):
        h, pooled = self._mlm_hidden(p, input_ids, token_type_ids,
                                     attention_mask)
        # decoder tied to word embeddings (standard BERT); under TP the
        # table leaf is vocab-sharded, so the logits come out sharded on
        # the vocab dim (consume with vocab_parallel_cross_entropy) —
        # the f-collective on h makes its grad sum the blocks
        table = p["bert"]["word_embeddings"]["weight"]
        if self.cfg.tp_axis is not None:
            from ..parallel.tensor_parallel import copy_to_model_parallel
            h = copy_to_model_parallel(h, self.cfg.tp_axis)
        mlm_logits = F.matmul(h, table.T.astype(h.dtype))
        nsp_logits = self.nsp(p["nsp"], pooled)
        return mlm_logits, nsp_logits

    def _mlm_hidden(self, p, input_ids, token_type_ids=None,
                    attention_mask=None):
        """Pre-decoder MLM hidden states (B, T, H) + pooled — shared by
        the logits path and the fused-head loss."""
        seq, pooled = self.bert(p["bert"], input_ids, token_type_ids,
                                attention_mask)
        h = self.mlm_ln(p["mlm_ln"], F.gelu(
            self.mlm_dense(p["mlm_dense"], seq),
            approximate=self.cfg.hidden_act != "gelu_exact"))
        return h, pooled

    def loss(self, p, input_ids, mlm_labels, nsp_labels,
             token_type_ids=None, attention_mask=None, ignore_index=-100):
        if self.cfg.tp_axis is not None:
            mlm_logits, nsp_logits = self(p, input_ids, token_type_ids,
                                          attention_mask)
            from ..parallel.tensor_parallel import \
                vocab_parallel_cross_entropy
            mlm_loss = vocab_parallel_cross_entropy(
                mlm_logits, mlm_labels, axis_name=self.cfg.tp_axis,
                ignore_index=ignore_index)
        else:
            h, pooled = self._mlm_hidden(p, input_ids, token_type_ids,
                                         attention_mask)
            nsp_logits = self.nsp(p["nsp"], pooled)
            valid = mlm_labels != ignore_index
            labels = jnp.where(valid, mlm_labels, 0)
            table = p["bert"]["word_embeddings"]["weight"]
            from ..quantization import QTensor
            if isinstance(table, QTensor):
                # fused_xent slices the table; it needs a real array
                table = table.dequant(h.dtype)
            if self.cfg.head_chunk:
                from ..nn.fused_xent import linear_cross_entropy
                B, T, H = h.shape
                nll = linear_cross_entropy(
                    h.reshape(B * T, H), table, labels.reshape(-1),
                    int(self.cfg.head_chunk)).reshape(B, T)
            else:
                mlm_logits = F.matmul(h, table.T.astype(h.dtype))
                logp = F.log_softmax(mlm_logits.astype(jnp.float32),
                                     axis=-1)
                nll = -jnp.take_along_axis(logp, labels[..., None],
                                           axis=-1)[..., 0]
            sp = self.cfg.sp_axis
            if sp is not None and _sp_in_scope(sp):
                # MLM is per-position: psum the masked sums so every
                # shard returns the global mean.  Grads then follow the
                # same convention as data parallelism — average them
                # over the sp axis (pmean / DDP) before the optimizer.
                from jax import lax
                num = lax.psum(jnp.sum(nll * valid), sp)
                den = lax.psum(jnp.sum(valid.astype(jnp.float32)), sp)
                mlm_loss = num / jnp.maximum(den, 1.0)
            else:
                mlm_loss = jnp.sum(nll * valid) / jnp.maximum(
                    jnp.sum(valid), 1)
        nsp_loss = F.cross_entropy(nsp_logits, nsp_labels)
        return mlm_loss + nsp_loss
