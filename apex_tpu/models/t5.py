"""T5 encoder-decoder (Raffel et al.) — the third architecture
archetype next to BERT (encoder-only) and the GPT/Llama decoders.

Faithful to the HF implementation the converter targets
(``utils.hf_interop.t5_from_hf``; parity pinned in tests/test_t5.py):

- T5's "LayerNorm" is RMS (no mean subtraction, no bias) — reused from
  models/llama.RMSNorm;
- attention is UNSCALED (no 1/sqrt(d_kv)) with a decoupled ``d_kv``;
- a learned relative-position bias (bucketed, 32 buckets / max
  distance 128) lives in layer 0 of each stack and is shared by every
  layer of that stack — bidirectional buckets in the encoder, causal
  in the decoder;
- feed-forward is relu (t5) or gated-gelu (t5 v1.1);
- with tied embeddings the decoder output is rescaled by
  ``d_model**-0.5`` before the LM head (HF quirk, load-bearing).

Decoding follows the repo's fixed-buffer discipline: the encoder runs
once, cross-attention K/V are precomputed per layer, and the decoder
walks its buffer with a (B, H, S, d_kv) self-attention cache —
one compiled program for any prompt/target length.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .. import nn
from ..nn import functional as F
from .llama import RMSNorm

__all__ = ["T5Config", "T5"]


class T5Config:
    def __init__(self, vocab_size=32128, d_model=512, d_kv=64,
                 d_ff=2048, num_layers=6, num_decoder_layers=None,
                 num_heads=8, relative_attention_num_buckets=32,
                 relative_attention_max_distance=128,
                 layer_norm_epsilon=1e-6, dropout_rate=0.1,
                 feed_forward_proj="relu", tie_word_embeddings=True,
                 decoder_start_token_id=0, max_length=512):
        self.vocab_size = vocab_size
        self.d_model = d_model
        self.d_kv = d_kv
        self.d_ff = d_ff
        self.num_layers = num_layers
        self.num_decoder_layers = (num_decoder_layers
                                   if num_decoder_layers is not None
                                   else num_layers)
        self.num_heads = num_heads
        self.relative_attention_num_buckets = \
            relative_attention_num_buckets
        self.relative_attention_max_distance = \
            relative_attention_max_distance
        self.layer_norm_epsilon = layer_norm_epsilon
        self.dropout_rate = dropout_rate
        if feed_forward_proj not in ("relu", "gated-gelu"):
            raise ValueError(f"feed_forward_proj="
                             f"{feed_forward_proj!r} not in "
                             f"('relu', 'gated-gelu')")
        self.feed_forward_proj = feed_forward_proj
        self.tie_word_embeddings = tie_word_embeddings
        self.decoder_start_token_id = decoder_start_token_id
        self.max_length = max_length        # decode buffer bound


def _relative_position_bucket(relative_position, bidirectional,
                              num_buckets, max_distance):
    """HF T5's bucketing, exactly (modeling_t5.py
    _relative_position_bucket): half the buckets for exact small
    offsets, the rest log-spaced out to max_distance."""
    ret = jnp.zeros_like(relative_position)
    n = -relative_position
    if bidirectional:
        num_buckets //= 2
        ret = ret + jnp.where(n < 0, num_buckets, 0)
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_if_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-20)
        / jnp.log(max_distance / max_exact)
        * (num_buckets - max_exact)).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


class T5Attention(nn.Module):
    """Unscaled multi-head attention with decoupled d_kv; layer 0 of a
    stack owns the shared relative-position bias table."""

    def __init__(self, cfg: T5Config, has_bias_table: bool):
        super().__init__()
        self.H = cfg.num_heads
        self.dkv = cfg.d_kv
        inner = self.H * self.dkv
        self.q = nn.Linear(cfg.d_model, inner, bias=False)
        self.k = nn.Linear(cfg.d_model, inner, bias=False)
        self.v = nn.Linear(cfg.d_model, inner, bias=False)
        self.o = nn.Linear(inner, cfg.d_model, bias=False)
        self.has_bias_table = has_bias_table
        self.nbuckets = cfg.relative_attention_num_buckets
        self.maxdist = cfg.relative_attention_max_distance
        if has_bias_table:
            self.relative_attention_bias = nn.Embedding(
                self.nbuckets, self.H)

    def position_bias(self, p, q_pos, k_pos, bidirectional):
        """(1, H, Tq, Tk) additive bias from the layer-0 table."""
        rel = k_pos[None, :] - q_pos[:, None]
        buckets = _relative_position_bucket(
            rel, bidirectional, self.nbuckets, self.maxdist)
        vals = self.relative_attention_bias(
            p["relative_attention_bias"], buckets)      # (Tq, Tk, H)
        return jnp.transpose(vals, (2, 0, 1))[None]

    def _heads(self, x, B, T):
        return jnp.moveaxis(x.reshape(B, T, self.H, self.dkv), 2, 1)

    def forward(self, p, x, kv, mask, position_bias):
        """``kv`` = x for self-attention, encoder states for cross.
        ``mask``: additive fp mask broadcastable to (B, H, Tq, Tk) or
        None; ``position_bias`` likewise (None for cross-attention)."""
        B, Tq, _ = x.shape
        Tk = kv.shape[1]
        q = self._heads(self.q(p["q"], x), B, Tq)
        k = self._heads(self.k(p["k"], kv), B, Tk)
        v = self._heads(self.v(p["v"], kv), B, Tk)
        scores = jnp.einsum("bhqd,bhkd->bhqk",
                            q.astype(jnp.float32),
                            k.astype(jnp.float32))   # NO 1/sqrt(d)
        if position_bias is not None:
            scores = scores + position_bias.astype(jnp.float32)
        if mask is not None:
            scores = scores + mask.astype(jnp.float32)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        ctx = jnp.moveaxis(ctx, 1, 2).reshape(B, Tq, self.H * self.dkv)
        return self.o(p["o"], ctx)


class T5FF(nn.Module):
    def __init__(self, cfg: T5Config):
        super().__init__()
        self.gated = cfg.feed_forward_proj == "gated-gelu"
        if self.gated:
            self.wi_0 = nn.Linear(cfg.d_model, cfg.d_ff, bias=False)
            self.wi_1 = nn.Linear(cfg.d_model, cfg.d_ff, bias=False)
        else:
            self.wi = nn.Linear(cfg.d_model, cfg.d_ff, bias=False)
        self.wo = nn.Linear(cfg.d_ff, cfg.d_model, bias=False)

    def forward(self, p, x):
        if self.gated:
            h = (F.gelu(self.wi_0(p["wi_0"], x))
                 * self.wi_1(p["wi_1"], x))
        else:
            h = F.relu(self.wi(p["wi"], x))
        return self.wo(p["wo"], h)


class T5EncoderBlock(nn.Module):
    def __init__(self, cfg: T5Config, first: bool):
        super().__init__()
        eps = cfg.layer_norm_epsilon
        self.ln_attn = RMSNorm(cfg.d_model, eps)
        self.attn = T5Attention(cfg, has_bias_table=first)
        self.ln_ff = RMSNorm(cfg.d_model, eps)
        self.ff = T5FF(cfg)

    def forward(self, p, x, mask, position_bias):
        x = x + self.attn(p["attn"], self.ln_attn(p["ln_attn"], x),
                          self.ln_attn(p["ln_attn"], x), mask,
                          position_bias)
        return x + self.ff(p["ff"], self.ln_ff(p["ln_ff"], x))


class T5DecoderBlock(nn.Module):
    def __init__(self, cfg: T5Config, first: bool):
        super().__init__()
        eps = cfg.layer_norm_epsilon
        self.ln_self = RMSNorm(cfg.d_model, eps)
        self.self_attn = T5Attention(cfg, has_bias_table=first)
        self.ln_cross = RMSNorm(cfg.d_model, eps)
        self.cross_attn = T5Attention(cfg, has_bias_table=False)
        self.ln_ff = RMSNorm(cfg.d_model, eps)
        self.ff = T5FF(cfg)

    def forward(self, p, x, enc, self_mask, cross_mask, position_bias):
        h = self.ln_self(p["ln_self"], x)
        x = x + self.self_attn(p["self_attn"], h, h, self_mask,
                               position_bias)
        x = x + self.cross_attn(p["cross_attn"],
                                self.ln_cross(p["ln_cross"], x), enc,
                                cross_mask, None)
        return x + self.ff(p["ff"], self.ln_ff(p["ln_ff"], x))


def _neg(mask01):
    """(B, T) 1=keep -> additive (B, 1, 1, T) with -inf-ish holes."""
    return (1.0 - mask01.astype(jnp.float32))[:, None, None, :] * -1e9


class T5(nn.Module):
    def __init__(self, cfg: T5Config):
        super().__init__()
        self.cfg = cfg
        self.shared = nn.Embedding(cfg.vocab_size, cfg.d_model)
        self.enc_blocks = nn.ModuleList(
            [T5EncoderBlock(cfg, i == 0)
             for i in range(cfg.num_layers)])
        self.enc_norm = RMSNorm(cfg.d_model, cfg.layer_norm_epsilon)
        self.dec_blocks = nn.ModuleList(
            [T5DecoderBlock(cfg, i == 0)
             for i in range(cfg.num_decoder_layers)])
        self.dec_norm = RMSNorm(cfg.d_model, cfg.layer_norm_epsilon)
        if not cfg.tie_word_embeddings:
            self.lm_head = nn.Linear(cfg.d_model, cfg.vocab_size,
                                     bias=False)

    # -- encoder -----------------------------------------------------------
    def encode(self, p, input_ids, attention_mask=None):
        B, T = input_ids.shape
        x = self.shared(p["shared"], input_ids)
        mask = (None if attention_mask is None
                else _neg(attention_mask))
        pos = jnp.arange(T)
        bias = self.enc_blocks[0].attn.position_bias(
            p["enc_blocks"]["0"]["attn"], pos, pos, bidirectional=True)
        for i in range(self.cfg.num_layers):
            x = self.enc_blocks[i](p["enc_blocks"][str(i)], x, mask,
                                   bias)
        return self.enc_norm(p["enc_norm"], x)

    # -- decoder (full sequence; training/scoring path) --------------------
    def _decode_hidden_full(self, p, dec_ids, enc, enc_mask):
        B, T = dec_ids.shape
        x = self.shared(p["shared"], dec_ids)
        causal = jnp.where(
            jnp.arange(T)[None, :] <= jnp.arange(T)[:, None],
            0.0, -1e9)[None, None]
        cross = None if enc_mask is None else _neg(enc_mask)
        pos = jnp.arange(T)
        bias = self.dec_blocks[0].self_attn.position_bias(
            p["dec_blocks"]["0"]["self_attn"], pos, pos,
            bidirectional=False)
        for i in range(self.cfg.num_decoder_layers):
            x = self.dec_blocks[i](p["dec_blocks"][str(i)], x, enc,
                                   causal, cross, bias)
        return self.dec_norm(p["dec_norm"], x)

    def _head(self, p, x):
        if self.cfg.tie_word_embeddings:
            # HF quirk: tied head rescales the decoder output
            x = x * jnp.asarray(self.cfg.d_model ** -0.5, x.dtype)
            table = p["shared"]["weight"]
        else:
            table = p["lm_head"]["weight"]
        return F.matmul(x, table.T.astype(x.dtype))

    def forward(self, p, input_ids, decoder_input_ids,
                attention_mask=None):
        enc = self.encode(p, input_ids, attention_mask)
        x = self._decode_hidden_full(p, decoder_input_ids, enc,
                                     attention_mask)
        return self._head(p, x)

    def loss(self, p, input_ids, labels, attention_mask=None,
             ignore_index=-100):
        """Teacher-forced CE: decoder inputs are labels shifted right
        with decoder_start_token_id (HF's _shift_right)."""
        start = jnp.full((labels.shape[0], 1),
                         self.cfg.decoder_start_token_id,
                         labels.dtype)
        safe_in = jnp.where(labels == ignore_index, 0, labels)
        dec_in = jnp.concatenate([start, safe_in[:, :-1]], axis=1)
        logits = self.forward(p, input_ids, dec_in, attention_mask)
        valid = labels != ignore_index
        safe = jnp.where(valid, labels, 0)
        logp = F.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, safe[..., None],
                                   axis=-1)[..., 0]
        return jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1)

    # -- cached greedy generation ------------------------------------------
    def generate(self, p, input_ids, max_new_tokens: int,
                 attention_mask=None):
        """Greedy decode from ``decoder_start_token_id``: encoder runs
        once, cross K/V precompute once per layer, decoder self-attn
        walks a (B, H, S, d_kv) cache.  Returns (B, max_new_tokens)
        generated ids (incl. whatever EOS convention the checkpoint
        uses — trimming is the tokenizer's job)."""
        cfg = self.cfg
        B = input_ids.shape[0]
        S = max_new_tokens
        enc = self.encode(p, input_ids, attention_mask)
        cross_mask = (None if attention_mask is None
                      else _neg(attention_mask))

        cross_kv = []
        for i in range(cfg.num_decoder_layers):
            ca = self.dec_blocks[i].cross_attn
            cp = p["dec_blocks"][str(i)]["cross_attn"]
            Tk = enc.shape[1]
            cross_kv.append((
                ca._heads(ca.k(cp["k"], enc), B, Tk),
                ca._heads(ca.v(cp["v"], enc), B, Tk)))

        cache = [{
            "k": jnp.zeros((B, cfg.num_heads, S, cfg.d_kv), enc.dtype),
            "v": jnp.zeros((B, cfg.num_heads, S, cfg.d_kv), enc.dtype),
        } for _ in range(cfg.num_decoder_layers)]

        bias_p = p["dec_blocks"]["0"]["self_attn"]
        b0 = self.dec_blocks[0].self_attn

        def body(t, carry):
            out, cache = carry
            tok = jnp.where(t == 0,
                            jnp.full((B,), cfg.decoder_start_token_id),
                            out[:, jnp.maximum(t - 1, 0)])
            x = self.shared(p["shared"], tok[:, None])
            # self-attn bias row for query position t over keys 0..S-1
            bias = b0.position_bias(
                bias_p, jnp.full((1,), t), jnp.arange(S),
                bidirectional=False)
            key_mask = jnp.where(jnp.arange(S)[None, None, None, :]
                                 <= t, 0.0, -1e9)
            new_cache = []
            for i in range(cfg.num_decoder_layers):
                blk = self.dec_blocks[i]
                bp = p["dec_blocks"][str(i)]
                h = blk.ln_self(bp["ln_self"], x)
                sa = blk.self_attn
                q = sa._heads(sa.q(bp["self_attn"]["q"], h), B, 1)
                k1 = sa._heads(sa.k(bp["self_attn"]["k"], h), B, 1)
                v1 = sa._heads(sa.v(bp["self_attn"]["v"], h), B, 1)
                ck = lax.dynamic_update_slice_in_dim(
                    cache[i]["k"], k1, t, axis=2)
                cv = lax.dynamic_update_slice_in_dim(
                    cache[i]["v"], v1, t, axis=2)
                new_cache.append({"k": ck, "v": cv})
                scores = jnp.einsum(
                    "bhqd,bhkd->bhqk", q.astype(jnp.float32),
                    ck.astype(jnp.float32)) + bias + key_mask
                probs = jax.nn.softmax(scores, -1).astype(x.dtype)
                ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, cv)
                ctx = jnp.moveaxis(ctx, 1, 2).reshape(
                    B, 1, cfg.num_heads * cfg.d_kv)
                x = x + sa.o(bp["self_attn"]["o"], ctx)
                # cross-attention against the precomputed encoder K/V
                hc = blk.ln_cross(bp["ln_cross"], x)
                ca = blk.cross_attn
                qc = ca._heads(ca.q(bp["cross_attn"]["q"], hc), B, 1)
                ckv, cvv = cross_kv[i]
                cs = jnp.einsum("bhqd,bhkd->bhqk",
                                qc.astype(jnp.float32),
                                ckv.astype(jnp.float32))
                if cross_mask is not None:
                    cs = cs + cross_mask
                cp2 = jax.nn.softmax(cs, -1).astype(x.dtype)
                cctx = jnp.einsum("bhqk,bhkd->bhqd", cp2, cvv)
                cctx = jnp.moveaxis(cctx, 1, 2).reshape(
                    B, 1, cfg.num_heads * cfg.d_kv)
                x = x + ca.o(bp["cross_attn"]["o"], cctx)
                x = x + blk.ff(bp["ff"], blk.ln_ff(bp["ln_ff"], x))
            x = self.dec_norm(p["dec_norm"], x)
            logits = self._head(p, x)[:, 0]
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            out = lax.dynamic_update_slice_in_dim(
                out, nxt[:, None], t, axis=1)
            return out, new_cache

        out = jnp.zeros((B, S), jnp.int32)
        out, _ = lax.fori_loop(0, S, body, (out, cache))
        return out

    # -- slot-granular serving contract (serving.Seq2SeqEngine) ------------
    def init_seq2seq_state(self, slots: int, src_len: int,
                           dec_len: int, dtype=jnp.float32):
        """Per-slot serving state: cross-attention K/V precomputed from
        each slot's encoder pass, a decoder self-attention cache, and
        the per-slot source validity mask.  Keys are str layer indices
        (the cache pytree discipline the decoder-only families use)."""
        cfg = self.cfg
        cross = {str(i): {
            "k": jnp.zeros((slots, cfg.num_heads, src_len, cfg.d_kv),
                           dtype),
            "v": jnp.zeros((slots, cfg.num_heads, src_len, cfg.d_kv),
                           dtype)} for i in range(cfg.num_decoder_layers)}
        dec = {str(i): {
            "k": jnp.zeros((slots, cfg.num_heads, dec_len, cfg.d_kv),
                           dtype),
            "v": jnp.zeros((slots, cfg.num_heads, dec_len, cfg.d_kv),
                           dtype)} for i in range(cfg.num_decoder_layers)}
        return {"cross": cross, "dec": dec,
                "src_mask": jnp.zeros((slots, src_len), jnp.float32)}

    def seed_slot_seq2seq(self, p, state, slot, src_row, n_src):
        """Run the encoder for ONE request (``src_row`` (src_len,),
        valid length ``n_src``) and scatter its cross K/V + source mask
        into ``slot``; the slot's decoder cache rows reset to zero."""
        cfg = self.cfg
        src_len = src_row.shape[0]
        mask01 = (jnp.arange(src_len) < n_src).astype(jnp.float32)
        enc = self.encode(p, src_row[None, :], mask01[None, :])
        state = {"cross": dict(state["cross"]),
                 "dec": dict(state["dec"]),
                 "src_mask": state["src_mask"].at[slot].set(mask01)}
        for i in range(cfg.num_decoder_layers):
            li = str(i)
            ca = self.dec_blocks[i].cross_attn
            cp = p["dec_blocks"][li]["cross_attn"]
            k = ca._heads(ca.k(cp["k"], enc), 1, src_len)
            v = ca._heads(ca.v(cp["v"], enc), 1, src_len)
            layer = state["cross"][li]
            state["cross"][li] = {
                "k": lax.dynamic_update_index_in_dim(
                    layer["k"], k[0].astype(layer["k"].dtype), slot, 0),
                "v": lax.dynamic_update_index_in_dim(
                    layer["v"], v[0].astype(layer["v"].dtype), slot, 0)}
            dlayer = state["dec"][li]
            state["dec"][li] = {
                "k": lax.dynamic_update_index_in_dim(
                    dlayer["k"], jnp.zeros_like(dlayer["k"][0]), slot,
                    0),
                "v": lax.dynamic_update_index_in_dim(
                    dlayer["v"], jnp.zeros_like(dlayer["v"][0]), slot,
                    0)}
        return state

    def _row_bias(self, p, pos, dec_len):
        """Per-row decoder self-attn bias: query at ``pos[b]`` over
        keys 0..dec_len-1 -> (B, H, 1, dec_len).  position_bias's
        (1, H, Tq, Tk) shape assumes a shared query position; serving
        rows sit at different positions."""
        sa = self.dec_blocks[0].self_attn
        bp = p["dec_blocks"]["0"]["self_attn"]
        rel = jnp.arange(dec_len)[None, :] - pos[:, None]    # (B, S)
        buckets = _relative_position_bucket(
            rel, False, sa.nbuckets, sa.maxdist)
        vals = sa.relative_attention_bias(
            bp["relative_attention_bias"], buckets)          # (B, S, H)
        return jnp.transpose(vals, (0, 2, 1))[:, :, None, :]

    def decode_step_rows(self, p, tok, pos, state):
        """One greedy-servable decoder step at PER-ROW positions:
        ``tok`` (B,) feeds position ``pos[b]`` of each slot; returns
        (logits (B, V), new state).  Mirrors ``generate``'s inner body
        but row-batched — the Seq2SeqEngine tick."""
        cfg = self.cfg
        B = tok.shape[0]
        dec_len = state["dec"]["0"]["k"].shape[2]
        x = self.shared(p["shared"], tok[:, None])
        bias = self._row_bias(p, pos, dec_len)
        key_mask = jnp.where(
            jnp.arange(dec_len)[None, None, None, :]
            <= pos[:, None, None, None], 0.0, -1e9)
        cross_mask = ((1.0 - state["src_mask"])
                      * -1e9)[:, None, None, :]
        new_state = {"cross": state["cross"], "dec": {},
                     "src_mask": state["src_mask"]}

        def put_row(buf, val):
            # (B, H, 1, d) written at per-row positions
            return jax.vmap(
                lambda b, vv, p0: lax.dynamic_update_slice(
                    b, vv.astype(b.dtype), (0, p0, 0)))(buf, val, pos)

        for i in range(cfg.num_decoder_layers):
            li = str(i)
            blk = self.dec_blocks[i]
            bp = p["dec_blocks"][li]
            h = blk.ln_self(bp["ln_self"], x)
            sa = blk.self_attn
            q = sa._heads(sa.q(bp["self_attn"]["q"], h), B, 1)
            k1 = sa._heads(sa.k(bp["self_attn"]["k"], h), B, 1)
            v1 = sa._heads(sa.v(bp["self_attn"]["v"], h), B, 1)
            layer = state["dec"][li]
            ck = put_row(layer["k"], k1)
            cv = put_row(layer["v"], v1)
            new_state["dec"][li] = {"k": ck, "v": cv}
            scores = jnp.einsum("bhqd,bhkd->bhqk",
                                q.astype(jnp.float32),
                                ck.astype(jnp.float32)) \
                + bias.astype(jnp.float32) + key_mask
            probs = jax.nn.softmax(scores, -1).astype(x.dtype)
            ctx = jnp.einsum("bhqk,bhkd->bhqd", probs,
                             cv.astype(probs.dtype))
            ctx = jnp.moveaxis(ctx, 1, 2).reshape(
                B, 1, cfg.num_heads * cfg.d_kv)
            x = x + sa.o(bp["self_attn"]["o"], ctx)
            hc = blk.ln_cross(bp["ln_cross"], x)
            ca = blk.cross_attn
            qc = ca._heads(ca.q(bp["cross_attn"]["q"], hc), B, 1)
            ckv = state["cross"][li]["k"]
            cvv = state["cross"][li]["v"]
            cs = jnp.einsum("bhqd,bhkd->bhqk", qc.astype(jnp.float32),
                            ckv.astype(jnp.float32)) + cross_mask
            cp2 = jax.nn.softmax(cs, -1).astype(x.dtype)
            cctx = jnp.einsum("bhqk,bhkd->bhqd", cp2,
                              cvv.astype(cp2.dtype))
            cctx = jnp.moveaxis(cctx, 1, 2).reshape(
                B, 1, cfg.num_heads * cfg.d_kv)
            x = x + ca.o(bp["cross_attn"]["o"], cctx)
            x = x + blk.ff(bp["ff"], blk.ln_ff(bp["ln_ff"], x))
        x = self.dec_norm(p["dec_norm"], x)
        return self._head(p, x)[:, 0], new_state
