"""Sampling strategies for the generate paths: temperature, top-k,
nucleus (top-p) — jit-safe (static shapes, no data-dependent control
flow), shared by GPT / Llama / Mixtral ``generate*``.

The reference toolkit has no generation story (2019, pre-LLM serving);
this follows the de-facto HF ``generate`` semantics so converted
checkpoints sample comparably: logits are scaled by ``1/temperature``
FIRST, then top-k keeps the k best, then top-p keeps the smallest
prefix of the sorted distribution whose mass reaches ``top_p``, then
min-p drops tokens under ``min_p * max_prob`` of the filtered
distribution (the best token always survives every filter).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["filter_logits", "sample_token"]


def filter_logits(logits: jax.Array, top_k: Optional[int] = None,
                  top_p: Optional[float] = None,
                  min_p: Optional[float] = None) -> jax.Array:
    """Mask (-inf) every vocab entry of ``logits (..., V)`` that falls
    outside the top-k set, the top-p nucleus, and/or below ``min_p``
    (tokens whose probability is under ``min_p * max_prob`` — the
    scale-relative cutoff; the best token always survives)."""
    if top_k is not None:
        if top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        kth = lax.top_k(logits, min(top_k, logits.shape[-1]))[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None:
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        sl = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
        probs = jax.nn.softmax(sl, axis=-1)
        # keep while the mass BEFORE this token is < top_p: the argmax
        # always survives, and the kept prefix is the smallest one
        # reaching top_p
        keep = (jnp.cumsum(probs, axis=-1) - probs) < top_p
        thresh = jnp.min(jnp.where(keep, sl, jnp.inf), axis=-1,
                         keepdims=True)
        logits = jnp.where(logits < thresh, -jnp.inf, logits)
    # min_p runs LAST, matching HF's warper order (temperature, top_k,
    # top_p, min_p): its softmax sees the already-filtered distribution,
    # so combined-filter sampling keeps the same token set HF would.
    if min_p is not None:
        if not 0.0 < min_p <= 1.0:
            raise ValueError(f"min_p must be in (0, 1], got {min_p}")
        probs = jax.nn.softmax(logits, axis=-1)
        cut = min_p * jnp.max(probs, axis=-1, keepdims=True)
        logits = jnp.where(probs < cut, -jnp.inf, logits)
    return logits


def sample_token(key: jax.Array, logits: jax.Array,
                 temperature: float = 1.0,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 min_p: Optional[float] = None) -> jax.Array:
    """One token id per row of ``logits (..., V)``.

    ``temperature == 0`` (a static python float) is greedy argmax —
    ``key`` may be anything; otherwise scaled + filtered categorical.
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    scaled = logits.astype(jnp.float32) / temperature
    return jax.random.categorical(
        key, filter_logits(scaled, top_k=top_k, top_p=top_p,
                           min_p=min_p))


def apply_repetition_penalty(logits: jax.Array, ids: jax.Array,
                             cur_len: jax.Array,
                             penalty: float) -> jax.Array:
    """HF-semantics repetition penalty: for every token already
    present in ``ids[b, :cur_len[b]]``, positive logits divide by
    ``penalty`` and negative logits multiply by it.  Static shapes:
    presence is a scatter over the vocab."""
    if penalty == 1.0:
        return logits
    B, S = ids.shape
    V = logits.shape[-1]
    seen_mask = jnp.arange(S)[None, :] < cur_len[:, None]
    presence = jnp.zeros((B, V), bool).at[
        jnp.arange(B)[:, None], ids].max(seen_mask)
    penalized = jnp.where(logits > 0, logits / penalty,
                          logits * penalty)
    return jnp.where(presence, penalized, logits)
