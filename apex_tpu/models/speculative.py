"""Speculative decoding: draft-model proposal + target-model
verification, losslessly reproducing the target's greedy output.

The reference toolkit predates LLM serving; this implements the greedy
variant of Leviathan et al. (2023): a cheap draft model proposes
``gamma`` tokens autoregressively, the target scores the whole
proposed prefix in ONE forward, and the longest prefix the target
agrees with is accepted plus one corrected token — so every outer
iteration advances by 1..gamma+1 tokens while the output is EXACTLY
the target's own greedy continuation (pinned against
``generate_cached`` in tests/test_speculative.py).

jit-shape discipline matches ``GPT.generate``: fixed (B, S) buffer,
per-row lengths, one compiled program for any prompt length; the outer
``while_loop`` terminates because every active row advances at least
one token per iteration.  Draft and target only need the shared
``model(params, ids, attention_mask) -> (B, S, V)`` contract, so any
family pairing works (GPT draft for a Llama target, etc.) as long as
the tokenizer/vocab agree.

Scope note: both models run full-prefix forwards per iteration (no KV
cache reuse across iterations).  That keeps the verification exact and
the program simple; the target-side win is running S-position scoring
once per 1..gamma+1 accepted tokens instead of once per token.  A
chunked cached-verify variant is the natural follow-up and would slot
behind the same API.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["generate_speculative"]


def generate_speculative(target, target_params, draft, draft_params,
                         input_ids, prompt_len, max_new_tokens: int,
                         gamma: int = 4):
    """Greedy speculative decoding.  Returns ``(ids, final_len)`` with
    the same contract as ``GPT.generate``: rows are left-aligned in the
    (B, S) buffer, generation stops at ``prompt_len + max_new_tokens``
    or the buffer end, positions past ``final_len`` keep the input
    buffer's content."""
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    B, S = input_ids.shape
    orig = jnp.asarray(input_ids)
    prompt_len = jnp.broadcast_to(jnp.asarray(prompt_len), (B,))
    final_len = jnp.minimum(prompt_len + max_new_tokens, S)
    pgrid = jnp.arange(S)[None, :]

    def next_token(model, params, ids, cur_len):
        """Greedy next token per row, reading position cur_len-1."""
        amask = (pgrid < cur_len[:, None]).astype(jnp.int32)
        logits = model(params, ids, amask)
        idx = jnp.clip(cur_len - 1, 0, S - 1)
        last = jnp.take_along_axis(
            logits, idx[:, None, None], axis=1)[:, 0]
        return jnp.argmax(last, axis=-1).astype(ids.dtype)

    def write_at(ids, pos, tok, can):
        return jax.vmap(
            lambda row, p, t, c: row.at[p].set(
                jnp.where(c, t, row[p])))(
            ids, jnp.minimum(pos, S - 1), tok, can)

    def cond(carry):
        _, cur_len = carry
        return jnp.any(cur_len < final_len)

    def body(carry):
        ids, cur_len = carry
        active = cur_len < final_len

        # 1. draft proposes gamma greedy tokens (rows stop at the
        # window edge; inactive rows propose nothing)
        ids_d, len_d = ids, cur_len
        dtoks = []
        for _ in range(gamma):
            t = next_token(draft, draft_params, ids_d, len_d)
            can = len_d < final_len
            ids_d = write_at(ids_d, len_d, t, can)
            dtoks.append(t)
            len_d = jnp.where(can, len_d + 1, len_d)
        dtoks = jnp.stack(dtoks, axis=1)                   # (B, gamma)

        # 2. target scores the whole proposed prefix in one forward
        amask = (pgrid < len_d[:, None]).astype(jnp.int32)
        tgt_next = jnp.argmax(
            target(target_params, ids_d, amask), axis=-1)  # (B, S)

        # 3. longest agreeing prefix; proposal j is only eligible if
        # the correction slot after it still fits the window
        offs = jnp.arange(gamma)[None, :]
        vpos = jnp.clip(cur_len[:, None] - 1 + offs, 0, S - 1)
        agree = dtoks == jnp.take_along_axis(tgt_next, vpos, axis=1)
        eligible = (cur_len[:, None] + offs) < (final_len[:, None] - 1)
        n_acc = jnp.sum(jnp.cumprod(agree & eligible, axis=1), axis=1)

        # 4. the corrected token: target's choice after the accepted
        # prefix (for a fully-agreeing draft this is the bonus token)
        cpos = jnp.clip(cur_len - 1 + n_acc, 0, S - 1)
        ctok = jnp.take_along_axis(tgt_next, cpos[:, None],
                                   axis=1)[:, 0].astype(ids.dtype)

        # 5. rebuild: accepted draft zone from ids_d, correction at
        # cur_len + n_acc, everything past it restored from the
        # original buffer (rejected proposals leave no trace)
        corr_at = cur_len + n_acc
        keep = pgrid < corr_at[:, None]
        is_corr = (pgrid == corr_at[:, None]) & active[:, None]
        ids_new = jnp.where(keep, ids_d,
                            jnp.where(is_corr, ctok[:, None], orig))
        new_len = jnp.where(active,
                            jnp.minimum(corr_at + 1, final_len),
                            cur_len)
        return ids_new, new_len

    ids, cur_len = lax.while_loop(cond, body, (orig, prompt_len))
    return ids, final_len
