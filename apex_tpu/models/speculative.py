"""Speculative decoding: draft-model proposal + target-model
verification, losslessly reproducing the target's greedy output.

The reference toolkit predates LLM serving; this implements the greedy
variant of Leviathan et al. (2023): a cheap draft model proposes
``gamma`` tokens autoregressively, the target scores the whole
proposed prefix in ONE forward, and the longest prefix the target
agrees with is accepted plus one corrected token — so every outer
iteration advances by 1..gamma+1 tokens while the output is EXACTLY
the target's own greedy continuation (pinned against
``generate_cached`` in tests/test_speculative.py).

jit-shape discipline matches ``GPT.generate``: fixed (B, S) buffer,
per-row lengths, one compiled program for any prompt length; the outer
``while_loop`` terminates because every active row advances at least
one token per iteration.  Draft and target only need the shared
``model(params, ids, attention_mask) -> (B, S, V)`` contract, so any
family pairing works (GPT draft for a Llama target, etc.) as long as
the tokenizer/vocab agree.

Two verification modes:

- ``verify="cached"`` (default) — the serving path: both models keep
  live KV caches (seeded by chunked prefill), the draft proposes with
  single-token cached steps and the target scores all gamma+1
  positions with ONE ``decode_chunk`` against its cache.  Per
  iteration the target does O((gamma+1) * S) attention instead of a
  full O(S^2) re-forward.  Rejected positions need no cache rewind:
  entries past the accepted point are rewritten by the next
  iteration's chunk before any query can attend them (the same
  argument that makes chunked prefill safe).
- ``verify="full"`` — both models re-run full-prefix forwards each
  iteration; simplest-possible oracle, used to cross-check the cached
  path in tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["generate_speculative", "spec_iteration"]


def _head_logits(model, p, h):
    """(B, L, V) logits from final hidden states, family-agnostic."""
    if hasattr(model, "_head"):
        return model._head(p, h)
    table = model._table(p)
    return jnp.matmul(h, table.T.astype(h.dtype))


def generate_speculative(target, target_params, draft, draft_params,
                         input_ids, prompt_len, max_new_tokens: int,
                         gamma: int = 4, verify: str = "cached",
                         temperature: float = 0.0, rng=None,
                         top_k=None, top_p=None):
    """Speculative decoding.  Returns ``(ids, final_len)`` with the
    same contract as ``GPT.generate``: rows are left-aligned in the
    (B, S) buffer, generation stops at ``prompt_len + max_new_tokens``
    or the buffer end, positions past ``final_len`` keep the input
    buffer's content.

    ``temperature == 0`` is the greedy variant (output EXACTLY the
    target's greedy continuation).  ``temperature > 0`` (cached verify
    only, needs ``rng``) is speculative SAMPLING (Leviathan et al.
    Thm. 1): draft tokens are accepted with probability
    ``min(1, p_t(x)/p_d(x))`` and rejections resample from the
    residual ``max(0, p_t - p_d)`` — the output DISTRIBUTION equals
    sampling the target directly with the same
    temperature/top_k/top_p filters."""
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    if verify not in ("cached", "full"):
        raise ValueError(f"verify {verify!r} not in ('cached', 'full')")
    if temperature > 0.0:
        if rng is None:
            raise ValueError("sampling (temperature > 0) needs rng=")
        if verify != "cached":
            raise NotImplementedError(
                "speculative sampling rides the cached-verify path")
    if verify == "cached":
        return _generate_cached_verify(target, target_params, draft,
                                       draft_params, input_ids,
                                       prompt_len, max_new_tokens,
                                       gamma, temperature, rng,
                                       top_k, top_p)
    B, S = input_ids.shape
    orig = jnp.asarray(input_ids)
    prompt_len = jnp.broadcast_to(jnp.asarray(prompt_len), (B,))
    final_len = jnp.minimum(prompt_len + max_new_tokens, S)
    pgrid = jnp.arange(S)[None, :]

    def next_token(model, params, ids, cur_len):
        """Greedy next token per row, reading position cur_len-1."""
        amask = (pgrid < cur_len[:, None]).astype(jnp.int32)
        logits = model(params, ids, amask)
        idx = jnp.clip(cur_len - 1, 0, S - 1)
        last = jnp.take_along_axis(
            logits, idx[:, None, None], axis=1)[:, 0]
        return jnp.argmax(last, axis=-1).astype(ids.dtype)

    def write_at(ids, pos, tok, can):
        return jax.vmap(
            lambda row, p, t, c: row.at[p].set(
                jnp.where(c, t, row[p])))(
            ids, jnp.minimum(pos, S - 1), tok, can)

    def cond(carry):
        _, cur_len = carry
        return jnp.any(cur_len < final_len)

    def body(carry):
        ids, cur_len = carry
        active = cur_len < final_len

        # 1. draft proposes gamma greedy tokens (rows stop at the
        # window edge; inactive rows propose nothing)
        ids_d, len_d = ids, cur_len
        dtoks = []
        for _ in range(gamma):
            t = next_token(draft, draft_params, ids_d, len_d)
            can = len_d < final_len
            ids_d = write_at(ids_d, len_d, t, can)
            dtoks.append(t)
            len_d = jnp.where(can, len_d + 1, len_d)
        dtoks = jnp.stack(dtoks, axis=1)                   # (B, gamma)

        # 2. target scores the whole proposed prefix in one forward
        amask = (pgrid < len_d[:, None]).astype(jnp.int32)
        tgt_next = jnp.argmax(
            target(target_params, ids_d, amask), axis=-1)  # (B, S)

        # 3. longest agreeing prefix; proposal j is only eligible if
        # the correction slot after it still fits the window
        offs = jnp.arange(gamma)[None, :]
        vpos = jnp.clip(cur_len[:, None] - 1 + offs, 0, S - 1)
        agree = dtoks == jnp.take_along_axis(tgt_next, vpos, axis=1)
        eligible = (cur_len[:, None] + offs) < (final_len[:, None] - 1)
        n_acc = jnp.sum(jnp.cumprod(agree & eligible, axis=1), axis=1)

        # 4. the corrected token: target's choice after the accepted
        # prefix (for a fully-agreeing draft this is the bonus token)
        cpos = jnp.clip(cur_len - 1 + n_acc, 0, S - 1)
        ctok = jnp.take_along_axis(tgt_next, cpos[:, None],
                                   axis=1)[:, 0].astype(ids.dtype)

        # 5. rebuild: accepted draft zone from ids_d, correction at
        # cur_len + n_acc, everything past it restored from the
        # original buffer (rejected proposals leave no trace)
        corr_at = cur_len + n_acc
        keep = pgrid < corr_at[:, None]
        is_corr = (pgrid == corr_at[:, None]) & active[:, None]
        ids_new = jnp.where(keep, ids_d,
                            jnp.where(is_corr, ctok[:, None], orig))
        new_len = jnp.where(active,
                            jnp.minimum(corr_at + 1, final_len),
                            cur_len)
        return ids_new, new_len

    ids, cur_len = lax.while_loop(cond, body, (orig, prompt_len))
    return ids, final_len


def spec_iteration(target, tp, draft, dp, ids, cur_len, final_len,
                   orig, t_cache, d_cache, gamma: int,
                   key=None, temperature: float = 0.0,
                   top_k=None, top_p=None):
    """ONE draft-propose / target-verify round over per-row state —
    the building block shared by ``generate_speculative`` (which loops
    it to completion) and ``serving.Engine`` (which runs one round per
    scheduler tick with requests arriving between rounds).

    Returns ``(ids, new_len, t_cache, d_cache, key)``; every active
    row advances 1..gamma+1 positions.  ``orig`` supplies the content
    restored past the correction point (the caller's pre-round buffer:
    rejected proposals leave no trace)."""
    from .sampling import filter_logits

    B, S = ids.shape
    L = gamma + 1
    sample = temperature > 0.0
    pgrid = jnp.arange(S)[None, :]
    if key is None:
        key = jax.random.PRNGKey(0)

    def probs_of(logits):
        # filtered sampling distribution (models/sampling.py order:
        # scale, then top-k, then top-p)
        fl = filter_logits(logits.astype(jnp.float32) / temperature,
                           top_k=top_k, top_p=top_p)
        return jax.nn.softmax(fl, axis=-1)

    def write_at(ids, pos, tok, can):
        return jax.vmap(
            lambda row, p, t, c: row.at[p].set(
                jnp.where(c, t, row[p])))(
            ids, jnp.minimum(pos, S - 1), tok, can)

    active = cur_len < final_len

    # 1. draft proposes gamma tokens with single-token cached
    # steps at PER-ROW positions (posd = last known position)
    ids_d, posd = ids, cur_len - 1
    dtoks, dprobs = [], []
    for _ in range(gamma):
        tok_in = jnp.take_along_axis(
            ids_d, jnp.clip(posd, 0, S - 1)[:, None], axis=1)
        h, d_cache = draft.decode_chunk(dp, tok_in, posd, d_cache)
        logits = _head_logits(draft, dp, h)[:, 0]
        if sample:
            pd = probs_of(logits)
            key, sub = jax.random.split(key)
            t = jax.random.categorical(
                sub, jnp.log(pd + 1e-30)).astype(ids.dtype)
            dprobs.append(pd)
        else:
            t = jnp.argmax(logits, axis=-1).astype(ids.dtype)
        can = (posd + 1) < final_len
        ids_d = write_at(ids_d, posd + 1, t, can)
        dtoks.append(t)
        posd = jnp.where(can, posd + 1, posd)
    dtoks = jnp.stack(dtoks, axis=1)                   # (B, gamma)

    # 2. target scores the whole chunk against its cache.  Chunk
    # start clamps to S - L near the buffer end; `off` re-aligns
    # the verify indices (re-ingested entries recompute to the
    # same values — RoPE/positions follow the clamped start)
    pos0 = jnp.clip(jnp.minimum(cur_len - 1, S - L), 0)
    chunk = jnp.take_along_axis(
        ids_d, pos0[:, None] + jnp.arange(L)[None, :], axis=1)
    th, t_cache = target.decode_chunk(tp, chunk, pos0, t_cache)
    t_logits = _head_logits(target, tp, th)             # (B, L, V)
    off = cur_len - 1 - pos0                            # (B,)
    idx = jnp.clip(off[:, None] + jnp.arange(L)[None, :], 0, L - 1)
    t_logits = jnp.take_along_axis(t_logits, idx[:, :, None],
                                   axis=1)  # aligned: row j is
    #                                         position cur-1+j

    offs = jnp.arange(gamma)[None, :]
    eligible = (cur_len[:, None] + offs) < (final_len[:, None] - 1)

    if sample:
        pt = probs_of(t_logits)                        # (B, L, V)
        pd = jnp.stack(dprobs, axis=1)                 # (B, g, V)
        # 3. accept x_j with prob min(1, p_t(x_j) / p_d(x_j))
        pt_x = jnp.take_along_axis(
            pt[:, :gamma], dtoks[..., None].astype(jnp.int32),
            axis=-1)[..., 0]
        pd_x = jnp.take_along_axis(
            pd, dtoks[..., None].astype(jnp.int32), axis=-1)[..., 0]
        key, sub = jax.random.split(key)
        u = jax.random.uniform(sub, (B, gamma))
        accept = u * pd_x < pt_x                       # min(1,.)
        n_acc = jnp.sum(jnp.cumprod(accept & eligible, axis=1),
                        axis=1)
        # 4. the token after the accepted run: residual
        # max(0, p_t - p_d) on a true rejection; p_t itself when
        # the run ended for eligibility/bonus reasons
        nai = jnp.clip(n_acc, 0, gamma)[:, None]
        pt_row = jnp.take_along_axis(
            pt, nai[..., None], axis=1)[:, 0]          # (B, V)
        pd_pad = jnp.concatenate(
            [pd, jnp.zeros((B, 1, pd.shape[-1]), pd.dtype)], axis=1)
        pd_row = jnp.take_along_axis(
            pd_pad, nai[..., None], axis=1)[:, 0]
        el_pad = jnp.concatenate(
            [eligible, jnp.zeros((B, 1), bool)], axis=1)
        was_rejection = jnp.take_along_axis(el_pad, nai,
                                            axis=1)[:, 0]
        resid = jnp.clip(pt_row - jnp.where(
            was_rejection[:, None], pd_row, 0.0), 0.0, None)
        norm = jnp.sum(resid, axis=-1, keepdims=True)
        resid = jnp.where(norm > 1e-12, resid / norm, pt_row)
        key, sub = jax.random.split(key)
        ctok = jax.random.categorical(
            sub, jnp.log(resid + 1e-30)).astype(ids.dtype)
    else:
        tgt_next = jnp.argmax(t_logits, axis=-1)        # (B, L)
        # 3. longest agreeing prefix (correction slot must fit)
        agree = dtoks == tgt_next[:, :gamma].astype(dtoks.dtype)
        n_acc = jnp.sum(jnp.cumprod(agree & eligible, axis=1),
                        axis=1)
        # 4. corrected token = target's choice after the run
        ctok = jnp.take_along_axis(
            tgt_next, jnp.clip(n_acc, 0, gamma)[:, None],
            axis=1)[:, 0].astype(ids.dtype)

    # 5. rebuild ids (accepted zone, correction, restore the rest)
    corr_at = cur_len + n_acc
    keep = pgrid < corr_at[:, None]
    is_corr = (pgrid == corr_at[:, None]) & active[:, None]
    ids_new = jnp.where(keep, ids_d,
                        jnp.where(is_corr, ctok[:, None], orig))
    new_len = jnp.where(active,
                        jnp.minimum(corr_at + 1, final_len),
                        cur_len)
    return ids_new, new_len, t_cache, d_cache, key

def _generate_cached_verify(target, tp, draft, dp, input_ids,
                            prompt_len, max_new_tokens: int,
                            gamma: int, temperature: float = 0.0,
                            rng=None, top_k=None, top_p=None):
    B, S = input_ids.shape
    if gamma + 1 > S:
        raise ValueError(f"gamma+1={gamma + 1} exceeds the buffer "
                         f"length {S}")
    orig = jnp.asarray(input_ids)
    prompt_len = jnp.broadcast_to(jnp.asarray(prompt_len), (B,))
    final_len = jnp.minimum(prompt_len + max_new_tokens, S)

    t_cache = target.prefill_cache(tp, orig)
    d_cache = draft.prefill_cache(dp, orig)
    key0 = rng if rng is not None else jax.random.PRNGKey(0)

    def cond(carry):
        _, cur_len, _, _, _ = carry
        return jnp.any(cur_len < final_len)

    def body(carry):
        ids, cur_len, t_cache, d_cache, key = carry
        return spec_iteration(target, tp, draft, dp, ids, cur_len,
                              final_len, orig, t_cache, d_cache,
                              gamma, key, temperature, top_k, top_p)

    ids, _, _, _, _ = lax.while_loop(
        cond, body, (orig, prompt_len, t_cache, d_cache, key0))
    return ids, final_len
