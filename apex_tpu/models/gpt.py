"""GPT-2-class causal language model.

The reference toolkit is BERT-era and ships no decoder-only model; this
completes the model-family surface with the architecture the framework's
long-context machinery exists for: pre-LN transformer decoder, causal
flash attention on TPU (ops/pallas_flash_attention via
dot_product_attention's dispatch), FusedLayerNorm, weight-tied LM head,
and optional tensor parallelism (``tp_axis``) reusing the same Megatron
modules as BERT (models/bert.py).

``generate`` is a jit-compatible fixed-buffer autoregressive loop:
static (B, block_size) shapes with a length mask, so XLA compiles ONE
program regardless of prompt/continuation lengths (no per-length
recompiles, the TPU-native shape discipline).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .. import nn
from ..nn import functional as F
from ..normalization import FusedLayerNorm
from ..parallel.sync_batchnorm import _axis_in_scope as _sp_in_scope
from ..transformer.attention import dot_product_attention

__all__ = ["GPTConfig", "GPT", "gpt2_small", "gpt2_medium"]


def _head_matmul(x, table):
    """Weight-tied LM head: x @ table.T in the activation dtype.
    A weight-only-int8 ``quantization.QTensor`` table works through the
    same expression (its .T/.astype dequantize; the convert fuses into
    the dot's operand read)."""
    return F.matmul(x, table.T.astype(x.dtype))


class GPTConfig:
    def __init__(self, vocab_size=50257, block_size=1024, n_layer=12,
                 n_head=12, n_embd=768, dropout=0.1,
                 layer_norm_eps=1e-5, tp_axis=None, sp_axis=None,
                 head_chunk=8192, n_kv_head=None, remat=None):
        # head_chunk: vocab chunk size for the fused LM-head loss
        # (nn.fused_xent — logits never materialized); None/0 restores
        # the dense logits + fp32 log_softmax path.  Ignored under
        # tp_axis (loss() routes to the vocab-parallel cross-entropy,
        # which already avoids the full-vocab gather; tp+sp combined is
        # rejected below, so the sp fused path never sees a sharded
        # table).
        self.head_chunk = head_chunk
        self.vocab_size = vocab_size
        self.block_size = block_size
        self.n_layer = n_layer
        self.n_head = n_head
        self.n_embd = n_embd
        self.dropout = dropout
        self.layer_norm_eps = layer_norm_eps
        self.tp_axis = tp_axis
        # sequence parallelism: tokens sharded over this mesh axis, the
        # causal attention runs as ring attention (K/V blocks rotate
        # over ICI), positions and the next-token label shift become
        # globally consistent automatically — block_size then means the
        # GLOBAL sequence length
        self.sp_axis = sp_axis
        # grouped-query attention: n_kv_head < n_head shares each K/V
        # head across n_head/n_kv_head query heads — the KV cache (the
        # long-context serving bottleneck) shrinks by that factor and
        # composes with the int8 cache.  None = MHA (GPT-2 parity; the
        # fused qkv weight layout [q-rows; k-rows; v-rows] is then
        # byte-identical to the pre-GQA layout).
        self.n_kv_head = n_kv_head if n_kv_head is not None else n_head
        if self.n_kv_head < 1 or n_head % self.n_kv_head:
            raise ValueError(f"n_kv_head={self.n_kv_head} must be a "
                             f"positive divisor of n_head={n_head}")
        # GQA composes with tp_axis: ParallelSelfAttention shards the
        # compact K/V projections too (n_kv_head % tp_size checked at
        # trace time inside the layer)
        # per-block rematerialization: None | "nothing" | "dots"
        # (models/_remat.py) — the long-context HBM lever
        from ._remat import _MODES
        if remat not in _MODES:
            raise ValueError(f"remat={remat!r} not in {_MODES}")
        self.remat = remat
        if tp_axis is not None and sp_axis is not None:
            raise NotImplementedError(
                "combined tp+sp GPT is not wired; pick one "
                "(see tests/test_tensor_parallel.py::"
                "test_3d_parallel_block_data_sp_tp for the pattern)")


def gpt2_small():
    return GPTConfig()


def gpt2_medium():
    return GPTConfig(n_layer=24, n_head=16, n_embd=1024)


class GPTSelfAttention(nn.Module):
    """Causal self-attention; flash kernel on TPU via dispatch."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.n_head = cfg.n_head
        self.n_kv = cfg.n_kv_head
        self.head_dim = cfg.n_embd // cfg.n_head
        self.dropout = cfg.dropout
        self.sp = cfg.sp_axis
        self.tp = cfg.tp_axis is not None
        if self.tp:
            from ..parallel.tensor_parallel import ParallelSelfAttention
            self.core = ParallelSelfAttention(
                cfg.n_embd, cfg.n_head, dropout=0.0, causal=True,
                attn_dropout=cfg.dropout, axis_name=cfg.tp_axis,
                num_kv_heads=cfg.n_kv_head)
        else:
            self.qkv = nn.Linear(
                cfg.n_embd, (cfg.n_head + 2 * self.n_kv) * self.head_dim)
            self.out = nn.Linear(cfg.n_embd, cfg.n_embd)
        self.drop = nn.Dropout(cfg.dropout)

    def _split_qkv(self, fused, B, T):
        """(B, T, (H+2Hkv)*D) -> q (B,H,T,D), k/v (B,Hkv,T,D).  Row
        order [q; k; v] matches the pre-GQA fused layout when Hkv==H."""
        H, Hkv, D = self.n_head, self.n_kv, self.head_dim
        q = fused[..., :H * D].reshape(B, T, H, D)
        k = fused[..., H * D:(H + Hkv) * D].reshape(B, T, Hkv, D)
        v = fused[..., (H + Hkv) * D:].reshape(B, T, Hkv, D)
        return (jnp.moveaxis(q, 2, 1), jnp.moveaxis(k, 2, 1),
                jnp.moveaxis(v, 2, 1))

    def forward(self, p, x, mask=None):
        B, T, E = x.shape
        if self.tp:
            return self.drop(p.get("drop", {}),
                             self.core(p["core"], x, mask))
        q, k, v = self._split_qkv(self.qkv(p["qkv"], x), B, T)
        if self.n_kv != self.n_head:
            # training path: expand K/V to full heads so the flash/ring
            # kernels see MHA (the cache-size win is the decode path's)
            rep = self.n_head // self.n_kv
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        if self.sp is not None and _sp_in_scope(self.sp):
            from ..transformer.ring_attention import ring_attention
            from ..nn.module import current_context
            actx = current_context()
            rng = None
            if self.dropout > 0.0 and actx is not None and actx.train:
                # same regularizer as the non-sp path: ring_attention's
                # in-kernel dropout folds device+step into this key
                rng = actx.make_rng()
            ctx = ring_attention(
                q, k, v, axis_name=self.sp, causal=True,
                dropout_rate=self.dropout if rng is not None else 0.0,
                dropout_rng=rng)
        else:
            ctx = dot_product_attention(q, k, v, mask, causal=True,
                                        dropout_rate=self.dropout)
        ctx = jnp.moveaxis(ctx, 1, 2).reshape(B, T, E)
        return self.drop(p.get("drop", {}), self.out(p["out"], ctx))

    def prefill(self, p, x):
        """Full-sequence attention that also returns the COMPACT K/V
        for cache seeding: ``(out, k, v)`` with k/v (B, Hkv, T, D) —
        one MXU-friendly pass instead of T sequential ``decode`` steps
        (eval-mode path, no dropout, like decode)."""
        B, T, E = x.shape
        q, k, v = self._split_qkv(self.qkv(p["qkv"], x), B, T)
        kc, vc = k, v
        if self.n_kv != self.n_head:
            rep = self.n_head // self.n_kv
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        ctx = dot_product_attention(q, k, v, None, causal=True,
                                    dropout_rate=0.0)
        ctx = jnp.moveaxis(ctx, 1, 2).reshape(B, T, E)
        return self.out(p["out"], ctx), kc, vc

    def decode_chunk(self, p, x, pos, cache):
        """L-token cached step at PER-ROW positions (the speculative-
        verify workhorse; contract mirrors LlamaAttention.decode_chunk;
        int8 caches quantize the chunk per position)."""
        B, L, E = x.shape
        S = cache["k"].shape[2]
        q, k, v = self._split_qkv(self.qkv(p["qkv"], x), B, L)

        def put(buf, val):
            return jax.vmap(
                lambda b, vv, p0: jax.lax.dynamic_update_slice(
                    b, vv.astype(b.dtype), (0, p0, 0)))(buf, val, pos)

        cache = dict(cache)
        if cache["k"].dtype == jnp.int8:
            from ._cache import quantize_kv
            for name, val in (("k", k), ("v", v)):
                ints, scale = quantize_kv(val)
                cache[name] = put(cache[name], ints)
                cache[f"{name}_scale"] = put(cache[f"{name}_scale"],
                                             scale)
            kf = (cache["k"].astype(jnp.float32)
                  * cache["k_scale"].astype(jnp.float32))
            vf = (cache["v"].astype(jnp.float32)
                  * cache["v_scale"].astype(jnp.float32))
        else:
            cache["k"] = put(cache["k"], k)
            cache["v"] = put(cache["v"], v)
            kf = cache["k"].astype(jnp.float32)
            vf = cache["v"].astype(jnp.float32)
        G = self.n_head // self.n_kv
        qg = q.reshape(B, self.n_kv, G, L, self.head_dim)
        scores = jnp.einsum("bkgld,bksd->bkgls",
                            qg.astype(jnp.float32), kf)
        scores = scores * (1.0 / (self.head_dim ** 0.5))
        posL = pos[:, None] + jnp.arange(L)
        valid = (jnp.arange(S)[None, None, None, None, :]
                 <= posL[:, None, None, :, None])
        scores = jnp.where(valid, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bkgls,bksd->bkgld", probs, vf).astype(x.dtype)
        ctx = jnp.transpose(ctx, (0, 3, 1, 2, 4)).reshape(B, L, E)
        return self.out(p["out"], ctx), cache

    def decode(self, p, x, pos, cache):
        """One-token step against the KV cache.

        ``x``: (B, 1, E) this position's activations; ``pos``: scalar
        position; ``cache``: {"k","v"} (B, Hkv, S, D) static buffers
        (Hkv = n_kv_head; = n_head under MHA) — plus
        {"k_scale","v_scale"} (B, Hkv, S, 1) when the buffers are
        int8 (GPT.init_cache(dtype=jnp.int8): per-position symmetric
        quantization, the cache-bandwidth/capacity lever for long-S
        serving).  Writes k/v at ``pos`` and attends q over positions
        <= pos.  Eval-mode path (no dropout).  Returns (out (B, 1, E),
        updated cache)."""
        if self.tp:
            raise NotImplementedError(
                "KV-cache decode is single-device; run the TP model "
                "through forward() or shard the batch instead")
        B, _, E = x.shape
        S = cache["k"].shape[2]
        q, k, v = self._split_qkv(self.qkv(p["qkv"], x), B, 1)
        q, k, v = q[:, :, 0], k[:, :, 0], v[:, :, 0]  # (B,H,D)/(B,Hkv,D)
        q8 = cache["k"].dtype == jnp.int8

        def put(buf, val):
            return lax.dynamic_update_slice_in_dim(
                buf, val[:, :, None, :].astype(buf.dtype), pos, axis=2)

        cache = dict(cache)
        if q8:
            from ._cache import quantize_kv
            for name, val in (("k", k), ("v", v)):
                ints, scale = quantize_kv(val)
                cache[name] = put(cache[name], ints)
                cache[f"{name}_scale"] = put(cache[f"{name}_scale"], scale)
            kf = (cache["k"].astype(jnp.float32)
                  * cache["k_scale"].astype(jnp.float32))
            vf = (cache["v"].astype(jnp.float32)
                  * cache["v_scale"].astype(jnp.float32))
        else:
            cache["k"] = put(cache["k"], k)
            cache["v"] = put(cache["v"], v)
            kf = cache["k"].astype(jnp.float32)
            vf = cache["v"].astype(jnp.float32)
        # grouped attention against the COMPACT (B, Hkv, S, D) cache —
        # query heads reshape into (Hkv, group) and share each KV head
        G = self.n_head // self.n_kv
        qg = q.reshape(B, self.n_kv, G, self.head_dim)
        scores = jnp.einsum("bkgd,bksd->bkgs", qg.astype(jnp.float32), kf)
        scores = scores * (1.0 / (self.head_dim ** 0.5))
        valid = jnp.arange(S)[None, None, None, :] <= pos
        scores = jnp.where(valid, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bkgs,bksd->bkgd", probs, vf).astype(x.dtype)
        ctx = ctx.reshape(B, 1, E)
        return self.out(p["out"], ctx), cache


class GPTBlock(nn.Module):
    """Pre-LN decoder block (GPT-2 ordering: x + attn(ln(x)))."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln_1 = FusedLayerNorm(cfg.n_embd, eps=cfg.layer_norm_eps)
        self.attn = GPTSelfAttention(cfg)
        self.ln_2 = FusedLayerNorm(cfg.n_embd, eps=cfg.layer_norm_eps)
        self.tp = cfg.tp_axis is not None
        if self.tp:
            from ..parallel.tensor_parallel import ParallelMLP
            self.mlp = ParallelMLP(cfg.n_embd, 4 * cfg.n_embd,
                                   activation="gelu",
                                   axis_name=cfg.tp_axis)
        else:
            self.fc = nn.Linear(cfg.n_embd, 4 * cfg.n_embd)
            self.proj = nn.Linear(4 * cfg.n_embd, cfg.n_embd)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, p, x, mask=None):
        x = x + self.attn(p["attn"], self.ln_1(p["ln_1"], x), mask)
        h = self.ln_2(p["ln_2"], x)
        if self.tp:
            h = self.mlp(p["mlp"], h)
        else:
            h = self.proj(p["proj"], F.gelu(self.fc(p["fc"], h)))
        return x + self.drop(p.get("drop", {}), h)

    def decode(self, p, x, pos, cache):
        a, cache = self.attn.decode(
            p["attn"], self.ln_1(p["ln_1"], x), pos, cache)
        x = x + a
        h = self.ln_2(p["ln_2"], x)
        h = self.proj(p["proj"], F.gelu(self.fc(p["fc"], h)))
        return x + h, cache

    def prefill(self, p, x):
        a, k, v = self.attn.prefill(p["attn"], self.ln_1(p["ln_1"], x))
        x = x + a
        h = self.ln_2(p["ln_2"], x)
        h = self.proj(p["proj"], F.gelu(self.fc(p["fc"], h)))
        return x + h, k, v

    def decode_chunk(self, p, x, pos, cache):
        a, cache = self.attn.decode_chunk(
            p["attn"], self.ln_1(p["ln_1"], x), pos, cache)
        x = x + a
        h = self.ln_2(p["ln_2"], x)
        h = self.proj(p["proj"], F.gelu(self.fc(p["fc"], h)))
        return x + h, cache


class GPT(nn.Module):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        if cfg.tp_axis is not None:
            from ..parallel.tensor_parallel import VocabParallelEmbedding
            self.wte = VocabParallelEmbedding(cfg.vocab_size, cfg.n_embd,
                                              axis_name=cfg.tp_axis,
                                              init_std=0.02)
        else:
            # GPT-2's initializer_range (the tied head would otherwise
            # start with ~9x-hot logits and ~40-nat loss)
            self.wte = nn.Embedding(cfg.vocab_size, cfg.n_embd,
                                    init_std=0.02)
        self.wpe = nn.Embedding(cfg.block_size, cfg.n_embd,
                                init_std=0.02)
        self.h = nn.ModuleList([GPTBlock(cfg) for _ in range(cfg.n_layer)])
        self.ln_f = FusedLayerNorm(cfg.n_embd, eps=cfg.layer_norm_eps)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, p, input_ids, attention_mask: Optional[jax.Array]
                = None, last_pos: Optional[jax.Array] = None):
        """Logits (B, T, V) — vocab-sharded under tp_axis.

        ``attention_mask``: (B, T) validity (1 = real token); combined
        with the causal constraint inside attention.  ``last_pos``:
        (B,) position indices — project ONLY those rows through the LM
        head and return (B, 1, V); decode loops read one row per step,
        and the full-vocab head matmul over all S positions is the
        dominant per-token cost they'd otherwise pay."""
        x = self._backbone(p, input_ids, attention_mask)
        if last_pos is not None:
            x = jnp.take_along_axis(x, last_pos[:, None, None], axis=1)
        # weight-tied LM head (GPT-2); under TP the table is
        # vocab-sharded -> sharded logits (f-collective on x so its
        # grad sums the blocks)
        table = p["wte"]["weight"]
        if self.cfg.tp_axis is not None:
            from ..parallel.tensor_parallel import copy_to_model_parallel
            x = copy_to_model_parallel(x, self.cfg.tp_axis)
        return _head_matmul(x, table)

    def _head_nll(self, p, x, safe_labels):
        """Per-position nll (B, T') through the weight-tied head.

        ``head_chunk`` set (default): nn.fused_xent streams the vocab —
        the (N, V) logits and fp32 logp are never materialized (at
        GPT-2 T=4096 that is ~1.2 GB of HBM traffic per step saved).
        ``head_chunk=None``: the dense logits + fp32 log_softmax
        reference path (kept as the parity oracle, tested equal)."""
        table = p["wte"]["weight"]
        from ..quantization import QTensor
        if isinstance(table, QTensor):
            # loss on quantized params: fused_xent slices the table, so
            # it needs a real array (the one QTensor consumer with no
            # array-shim route)
            table = table.dequant(x.dtype)
        B, T, D = x.shape
        if self.cfg.head_chunk:
            from ..nn.fused_xent import linear_cross_entropy
            nll = linear_cross_entropy(x.reshape(B * T, D), table,
                                       safe_labels.reshape(-1),
                                       int(self.cfg.head_chunk))
            return nll.reshape(B, T)
        logits = _head_matmul(x, table)
        logp = F.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, safe_labels[..., None],
                                    axis=-1)[..., 0]

    def _backbone(self, p, input_ids, attention_mask=None):
        """Pre-head hidden states (B, T, D) — shared by the logits path
        and the fused-head loss (which never materializes logits)."""
        B, T = input_ids.shape
        sp = self.cfg.sp_axis
        in_sp = sp is not None and _sp_in_scope(sp)
        if in_sp:
            if attention_mask is not None:
                raise NotImplementedError(
                    "attention_mask under sequence parallelism is not "
                    "wired; pack/pad outside the sp axis instead")
            spn = lax.axis_size(sp)
            if T * spn > self.cfg.block_size:
                raise ValueError(
                    f"global sequence {T}x{spn} exceeds block_size "
                    f"{self.cfg.block_size}")
            # GLOBAL positions for this device's token shard
            pos = lax.axis_index(sp) * T + jnp.arange(T)[None, :]
        else:
            if T > self.cfg.block_size:
                raise ValueError(f"sequence length {T} exceeds "
                                 f"block_size {self.cfg.block_size}")
            pos = jnp.arange(T)[None, :]
        x = (self.wte(p["wte"], input_ids)
             + self.wpe(p["wpe"], pos))
        x = self.drop(p.get("drop", {}), x)
        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)
        from ._remat import wrap_block
        for i in range(self.cfg.n_layer):
            fn = wrap_block(
                lambda pp, xx, blk=self.h[i]: blk(pp, xx, mask),
                self.cfg.remat)
            x = fn(p["h"][str(i)], x)
        return self.ln_f(p["ln_f"], x)

    def loss(self, p, input_ids, attention_mask: Optional[jax.Array]
             = None, ignore_index: int = -100):
        """Next-token cross-entropy: predict ids[t+1] from prefix <=t.
        Padding positions (attention_mask == 0) are ignored.

        Under ``sp_axis`` the shift crosses shard boundaries: each
        device's last position is supervised by the NEXT device's first
        token (one (B, 1) ppermute), the global last position is
        masked, and the mean is psum'd over the axis so every device
        returns the global loss."""
        sp = self.cfg.sp_axis
        if sp is not None and _sp_in_scope(sp):
            if attention_mask is not None:
                # forward would raise, but the mask must not be dropped
                # silently before it gets there
                raise NotImplementedError(
                    "attention_mask under sequence parallelism is not "
                    "wired; pack/pad outside the sp axis instead")
            B, T = input_ids.shape
            spn = lax.axis_size(sp)
            idx = lax.axis_index(sp)
            x = self._backbone(p, input_ids)            # (B, T, D)
            nxt_first = lax.ppermute(
                input_ids[:, :1], sp,
                [(i, (i - 1) % spn) for i in range(spn)])
            labels = jnp.concatenate([input_ids[:, 1:], nxt_first], 1)
            # the global final position has no successor (the wrapped
            # ppermute delivered shard 0's first token — mask it)
            is_last = (idx == spn - 1)
            labels = labels.at[:, -1].set(
                jnp.where(is_last, ignore_index, labels[:, -1]))
            valid = labels != ignore_index
            safe = jnp.where(valid, labels, 0)
            nll = self._head_nll(p, x, safe)
            num = lax.psum(jnp.sum(nll * valid), sp)
            den = lax.psum(jnp.sum(valid.astype(jnp.float32)), sp)
            return num / jnp.maximum(den, 1.0)
        labels = input_ids[:, 1:]
        if attention_mask is not None:
            labels = jnp.where(attention_mask[:, 1:] != 0, labels,
                               ignore_index)
        if self.cfg.tp_axis is not None:
            logits = self(p, input_ids, attention_mask)[:, :-1]
            from ..parallel.tensor_parallel import \
                vocab_parallel_cross_entropy
            return vocab_parallel_cross_entropy(
                logits, labels, axis_name=self.cfg.tp_axis,
                ignore_index=ignore_index)
        x = self._backbone(p, input_ids, attention_mask)[:, :-1]
        valid = labels != ignore_index
        safe = jnp.where(valid, labels, 0)
        nll = self._head_nll(p, x, safe)
        return jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1)

    def generate(self, p, input_ids, prompt_len, max_new_tokens: int,
                 temperature: float = 0.0,
                 rng: Optional[jax.Array] = None,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None):
        """Fixed-buffer autoregressive decoding (jit-compatible).

        ``input_ids``: (B, block_size) buffer holding the prompt left-
        aligned (anything at position >= prompt_len is overwritten);
        ``prompt_len``: (B,) or scalar prompt lengths.  Greedy when
        ``temperature == 0`` (static python float), else samples with
        ``rng`` (``top_k``/``top_p`` filter per models/sampling.py).
        One compiled program serves any prompt length.
        Generation for a row stops when its buffer fills: at most
        ``block_size - prompt_len`` new tokens land; further iterations
        leave the row untouched (``final_len`` caps at block_size).
        """
        from . import sampling
        B, S = input_ids.shape
        prompt_len = jnp.broadcast_to(jnp.asarray(prompt_len), (B,))
        if temperature > 0.0 and rng is None:
            raise ValueError("sampling (temperature > 0) needs rng=")

        def body(i, carry):
            ids, cur_len, key = carry
            amask = (jnp.arange(S)[None, :] < cur_len[:, None]).astype(
                jnp.int32)
            # one (B, 1, V) head row per step, not (B, S, V)
            last = self(p, ids, amask,
                        last_pos=jnp.minimum(cur_len - 1, S - 1))[:, 0]
            tp = self.cfg.tp_axis
            if tp is not None and _sp_in_scope(tp):
                # logits are VOCAB-SHARDED: a local argmax would emit
                # shard-local ids.  Global greedy: max over shards,
                # lowest winning global id (ties break like the
                # unmapped argmax)
                if temperature > 0.0:
                    raise NotImplementedError(
                        "sampled generate under tensor parallelism is "
                        "not wired (needs the full distribution); use "
                        "greedy or gather logits outside")
                vloc = last.shape[-1]
                lm = jnp.max(last, axis=-1)
                li = (jnp.argmax(last, axis=-1)
                      + lax.axis_index(tp) * vloc)
                gm = lax.pmax(lm, tp)
                cand = jnp.where(lm == gm, li,
                                 jnp.iinfo(jnp.int32).max)
                nxt = lax.pmin(cand, tp)
            elif temperature > 0.0:
                key, sub = jax.random.split(key)
                nxt = sampling.sample_token(sub, last, temperature,
                                            top_k=top_k, top_p=top_p)
            else:
                nxt = jnp.argmax(last, axis=-1)
            # write at cur_len; a saturated row (cur_len == S) keeps its
            # last slot instead of re-decoding over it every iteration
            can = cur_len < S
            wpos = jnp.minimum(cur_len, S - 1)
            ids = jnp.asarray(ids)
            ids = jax.vmap(
                lambda row, pos, tok, c: row.at[pos].set(
                    jnp.where(c, tok, row[pos])))(
                ids, wpos, nxt.astype(ids.dtype), can)
            return ids, jnp.minimum(cur_len + 1, S), key

        key = rng if rng is not None else jax.random.PRNGKey(0)
        ids, final_len, _ = lax.fori_loop(
            0, max_new_tokens, body, (input_ids, prompt_len, key))
        return ids, final_len

    def init_cache(self, batch_size: int, dtype=jnp.float32):
        """Per-layer (B, n_kv_head, S, D) k/v buffers for cached
        decoding (n_kv_head = n_head under MHA; smaller under GQA —
        that factor is the cache-size win).

        ``dtype=jnp.int8`` adds per-position (B, n_kv_head, S, 1) scale
        sidecars: entries quantize symmetrically as they are written
        and dequantize fused into the attention reads — half the cache
        bytes of bf16, double the context per HBM byte."""
        cfg = self.cfg
        # GQA: only n_kv_head KV heads are cached (the whole point)
        shape = (batch_size, cfg.n_kv_head, cfg.block_size,
                 cfg.n_embd // cfg.n_head)

        # one allocation PER LAYER: sharing a single zeros buffer
        # across layers (the old `dict(layer)` shallow copy) breaks
        # buffer donation — donating the cache would donate the same
        # buffer n_layer times (serving.Engine donates its caches)
        def layer():
            out = {"k": jnp.zeros(shape, dtype),
                   "v": jnp.zeros(shape, dtype)}
            if dtype == jnp.int8:
                sshape = shape[:3] + (1,)
                out["k_scale"] = jnp.zeros(sshape, jnp.float32)
                out["v_scale"] = jnp.zeros(sshape, jnp.float32)
            return out

        return {str(i): layer() for i in range(cfg.n_layer)}

    def _decode_hidden(self, p, token, pos, cache):
        """Blocks-only decode step: (B,) token at ``pos`` -> ((B, 1, E)
        final hidden state, updated cache).  The LM head is separate so
        prefill steps can skip the full-vocab matmul."""
        B = token.shape[0]
        x = (self.wte(p["wte"], token[:, None])
             + self.wpe(p["wpe"], jnp.full((B, 1), pos)))
        new_cache = {}
        for i in range(self.cfg.n_layer):
            li = str(i)
            x, new_cache[li] = self.h[i].decode(p["h"][li], x, pos,
                                                cache[li])
        return self.ln_f(p["ln_f"], x), new_cache

    def prefill_cache(self, p, input_ids, cache=None, cache_dtype=None):
        """Seed every layer's KV cache with ONE full-buffer forward
        (models/_cache.py semantics; identical values to walking the
        positions with decode)."""
        from ._cache import seed_layer
        B, S = input_ids.shape
        if cache is None:
            if cache_dtype is None:
                cache_dtype = p["wte"]["weight"].dtype
            cache = self.init_cache(B, dtype=cache_dtype)
        x = (self.wte(p["wte"], input_ids)
             + self.wpe(p["wpe"], jnp.arange(S)[None, :]))
        for i in range(self.cfg.n_layer):
            li = str(i)
            x, k, v = self.h[i].prefill(p["h"][li], x)
            cache[li] = seed_layer(cache[li], k, v)
        return cache

    def decode_chunk(self, p, tokens, pos, cache):
        """Cached multi-token step at per-row positions: ``tokens``
        (B, L) for positions ``[pos[b], pos[b]+L)`` -> (final hidden
        (B, L, E), updated cache); head separate like _decode_hidden."""
        B, L = tokens.shape
        posL = pos[:, None] + jnp.arange(L)
        x = (self.wte(p["wte"], tokens) + self.wpe(p["wpe"], posL))
        new_cache = {}
        for i in range(self.cfg.n_layer):
            li = str(i)
            x, new_cache[li] = self.h[i].decode_chunk(p["h"][li], x, pos,
                                                      cache[li])
        return self.ln_f(p["ln_f"], x), new_cache

    def _head(self, p, x):
        table = p["wte"]["weight"]
        return _head_matmul(x, table)

    def decode_step(self, p, token, pos, cache):
        """token: (B,) ids at scalar position ``pos`` -> ((B, V) logits
        for the NEXT position, updated cache).  O(S) per token vs the
        O(S^2) of re-running the full prefix; eval-mode (no dropout)."""
        x, new_cache = self._decode_hidden(p, token, pos, cache)
        return self._head(p, x)[:, 0], new_cache

    def generate_cached(self, p, input_ids, prompt_len,
                        max_new_tokens: int, temperature: float = 0.0,
                        rng: Optional[jax.Array] = None,
                        cache_dtype=None,
                        top_k: Optional[int] = None,
                        top_p: Optional[float] = None,
                        prefill_mode: str = "chunked",
                        min_p: Optional[float] = None,
                        repetition_penalty: float = 1.0):
        """KV-cached ``generate``: one fused prefill+decode loop over
        the buffer positions, O(S) attention per step against the
        static (B, n_kv_head, S, D) caches.  Greedy output is IDENTICAL to
        ``generate`` (parity-tested); single-device (no tp_axis).

        One compiled program serves any prompt length: the loop bound is
        a traced ``max(final_len) - 1`` (lowered to while_loop), prefill
        steps skip the full-vocab head matmul entirely (``lax.cond``),
        and ``cache_dtype`` defaults to the embedding table's dtype (so
        a bf16 model gets a bf16 cache, half the memory).
        ``top_k``/``top_p`` filter sampled steps (models/sampling.py).

        ``prefill_mode="chunked"`` (default) seeds the KV cache with
        ONE full-buffer forward (models/_cache.py) and starts the
        sequential loop at the earliest prompt end — prompt processing
        rides the MXU instead of min(prompt_len) dependent steps.
        ``"step"`` restores the walk-every-position loop.
        """
        from . import sampling
        if self.cfg.tp_axis is not None:
            raise NotImplementedError("generate_cached is single-device; "
                                      "use generate() under TP")
        if prefill_mode not in ("chunked", "step"):
            raise ValueError(f"prefill_mode {prefill_mode!r} not in "
                             f"('chunked', 'step')")
        B, S = input_ids.shape
        prompt_len = jnp.broadcast_to(jnp.asarray(prompt_len), (B,))
        if temperature > 0.0 and rng is None:
            raise ValueError("sampling (temperature > 0) needs rng=")
        final_len = jnp.minimum(prompt_len + max_new_tokens, S)
        first_gen = jnp.min(prompt_len)     # earliest live head step

        def body(i, carry):
            ids, cache, key = carry
            x, cache = self._decode_hidden(p, ids[:, i], i, cache)

            def live(args):
                x, key = args
                logits = self._head(p, x)[:, 0]
                if repetition_penalty != 1.0:
                    logits = sampling.apply_repetition_penalty(
                        logits, ids, jnp.maximum(prompt_len, i + 1),
                        repetition_penalty)
                if temperature > 0.0:
                    key, sub = jax.random.split(key)
                    nxt = sampling.sample_token(sub, logits, temperature,
                                                top_k=top_k, top_p=top_p,
                                                min_p=min_p)
                else:
                    nxt = jnp.argmax(logits, axis=-1)
                return nxt.astype(ids.dtype), key

            def prefill(args):
                _, key = args
                return jnp.zeros((B,), ids.dtype), key

            # prefill steps (every row still inside its prompt) skip the
            # full-vocab head matmul and the sample
            nxt, key = lax.cond(i + 1 >= first_gen, live, prefill,
                                (x, key))
            # position i+1 receives a generated token iff it lies in the
            # generation window [prompt_len, final_len)
            should = (i + 1 >= prompt_len) & (i + 1 < final_len)
            col = jnp.where(should, nxt, ids[:, i + 1])
            ids = lax.dynamic_update_slice_in_dim(
                ids, col[:, None], i + 1, axis=1)
            return ids, cache, key

        key = rng if rng is not None else jax.random.PRNGKey(0)
        if cache_dtype is None:
            cache_dtype = p["wte"]["weight"].dtype
        cache = self.init_cache(B, dtype=cache_dtype)
        start = 0
        if prefill_mode == "chunked":
            cache = self.prefill_cache(p, input_ids, cache)
            # entries at positions >= first_gen - 1 are rewritten by
            # the loop before any later position reads them
            start = jnp.maximum(first_gen - 1, 0)
        # traced bound: no dead steps past the longest row's final_len
        ids, _, _ = lax.fori_loop(start, jnp.max(final_len) - 1, body,
                                  (input_ids, cache, key))
        return ids, final_len
