"""KV-cache seeding shared by the GPT/Llama chunked-prefill paths.

``seed_layer`` writes a full (B, Hkv, T, D) K/V block into one layer's
static cache buffers with EXACTLY the math the per-token ``decode``
write would have used — including the int8 per-position quantization
(amax/127 scale sidecars) — so chunked prefill is numerically
interchangeable with stepping the prompt token by token.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["seed_layer", "quantize_kv"]


def quantize_kv(val):
    """THE int8 KV quantization: per-position symmetric amax/127 over
    the head dim.  Every cache write path (single-token decode,
    decode_chunk, full-buffer seeding) MUST use this one function —
    chunked prefill's exactness vs the per-token walk depends on the
    math staying bit-identical.  Returns (int8 values, fp32 scales)."""
    f = val.astype(jnp.float32)
    amax = jnp.max(jnp.abs(f), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    return (jnp.clip(jnp.round(f / scale), -127, 127).astype(jnp.int8),
            scale)


def seed_layer(layer_cache, k, v):
    """New layer-cache dict with k/v (B, Hkv, T, D) written at
    positions [0, T) (T == the buffer length S for full-buffer
    prefill)."""
    out = dict(layer_cache)
    if layer_cache["k"].dtype == jnp.int8:
        for name, val in (("k", k), ("v", v)):
            ints, scale = quantize_kv(val)
            out[name] = ints
            out[f"{name}_scale"] = scale.astype(
                layer_cache[f"{name}_scale"].dtype)
    else:
        out["k"] = k.astype(layer_cache["k"].dtype)
        out["v"] = v.astype(layer_cache["v"].dtype)
    return out
