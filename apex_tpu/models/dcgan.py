"""DCGAN generator/discriminator (the reference ships a DCGAN amp example,
examples/dcgan/main_amp.py; the 64x64 topology is the standard
Radford et al. 2015 layout).

The generator upsamples z (B, nz, 1, 1) -> (B, nc, 64, 64) through
strided transposed convs; the discriminator mirrors it downward to one
logit. Both are amp-friendly: convs ride the MXU whitelist, BatchNorm
stays fp32 under O2 (keep_batchnorm_fp32), and the final D output is a
logit so the loss is the fp32 ``binary_cross_entropy_with_logits`` (the
plain BCE form is banned under amp — apex_tpu.amp.lists.BANNED_FUNCS).
"""

from __future__ import annotations

from .. import nn

__all__ = ["Generator", "Discriminator", "dcgan"]


class Generator(nn.Module):
    def __init__(self, nz: int = 100, ngf: int = 64, nc: int = 3):
        super().__init__()
        self.nz = nz
        self.main = nn.Sequential([
            # (nz, 1, 1) -> (ngf*8, 4, 4)
            nn.ConvTranspose2d(nz, ngf * 8, 4, 1, 0, bias=False),
            nn.BatchNorm2d(ngf * 8), nn.ReLU(),
            # -> (ngf*4, 8, 8)
            nn.ConvTranspose2d(ngf * 8, ngf * 4, 4, 2, 1, bias=False),
            nn.BatchNorm2d(ngf * 4), nn.ReLU(),
            # -> (ngf*2, 16, 16)
            nn.ConvTranspose2d(ngf * 4, ngf * 2, 4, 2, 1, bias=False),
            nn.BatchNorm2d(ngf * 2), nn.ReLU(),
            # -> (ngf, 32, 32)
            nn.ConvTranspose2d(ngf * 2, ngf, 4, 2, 1, bias=False),
            nn.BatchNorm2d(ngf), nn.ReLU(),
            # -> (nc, 64, 64)
            nn.ConvTranspose2d(ngf, nc, 4, 2, 1, bias=False),
            nn.Tanh(),
        ])

    def forward(self, params, z):
        return self.main(params["main"], z)


class Discriminator(nn.Module):
    def __init__(self, ndf: int = 64, nc: int = 3):
        super().__init__()
        self.main = nn.Sequential([
            # (nc, 64, 64) -> (ndf, 32, 32)
            nn.Conv2d(nc, ndf, 4, 2, 1, bias=False),
            nn.LeakyReLU(0.2),
            nn.Conv2d(ndf, ndf * 2, 4, 2, 1, bias=False),
            nn.BatchNorm2d(ndf * 2), nn.LeakyReLU(0.2),
            nn.Conv2d(ndf * 2, ndf * 4, 4, 2, 1, bias=False),
            nn.BatchNorm2d(ndf * 4), nn.LeakyReLU(0.2),
            nn.Conv2d(ndf * 4, ndf * 8, 4, 2, 1, bias=False),
            nn.BatchNorm2d(ndf * 8), nn.LeakyReLU(0.2),
            # -> (1, 1, 1) logit
            nn.Conv2d(ndf * 8, 1, 4, 1, 0, bias=False),
        ])

    def forward(self, params, x):
        out = self.main(params["main"], x)
        return out.reshape(out.shape[0])  # (B,) logits


def dcgan(nz: int = 100, ngf: int = 64, ndf: int = 64, nc: int = 3):
    return Generator(nz, ngf, nc), Discriminator(ndf, nc)
