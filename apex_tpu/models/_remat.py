"""Per-block rematerialization for the transformer families.

The reference-era equivalent is torch checkpointing (not in the 2019
Apex snapshot); on TPU this is the standard HBM lever: activations are
the long-context memory bottleneck, and ``jax.checkpoint`` around each
decoder block trades backward-pass FLOPs for not storing them
(SURVEY.md §preamble: "use jax.checkpoint / rematerialisation to trade
FLOPs for memory").

Modes (the ``remat=`` config field on GPTConfig/LlamaConfig):

- ``None``        — store everything (XLA default).
- ``"nothing"``   — save only block boundaries; recompute the whole
                    block in backward (max memory saving).
- ``"dots"``      — ``dots_with_no_batch_dims_saveable``: keep matmul
                    outputs, recompute the cheap elementwise/norm ops —
                    the usual sweet spot on MXU-bound steps.

Gradients are mathematically identical either way (pinned in
tests/test_remat.py, along with a backward-FLOPs increase check).
"""

from __future__ import annotations

import jax

__all__ = ["wrap_block"]

_MODES = (None, "nothing", "dots")


def wrap_block(fn, mode):
    """``fn(params, x) -> out`` wrapped per ``mode`` (see module doc)."""
    if mode is None:
        return fn
    if mode == "nothing":
        policy = jax.checkpoint_policies.nothing_saveable
    elif mode == "dots":
        policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    else:
        raise ValueError(f"remat mode {mode!r} not in {_MODES}")
    return jax.checkpoint(fn, policy=policy)
