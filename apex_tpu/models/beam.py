"""Beam search over the fixed-buffer generate contract.

Maximizes total log-probability of the generated continuation with
``num_beams`` hypotheses per row (fixed length — no EOS concept in the
buffer contract; rows stop at ``prompt_len + max_new_tokens`` or the
buffer end).  ``num_beams=1`` reduces exactly to greedy ``generate``
(pinned in tests/test_beam.py, along with exhaustive-search parity at
small horizons).

Shape discipline matches ``GPT.generate``: the batch is expanded to
``B * num_beams`` rows, every step is one full-prefix forward (simple
and exact — the KV-cached variant would add per-step cache reordering
by beam index), and all reindexing is static-shape ``top_k`` +
``take_along_axis``, so the whole search jits as one program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["beam_search"]

NEG = -1e30


def beam_search(model, params, input_ids, prompt_len,
                max_new_tokens: int, num_beams: int = 4):
    """Returns ``(ids (B, S), final_len (B,), score (B,))`` — the best
    beam per row and its total continuation log-probability."""
    if num_beams < 1:
        raise ValueError(f"num_beams must be >= 1, got {num_beams}")
    B, S = input_ids.shape
    K = num_beams
    prompt_len = jnp.broadcast_to(jnp.asarray(prompt_len), (B,))
    final_len = jnp.minimum(prompt_len + max_new_tokens, S)
    pgrid = jnp.arange(S)[None, :]

    ids0 = jnp.repeat(jnp.asarray(input_ids), K, axis=0)   # (B*K, S)
    # all beams start identical: only beam 0 is live, or the first
    # step would pick the same token K times
    scores0 = jnp.where(jnp.arange(K)[None, :] == 0, 0.0, NEG)
    scores0 = jnp.broadcast_to(scores0, (B, K))

    def body(t, carry):
        ids, scores, cur_len = carry
        active = cur_len < final_len                        # (B,)
        lens = jnp.repeat(cur_len, K)
        amask = (pgrid < lens[:, None]).astype(jnp.int32)
        logits = model(params, ids, amask)
        idx = jnp.clip(lens - 1, 0, S - 1)
        last = jnp.take_along_axis(
            logits, idx[:, None, None], axis=1)[:, 0]       # (B*K, V)
        V = last.shape[-1]
        logp = jax.nn.log_softmax(last.astype(jnp.float32), axis=-1)
        total = scores[:, :, None] + logp.reshape(B, K, V)
        top_scores, top_idx = lax.top_k(total.reshape(B, K * V), K)
        beam_idx = top_idx // V                             # (B, K)
        tok = (top_idx % V).astype(ids.dtype)

        # reorder beams, then append the chosen token at cur_len —
        # ONLY for active rows: a finished row must keep ids AND
        # scores frozen together (reordering its ids while freezing
        # its scores would desynchronize the final argmax)
        prev = ids.reshape(B, K, S)
        reord = jnp.take_along_axis(prev, beam_idx[:, :, None], axis=1)
        wpos = jnp.clip(cur_len, 0, S - 1)
        cols = jax.vmap(lambda row_ids, p, toks: row_ids.at[:, p].set(
            toks))(reord, wpos, tok)
        keep = active[:, None, None]
        ids = jnp.where(keep, cols, prev).reshape(B * K, S)
        scores = jnp.where(active[:, None], top_scores, scores)
        return ids, scores, jnp.where(active, cur_len + 1, cur_len)

    ids, scores, _ = lax.fori_loop(
        0, max_new_tokens, body, (ids0, scores0, prompt_len))
    best = jnp.argmax(scores, axis=-1)                      # (B,)
    out = jnp.take_along_axis(
        ids.reshape(B, K, S), best[:, None, None], axis=1)[:, 0]
    return out, final_len, jnp.take_along_axis(
        scores, best[:, None], axis=1)[:, 0]
