"""Routing policies, retry/backoff, and fleet-level backpressure.

A policy answers one question per dispatch: *which admissible replica
takes this request?*  The fleet pre-filters the candidate list (healthy
before degraded, free slot required, breaker/drain respected), so
policies stay pure ranking functions over live scheduler stats and are
trivially testable.

Every ``select`` also leaves ``last_decision`` — a small
JSON-serializable dict saying *why* that replica won (per-candidate
loads, the matched prefix owner, the rotation cursor).  The fleet
copies it onto the request's ``fleet_route`` trace event, so a flight
record answers "why replica 2?" without re-deriving the ranking.

- :class:`RoundRobin` — cycle through candidates; the baseline.
- :class:`LeastLoaded` — rank by each replica's ``stats()`` occupancy
  plus its queue depth (normalized by slot count), ties to the lowest
  index.  The default.
- :class:`PrefixAffinity` — prompts sharing a prefix registered through
  ``Fleet.register_prefix`` route to the replica holding that prefix's
  pool row (its KV splice makes admission cheap THERE and nowhere
  else); everything else falls through to an inner policy.

:class:`RetryPolicy` is the dispatch-failure schedule: exponential
backoff with seeded jitter, measured in FLEET STEPS so the whole retry
timeline is deterministic under the fault harness.  ``max_attempts``
exhausted fails the request (``Fleet.result`` raises with the last
error).  :class:`FleetOverloaded` is the explicit shed signal raised by
``Fleet.submit`` when the bounded fleet queue is full — retriable by
construction: the queue drains as replicas finish work.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

__all__ = ["FleetOverloaded", "RetryPolicy", "RoundRobin",
           "LeastLoaded", "PrefixAffinity", "make_policy"]


class FleetOverloaded(RuntimeError):
    """The bounded fleet queue is full: the request was SHED, not
    queued.  Retriable — resubmit after backoff; ``queue_depth`` and
    ``max_queue`` say how far over capacity the caller found us.
    Under a multi-class :class:`~apex_tpu.fleet.qos.QosPolicy`,
    ``qos_class`` names the priority class whose quota (or the global
    queue) rejected the submit — a batch client seeing its own class
    here knows backing off harder won't help the interactive tier,
    it IS the relief."""

    def __init__(self, queue_depth: int, max_queue: int,
                 qos_class=None):
        cls = f" [class {qos_class}]" if qos_class is not None else ""
        super().__init__(
            f"fleet queue full ({queue_depth}/{max_queue}){cls}; "
            f"request shed — retry after backoff")
        self.queue_depth = queue_depth
        self.max_queue = max_queue
        self.qos_class = qos_class


class RetryPolicy:
    """Exponential backoff with full seeded jitter, in fleet steps.

    Attempt k (0-based) that fails waits
    ``min(base_delay_steps * backoff**k, max_delay_steps)`` steps,
    scaled by ``uniform(1 - jitter, 1 + jitter)`` from a seeded RNG —
    deterministic per policy instance, which is what lets the tests
    pin exact retry timelines."""

    def __init__(self, max_attempts: int = 4,
                 base_delay_steps: int = 1,
                 max_delay_steps: int = 16,
                 backoff: float = 2.0,
                 jitter: float = 0.5,
                 seed: int = 0):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got "
                             f"{max_attempts}")
        if not (0.0 <= jitter < 1.0):
            raise ValueError(f"jitter must be in [0, 1), got {jitter}")
        self.max_attempts = max_attempts
        self.base_delay_steps = base_delay_steps
        self.max_delay_steps = max_delay_steps
        self.backoff = backoff
        self.jitter = jitter
        self._rng = np.random.RandomState(seed)

    def delay_steps(self, attempt: int) -> int:
        """Steps to wait after failed attempt number ``attempt``
        (0-based)."""
        d = min(self.base_delay_steps * self.backoff ** attempt,
                float(self.max_delay_steps))
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.uniform() - 1.0)
        return max(1, int(round(d)))


# -- policies --------------------------------------------------------------

def _req_tags(req) -> dict:
    """Tenant/priority tags of a request, for ``last_decision``: the
    routing record of a tagged request says WHOSE request was ranked
    (the fleet copies the decision onto the ``fleet_route`` trace
    event; since PR 19 the QoS plane consumes the priority BEFORE
    routing — the WfqQueue decides who meets the router first, the
    policy only decides where — and the fleet stamps the resolved
    ``qos_class`` on the trace event itself).  Untagged requests keep
    the pre-tenant decision shape."""
    tags = {}
    tenant = getattr(req, "tenant", None)
    if tenant is not None:
        tags["tenant"] = tenant
    priority = getattr(req, "priority", None)
    if priority is not None:
        tags["priority"] = priority
    return tags


def _load(replica) -> float:
    """Occupancy + queued work, both normalized per slot — one scalar
    'how busy' from the scheduler's cheap accessors (``stats()`` is
    too heavy for a per-dispatch read)."""
    slots = max(replica.slots, 1)
    return replica.live() / slots + replica.queue_depth() / slots


class RoundRobin:
    """Cycle through the candidate list."""
    name = "round_robin"

    def __init__(self):
        self._next = 0
        self.last_decision = None

    def select(self, fleet, candidates: Sequence[int], req) -> int:
        # candidates are sorted replica indices; take the first one at
        # or after the cursor so removal of a replica (drain/death)
        # cannot wedge the rotation
        cursor = self._next
        pick = next((i for i in candidates if i >= cursor),
                    candidates[0])
        self._next = pick + 1
        self.last_decision = {"cursor": cursor, "wrapped":
                              pick < cursor, **_req_tags(req)}
        return pick


class LeastLoaded:
    """Lowest occupancy+queue replica wins; ties to the lowest
    index."""
    name = "least_loaded"

    def __init__(self):
        self.last_decision = None

    def select(self, fleet, candidates: Sequence[int], req) -> int:
        loads = {i: _load(fleet.replicas[i]) for i in candidates}
        pick = min(candidates, key=lambda i: (loads[i], i))
        # JSON object keys are strings; stringify (and round for
        # display only — selection uses full precision) so the
        # decision survives the trace record round-trip unchanged
        self.last_decision = {"load": {str(i): round(loads[i], 4)
                                       for i in candidates},
                              **_req_tags(req)}
        return pick


class PrefixAffinity:
    """Route prompts to the replica holding their registered prefix.

    ``Fleet.register_prefix`` prefills the prefix into ONE replica's
    pool and records the owner; a prompt starting with a registered
    prefix prefers that owner (longest match wins) whenever it is an
    admissible candidate — landing the request on the replica where
    admission is a KV splice instead of a full prefill.  Everything
    else (no match, owner dead/draining/full) falls through to
    ``fallback``."""
    name = "prefix_affinity"

    def __init__(self, fallback=None):
        self.fallback = fallback or LeastLoaded()
        self.last_decision = None

    def select(self, fleet, candidates: Sequence[int], req) -> int:
        owner = fleet.prefix_owner(req.prompt)
        if owner is not None and owner in candidates:
            self.last_decision = {"prefix_owner": owner,
                                  **_req_tags(req)}
            return owner
        pick = self.fallback.select(fleet, candidates, req)
        self.last_decision = {
            # owner set but inadmissible (dead/draining/full) is the
            # interesting trace distinction vs no registered match
            "prefix_owner": owner, "fallback":
            getattr(self.fallback, "name",
                    type(self.fallback).__name__),
            **(getattr(self.fallback, "last_decision", None) or {})}
        return pick


_POLICIES = {"round_robin": RoundRobin, "least_loaded": LeastLoaded,
             "prefix_affinity": PrefixAffinity}


def make_policy(policy) -> object:
    """Resolve a policy name or pass an instance through."""
    if isinstance(policy, str):
        try:
            return _POLICIES[policy]()
        except KeyError:
            raise ValueError(
                f"unknown routing policy {policy!r}; known: "
                f"{sorted(_POLICIES)}") from None
    if not hasattr(policy, "select"):
        raise TypeError(f"policy must be a name or expose "
                        f".select(fleet, candidates, req); got "
                        f"{type(policy).__name__}")
    return policy
