"""Self-healing serving: the telemetry→action loop, serving side.

:class:`SloController` closes the loop PR 10's ``fleet/slo.py``
opened: every control tick it reads the :class:`~apex_tpu.fleet.slo.
SloTracker`'s queue-wait vs service split and deadline attainment —
the SAME flushed aggregates ``/statusz`` serves, nothing new is
measured — and actuates only what the fleet already exposes:

- **admission bound** — ``Fleet.max_queue``, the bounded-queue shed
  knob: tightening it under overload converts would-be deadline
  misses into immediate, retriable ``FleetOverloaded`` sheds, so the
  requests that ARE admitted still meet their deadlines (goodput over
  raw throughput, the PR 10 argument closed into an actuator);
- **drain / undrain** — capacity out and in (``Fleet.drain`` /
  ``undrain``): queue-wait dominance with a drained replica parked is
  the signal to re-enlist it; sustained idleness (opt-in
  ``scale_in``) is the signal to park one;
- **the breaker's step-counted cooldowns** —
  :meth:`~apex_tpu.fleet.health.ReplicaHealth.set_cooldown`: when the
  fleet is starved AND a circuit is open, shorten the remaining
  cooldown so the half-open probe fires sooner; when a replica keeps
  failing probes under light load, leave the breaker's own
  exponential backoff alone;
- **decode window size** — duck-typed ``set_window(k)`` on replicas
  that support it (the stdlib ``Engine`` compiles its window into
  ``_step_k``, so live window actuation applies to replicas built for
  it — stub/elastic replicas in the chaos harness, or an engine
  wrapper that pre-compiles several window sizes).  A larger window
  buys throughput per host sync; a smaller one sheds per-request
  latency under a deadline crunch.

Decisions are DETERMINISTIC and hysteretic: attainment and the
wait/service split are computed as per-tick DELTAS of the tracker's
cumulative aggregates (no wall-clock windows — tick-exact under the
fault harness's injected clocks), an overload EPISODE opens on the
transition past the thresholds, at most one actuation fires per
``cooldown_ticks``, and ``max_actions_per_episode`` bounds the total
— the no-oscillation contract ``tests/ci/chaos_smoke.py`` gates.
Episodes, actions and MTTR share :class:`~apex_tpu.fleet.recovery.
RecoveryLog` with the training controller, so both directions of the
loop emit one ``kind: recovery`` record shape
(``observability.exporters.validate_recovery_record``).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

from .health import HEALTHY
from .recovery import RecoveryLog

__all__ = ["AutoscaleConfig", "SloController"]


class AutoscaleConfig:
    """Control-loop thresholds (all tick-counted, deterministic under
    injected clocks).

    - ``target_attainment``: recent (per-tick delta) deadline
      attainment below this opens an overload episode;
    - ``queue_wait_dominance``: queue-wait mean exceeding this multiple
      of the service mean — with work actually queued — also counts as
      overload (the fleet had no capacity; the replicas were fine);
    - ``backlog_factor``: a fleet queue deeper than this multiple of
      the replicas' combined slot capacity counts as overload
      IMMEDIATELY — the leading-edge signal: a spike is visible in the
      backlog the tick it lands, a full service time before its first
      deadline miss can resolve;
    - ``min_queue``: the admission bound is never tightened below this
      (an admission bound of 0 would be a full outage, not control);
    - ``cooldown_ticks``: at least this many control ticks between
      actuations (hysteresis — let the last action take effect before
      judging it);
    - ``relax_after_ticks``: healthy ticks required before the
      controller starts undoing its own tightening;
    - ``max_actions_per_episode``: hard bound on actuations per
      overload episode — exceeding it stops actuating and leaves the
      episode for a human (chaos_smoke asserts the bound holds);
    - ``probe_cooldown_steps``: what an open breaker's remaining
      cooldown is shortened to when the fleet is starved;
    - ``window_bounds``: ``(min, max)`` decode window the duck-typed
      ``set_window`` actuator may choose;
    - ``scale_in`` / ``idle_ticks_to_drain``: opt-in scale-in — drain
      one healthy replica after that many consecutive idle ticks.
    """

    def __init__(self, target_attainment: float = 0.9,
                 queue_wait_dominance: float = 2.0,
                 backlog_factor: float = 2.0,
                 min_queue: int = 4,
                 cooldown_ticks: int = 2,
                 relax_after_ticks: int = 4,
                 max_actions_per_episode: int = 8,
                 probe_cooldown_steps: int = 1,
                 window_bounds=(1, 32),
                 scale_in: bool = False,
                 idle_ticks_to_drain: int = 8):
        if not (0.0 < target_attainment <= 1.0):
            raise ValueError(f"target_attainment must be in (0, 1], "
                             f"got {target_attainment}")
        if queue_wait_dominance <= 1.0:
            raise ValueError(f"queue_wait_dominance must be > 1, got "
                             f"{queue_wait_dominance}")
        if backlog_factor <= 0.0:
            raise ValueError(f"backlog_factor must be > 0, got "
                             f"{backlog_factor}")
        if min_queue < 1:
            raise ValueError(f"min_queue must be >= 1, got {min_queue}")
        if cooldown_ticks < 1 or relax_after_ticks < 1:
            raise ValueError("cooldown_ticks and relax_after_ticks "
                             "must be >= 1")
        if max_actions_per_episode < 1:
            raise ValueError(f"max_actions_per_episode must be >= 1, "
                             f"got {max_actions_per_episode}")
        if probe_cooldown_steps < 1:
            raise ValueError(f"probe_cooldown_steps must be >= 1, got "
                             f"{probe_cooldown_steps}")
        lo, hi = window_bounds
        if not (1 <= lo <= hi):
            raise ValueError(f"window_bounds must satisfy "
                             f"1 <= min <= max, got {window_bounds}")
        if idle_ticks_to_drain < 1:
            raise ValueError(f"idle_ticks_to_drain must be >= 1, got "
                             f"{idle_ticks_to_drain}")
        self.target_attainment = target_attainment
        self.queue_wait_dominance = queue_wait_dominance
        self.backlog_factor = backlog_factor
        self.min_queue = min_queue
        self.cooldown_ticks = cooldown_ticks
        self.relax_after_ticks = relax_after_ticks
        self.max_actions_per_episode = max_actions_per_episode
        self.probe_cooldown_steps = probe_cooldown_steps
        self.window_bounds = (int(lo), int(hi))
        self.scale_in = scale_in
        self.idle_ticks_to_drain = idle_ticks_to_drain


class SloController:
    """SLO-feedback controller over one :class:`~apex_tpu.fleet.Fleet`.

    Call :meth:`tick` once per control interval (every N fleet steps —
    the caller owns the cadence, typically the same loop that calls
    ``fleet.step()``); each tick reads the tracker deltas, classifies
    the fleet as overloaded / healthy, and actuates AT MOST one knob.
    Returns the actions taken (empty list = no actuation needed)."""

    def __init__(self, fleet, config: Optional[AutoscaleConfig] = None,
                 clock: Optional[Callable[[], float]] = None,
                 ring=None, registry=None):
        self.fleet = fleet
        self.config = config or AutoscaleConfig()
        self._clock = clock if clock is not None else fleet._clock
        self.log = RecoveryLog("serving",
                               getattr(fleet, "trace_id", "fleet"),
                               clock=self._clock, ring=ring,
                               registry=registry)
        self.base_max_queue = int(fleet.max_queue)
        # per-class actuation (PR 19): with a multi-class QosPolicy on
        # the fleet, admission tightens PER CLASS — the batch class's
        # queue_share halves while the interactive class's quota is
        # never touched.  Baseline shares snapshot here so relax can
        # restore them exactly (including a None = unbounded share).
        self._qos = getattr(fleet, "qos", None)
        self._qos_active = bool(getattr(fleet, "_qos_active", False)
                                and self._qos is not None)
        self._base_shares = (
            {name: c.queue_share
             for name, c in self._qos.classes.items()}
            if self._qos_active else {})
        # replicas' combined slot capacity — the backlog signal's
        # yardstick (replicas without a slots attribute count as 1)
        self.total_slots = sum(int(getattr(r, "slots", 1))
                               for r in fleet.replicas)
        # baseline decode windows, snapshotted at construction like
        # base_max_queue: the grow actuator restores TOWARD these, so
        # a replica the controller shrank is never left small forever
        # just because it lacks some extra attribute
        self._base_windows = {
            i: int(r.window) for i, r in enumerate(fleet.replicas)
            if hasattr(r, "set_window") and hasattr(r, "window")}
        self._ticks = 0
        self._last_action_tick = -10**9
        self._healthy_ticks = 0
        self._idle_ticks = 0
        # fleet MTTR measurements already accounted for: start at the
        # CURRENT count, so a recovery that completed before this
        # controller attached can never be mis-attributed to its
        # first episode (the supervisor's ring-watermark discipline)
        self._mttr_seen = int(fleet.mttr()["count"])
        # cumulative-tracker watermarks for the per-tick deltas
        self._seen_with = 0
        self._seen_within = 0
        self._seen_wait = (0, 0.0)       # (count, sum)
        self._seen_service = (0, 0.0)
        self.last_signal: Dict[str, Any] = {}

    # -- signal extraction (cheap accessors + tracker deltas) --------------
    def _signal(self) -> Dict[str, Any]:
        slo = self.fleet.slo
        stats = slo.stats()
        dw = stats["with_deadline"] - self._seen_with
        dwi = stats["within_deadline"] - self._seen_within
        self._seen_with = stats["with_deadline"]
        self._seen_within = stats["within_deadline"]
        attain = (dwi / dw) if dw > 0 else None

        def hist_delta(summary, seen):
            c = (summary["count"] or 0) - seen[0]
            s = (summary["sum"] or 0.0) - seen[1]
            return c, s, ((summary["count"] or 0),
                          (summary["sum"] or 0.0))

        wc, ws, self._seen_wait = hist_delta(stats["queue_wait"],
                                             self._seen_wait)
        sc, ss, self._seen_service = hist_delta(stats["service_time"],
                                                self._seen_service)
        return {"tick": self._ticks,
                "resolved_deadlined": dw,
                "attainment": attain,
                "queue_wait_mean": (ws / wc) if wc else None,
                "service_mean": (ss / sc) if sc else None,
                "queue_depth": self.fleet.queue_depth(),
                "inflight": self.fleet.inflight()}

    def _overloaded(self, sig: Dict[str, Any]) -> Optional[str]:
        cfg = self.config
        a = sig["attainment"]
        if a is not None and a < cfg.target_attainment:
            return (f"attainment {a:.3f} < target "
                    f"{cfg.target_attainment}")
        backlog = cfg.backlog_factor * self.total_slots
        if sig["queue_depth"] > backlog:
            return (f"backlog {sig['queue_depth']} > "
                    f"{cfg.backlog_factor} x {self.total_slots} slots")
        qw, sv = sig["queue_wait_mean"], sig["service_mean"]
        if (qw is not None and sv is not None and sv > 0
                and sig["queue_depth"] > 0
                and qw > cfg.queue_wait_dominance * sv):
            return (f"queue-wait mean {qw:.4f} dominates service mean "
                    f"{sv:.4f} with {sig['queue_depth']} queued")
        return None

    # -- actuators ----------------------------------------------------------
    def _window_replicas(self) -> List[Any]:
        return [(i, self.fleet.replicas[i])
                for i in sorted(self._base_windows)]

    def _class_cap(self, name: str) -> int:
        return self._qos.cap(name, self.fleet.max_queue)

    def _act_class_tighten(self, reason: str) \
            -> Optional[Dict[str, Any]]:
        """Halve the queue quota of the LOWEST-priority class that
        still has room to give, never the top class: shedding lands on
        the batch tier while the interactive tier's admission is
        untouched — the per-class knob ROADMAP item 4 asked for."""
        names = list(self._qos.classes)
        for name in reversed(names[1:]):    # lowest priority first;
            cap = self._class_cap(name)     # rank 0 is never tightened
            if cap > 1:
                new_cap = max(1, cap // 2)
                cls = self._qos.classes[name]
                cls.queue_share = new_cap / self.fleet.max_queue
                return self.log.action("class_admission_tighten",
                                       qos_class=name,
                                       queue_cap_from=cap,
                                       queue_cap_to=new_cap,
                                       reason=reason)
        return None

    def _act_class_relax(self) -> Optional[Dict[str, Any]]:
        """Restore one notch of a tightened class quota toward its
        baseline share (lowest-priority classes first — they were
        tightened first)."""
        names = list(self._qos.classes)
        for name in reversed(names[1:]):
            base_share = self._base_shares.get(name)
            base_cap = (self.fleet.max_queue if base_share is None
                        else max(1, int(base_share
                                        * self.fleet.max_queue)))
            cap = self._class_cap(name)
            if cap < base_cap:
                new_cap = min(base_cap, cap * 2)
                cls = self._qos.classes[name]
                cls.queue_share = (base_share if new_cap == base_cap
                                   else new_cap / self.fleet.max_queue)
                return self.log.action("class_admission_relax",
                                       qos_class=name,
                                       queue_cap_from=cap,
                                       queue_cap_to=new_cap)
        return None

    def _act_overload(self, reason: str) -> Optional[Dict[str, Any]]:
        """One actuation per tick, in fixed priority order: capacity
        back first (undrain, fast-probe a broken breaker), then load
        shedding (tighten admission — per CLASS when the fleet runs a
        multi-class QoS policy, so the batch tier sheds and the
        interactive tier is untouched), then latency (shrink
        windows)."""
        fl, cfg = self.fleet, self.config
        for i, h in enumerate(fl.health):
            if h.drained:
                fl.undrain(i)
                return self.log.action("undrain", replica=i,
                                       reason=reason)
        for i, h in enumerate(fl.health):
            if h.circuit == "open" \
                    and h.cooldown_left > cfg.probe_cooldown_steps:
                h.set_cooldown(max(h.config.cooldown_steps, 1),
                               remaining=cfg.probe_cooldown_steps)
                return self.log.action(
                    "cooldown_shorten", replica=i,
                    remaining=cfg.probe_cooldown_steps, reason=reason)
        if self._qos_active:
            # per-class shed: the global max_queue (and with it the
            # interactive class's quota) is deliberately NOT touched —
            # when every lower class is already at cap 1 the next
            # lever is latency (windows), not interactive admission
            act = self._act_class_tighten(reason)
            if act is not None:
                return act
        elif fl.max_queue > cfg.min_queue:
            new = max(cfg.min_queue, fl.max_queue // 2)
            old, fl.max_queue = fl.max_queue, new
            return self.log.action("admission_tighten",
                                   max_queue_from=old,
                                   max_queue_to=new, reason=reason)
        lo, _hi = cfg.window_bounds
        for i, r in self._window_replicas():
            if int(r.window) > lo:
                old = int(r.window)
                r.set_window(max(lo, old // 2))
                return self.log.action("window_shrink", replica=i,
                                       window_from=old,
                                       window_to=int(r.window),
                                       reason=reason)
        return None

    def _act_relax(self) -> Optional[Dict[str, Any]]:
        """Undo one notch of tightening after sustained health."""
        fl, cfg = self.fleet, self.config
        if self._qos_active:
            act = self._act_class_relax()
            if act is not None:
                return act
        if fl.max_queue < self.base_max_queue:
            new = min(self.base_max_queue, fl.max_queue * 2)
            old, fl.max_queue = fl.max_queue, new
            return self.log.action("admission_relax",
                                   max_queue_from=old,
                                   max_queue_to=new)
        _lo, hi = cfg.window_bounds
        for i, r in self._window_replicas():
            base = min(hi, self._base_windows[i])
            if int(r.window) < base:
                old = int(r.window)
                r.set_window(min(base, old * 2))
                return self.log.action("window_grow", replica=i,
                                       window_from=old,
                                       window_to=int(r.window))
        return None

    def _act_scale_in(self) -> Optional[Dict[str, Any]]:
        fl = self.fleet
        healthy = [i for i, h in enumerate(fl.health)
                   if h.state == HEALTHY]
        if len(healthy) > 1:
            i = healthy[-1]
            fl.drain(i)
            return self.log.action("drain", replica=i,
                                   reason="sustained idleness")
        return None

    # -- the control tick ---------------------------------------------------
    def tick(self) -> List[Dict[str, Any]]:
        cfg = self.config
        self._ticks += 1
        sig = self._signal()
        self.last_signal = sig
        # serving MTTR rides the fleet's own accounting (failover →
        # first post-recovery progress); the log mirrors each
        # completed measurement once — _mttr_seen advances ONLY when
        # a measurement is consumed (at episode close), so one that
        # completes while the episode is still open is not lost
        fm = self.fleet.mttr()
        actions: List[Dict[str, Any]] = []
        reason = self._overloaded(sig)
        can_act = (self._ticks - self._last_action_tick
                   >= cfg.cooldown_ticks)
        if reason is not None:
            self._healthy_ticks = 0
            self._idle_ticks = 0
            self.log.open_episode(reason, tick=self._ticks)
            if (can_act and self.log.actions_this_episode
                    < cfg.max_actions_per_episode):
                act = self._act_overload(reason)
                if act is not None:
                    actions.append(act)
                    self._last_action_tick = self._ticks
        else:
            self._healthy_ticks += 1
            if self.log.in_flight:
                fresh = fm["count"] > self._mttr_seen
                self.log.close_episode(
                    mttr_s=fm["last"] if fresh else None,
                    tick=self._ticks)
            # consume measurements only on healthy ticks: one that
            # completed mid-episode is mirrored by the close above; a
            # failover absorbed without any SLO impact stays on the
            # fleet's own mttr surface and is never mis-attributed to
            # a later unrelated episode
            self._mttr_seen = fm["count"]
            if (self._healthy_ticks >= cfg.relax_after_ticks
                    and can_act):
                act = self._act_relax()
                if act is not None:
                    actions.append(act)
                    self._last_action_tick = self._ticks
            if (cfg.scale_in and sig["queue_depth"] == 0
                    and sig["inflight"] == 0):
                self._idle_ticks += 1
                if (self._idle_ticks >= cfg.idle_ticks_to_drain
                        and can_act):
                    act = self._act_scale_in()
                    if act is not None:
                        actions.append(act)
                        self._last_action_tick = self._ticks
                        self._idle_ticks = 0
            else:
                self._idle_ticks = 0
        return actions

    # -- outputs ------------------------------------------------------------
    def status(self) -> Dict[str, Any]:
        """The ``/statusz``-ready snapshot."""
        return {"ticks": self._ticks,
                "episode_open": self.log.in_flight,
                "episodes": self.log.episodes,
                "actions_total": self.log.actions_total,
                "max_actions_in_episode":
                    self.log.max_actions_in_episode,
                "max_queue": self.fleet.max_queue,
                "base_max_queue": self.base_max_queue,
                **({"class_queue_caps":
                    {name: self._class_cap(name)
                     for name in self._qos.classes}}
                   if self._qos_active else {}),
                "healthy_ticks": self._healthy_ticks,
                "last_signal": dict(self.last_signal),
                "fleet_mttr": self.fleet.mttr()}

    def record(self, **extra) -> Dict[str, Any]:
        """The serving-side ``kind: recovery`` record (fleet MTTR and
        the admission bound ride along as role extras)."""
        return self.log.record(
            max_queue=self.fleet.max_queue,
            base_max_queue=self.base_max_queue,
            fleet_mttr=self.fleet.mttr(), **extra)
