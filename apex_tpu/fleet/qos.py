"""Multi-tenant QoS: priority classes and weighted-fair admission.

The fleet used to treat every request identically — one bounded FIFO
queue, one SLO.  This module is the scheduling plane that replaces the
FIFO: a :class:`QosPolicy` names the priority classes (weight, default
deadline, queue share, preemptibility) and maps tenants onto them, and
a :class:`WfqQueue` orders the pending queue by deterministic stride
scheduling (virtual-time weighted-fair queuing) so a low-priority
flood cannot starve a high-priority trickle.

Design constraints, in order:

1. **Drop-in for the FIFO.**  ``Fleet._pending`` used to be a plain
   list and half the fleet (and its tests) touch it directly:
   ``len()``, iteration, ``remove(req)``, ``append(req)``, ``[0]``
   indexing, and the failover/drain front-requeue idiom
   ``self._pending[:0] = moved``.  ``WfqQueue`` supports every one of
   those, and under the default single-class policy its iteration
   order IS submission order — byte-for-byte FIFO, so a fleet built
   without a policy behaves exactly as before.
2. **Deterministic.**  Stride scheduling over integer virtual time:
   each class holds a persistent ``pass`` value advanced by
   ``STRIDE_SCALE // weight`` per dequeue; the merged order always
   picks the minimum pass (priority order breaks ties).  No clocks,
   no randomness — the same submissions in the same order always
   dispatch in the same order, which is what lets preemption-exactness
   tests pin tokens.
3. **No starvation either way.**  Weighted-fair means the batch class
   still drains under an interactive trickle (its pass catches up),
   and a class waking from empty inherits the minimum live pass so it
   cannot monopolize the queue with a stale low pass.

Per-class admission: ``queue_share`` bounds how much of the fleet's
``max_queue`` one class may occupy, so a flood sheds against its own
quota (per-class ``FleetOverloaded``) long before it squeezes the
interactive class out of the queue.
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Mapping, Optional, Sequence

__all__ = ["QosClass", "QosPolicy", "WfqQueue", "DEFAULT_CLASS",
           "STRIDE_SCALE"]

# Integer stride numerator.  Large enough that weight ratios up to
# ~1e5 stay exact in integer division; virtual time is unbounded
# Python int so overflow is not a concern.
STRIDE_SCALE = 1 << 20

# Name of the implicit class a policy-less fleet runs under.
DEFAULT_CLASS = "default"


class QosClass:
    """One priority class: scheduling weight plus per-class knobs.

    ``weight``       relative share of dispatch bandwidth (stride
                     scheduling: a weight-8 class dequeues 8x as often
                     as a weight-1 class under contention).
    ``deadline_s``   default request deadline applied at submit when
                     the caller did not pass one (None = no default).
    ``queue_share``  fraction of ``Fleet.max_queue`` this class may
                     occupy (None = the whole queue).  The effective
                     cap is ``max(1, int(share * max_queue))`` so a
                     tiny share never rounds to an un-admittable 0.
    ``preemptible``  whether in-flight requests of this class may be
                     evicted mid-decode to admit a higher class.
    """

    __slots__ = ("name", "weight", "deadline_s", "queue_share",
                 "preemptible")

    def __init__(self, name: str, weight: int = 1,
                 deadline_s: Optional[float] = None,
                 queue_share: Optional[float] = None,
                 preemptible: bool = True):
        if not isinstance(name, str) or not name:
            raise ValueError(f"class name must be a non-empty string, "
                             f"got {name!r}")
        if not isinstance(weight, int) or isinstance(weight, bool) \
                or weight < 1:
            raise ValueError(f"weight must be an int >= 1, got "
                             f"{weight!r}")
        if deadline_s is not None and not deadline_s > 0:
            raise ValueError(f"deadline_s must be > 0 or None, got "
                             f"{deadline_s!r}")
        if queue_share is not None and not (0.0 < queue_share <= 1.0):
            raise ValueError(f"queue_share must be in (0, 1] or None, "
                             f"got {queue_share!r}")
        self.name = name
        self.weight = weight
        self.deadline_s = deadline_s
        self.queue_share = queue_share
        self.preemptible = bool(preemptible)

    @property
    def stride(self) -> int:
        return STRIDE_SCALE // self.weight

    def spec(self) -> Dict[str, object]:
        """JSON-ready view (for /tenantz class blocks and records)."""
        return {"weight": self.weight, "deadline_s": self.deadline_s,
                "queue_share": self.queue_share,
                "preemptible": self.preemptible}

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (f"QosClass({self.name!r}, weight={self.weight}, "
                f"deadline_s={self.deadline_s}, "
                f"queue_share={self.queue_share}, "
                f"preemptible={self.preemptible})")


class QosPolicy:
    """Priority classes in rank order plus the tenant -> class map.

    ``classes`` is a sequence of :class:`QosClass` in PRIORITY order:
    the first class outranks every later one (rank 0 is highest).
    Rank decides preemption direction (only a strictly higher-ranked
    request may evict a lower-ranked one) and breaks virtual-time
    ties, so equal-pass contention resolves toward the interactive
    class deterministically.

    Untagged traffic lands in ``default_class`` — by default the LAST
    (lowest-priority) class, because anonymous traffic should never
    outrank explicitly tagged interactive requests.

    Class resolution at submit (:meth:`resolve`) is total, never
    raising: an explicit ``priority=`` naming a known class wins, then
    the tenant mapping, then the default class.  Unknown priorities
    fold to the default rather than erroring so pre-QoS callers that
    stamped free-form priority tags keep working.
    """

    def __init__(self, classes: Sequence[QosClass],
                 tenant_class: Optional[Mapping[str, str]] = None,
                 default_class: Optional[str] = None):
        if not classes:
            raise ValueError("QosPolicy needs at least one class")
        self.classes: Dict[str, QosClass] = {}
        for c in classes:
            if not isinstance(c, QosClass):
                raise TypeError(f"classes must be QosClass instances, "
                                f"got {type(c).__name__}")
            if c.name in self.classes:
                raise ValueError(f"duplicate class {c.name!r}")
            self.classes[c.name] = c
        self._rank = {name: i for i, name in enumerate(self.classes)}
        self.tenant_class: Dict[str, str] = dict(tenant_class or {})
        for t, c in self.tenant_class.items():
            if c not in self.classes:
                raise ValueError(f"tenant {t!r} maps to unknown class "
                                 f"{c!r}")
        if default_class is None:
            default_class = next(reversed(self.classes))
        if default_class not in self.classes:
            raise ValueError(f"default_class {default_class!r} is not "
                             f"a declared class")
        self.default_class = default_class

    @classmethod
    def single(cls) -> "QosPolicy":
        """The implicit policy of a QoS-less fleet: one class holding
        the whole queue — WFQ over it degenerates to exact FIFO."""
        return cls([QosClass(DEFAULT_CLASS, weight=1)])

    def resolve(self, tenant: Optional[str] = None,
                priority: Optional[str] = None) -> str:
        if priority is not None and priority in self.classes:
            return priority
        if tenant is not None:
            mapped = self.tenant_class.get(tenant)
            if mapped is not None:
                return mapped
        return self.default_class

    def rank(self, name: str) -> int:
        """0 = highest priority; unknown classes rank below all."""
        return self._rank.get(name, len(self._rank))

    def deadline_for(self, name: str) -> Optional[float]:
        c = self.classes.get(name)
        return c.deadline_s if c is not None else None

    def preemptible(self, name: str) -> bool:
        c = self.classes.get(name)
        return c.preemptible if c is not None else True

    def cap(self, name: str, max_queue: int) -> int:
        """Effective per-class queue cap under a fleet ``max_queue``."""
        c = self.classes.get(name)
        share = c.queue_share if c is not None else None
        if share is None:
            return max_queue
        return max(1, int(share * max_queue))

    def spec(self) -> Dict[str, Dict[str, object]]:
        return {name: c.spec() for name, c in self.classes.items()}


class WfqQueue:
    """List-compatible pending queue ordered by stride scheduling.

    Holds one FIFO per class plus a persistent integer ``pass`` value
    per class.  The merged iteration order simulates the scheduler:
    repeatedly take the non-empty class with the minimum pass (rank
    breaks ties), yield its head, and advance the simulated pass by
    the class stride.  The REAL pass advances in :meth:`remove` —
    i.e. when the fleet actually takes a request out (dispatch, shed
    sweep, deadline sweep) — which keeps the virtual clock in step
    with service actually consumed.

    Front-requeue (``q[:0] = moved``, the failover/drain idiom)
    reinserts each request at the head of its own class queue without
    touching virtual time, mirroring what the old list did: a
    reclaimed request goes back to the front of ITS line, not the
    front of everyone's.
    """

    def __init__(self, policy: Optional[QosPolicy] = None):
        self.policy = policy or QosPolicy.single()
        self._q: Dict[str, List[object]] = {
            name: [] for name in self.policy.classes}
        self._pass: Dict[str, int] = {
            name: 0 for name in self.policy.classes}

    # -- class helpers ----------------------------------------------

    def class_of(self, req: object) -> str:
        name = getattr(req, "qos_class", None)
        if name is None or name not in self._q:
            return self.policy.default_class
        return name

    def class_depths(self) -> Dict[str, int]:
        return {name: len(q) for name, q in self._q.items()}

    def depth(self, name: str) -> int:
        return len(self._q.get(name, ()))

    # -- the stride schedule ----------------------------------------

    def _order(self) -> List[object]:
        passes = dict(self._pass)
        idx = {name: 0 for name in self._q}
        out: List[object] = []
        names = list(self.policy.classes)  # rank order = tiebreak
        remaining = sum(len(q) for q in self._q.values())
        while remaining:
            best = None
            for name in names:
                if idx[name] >= len(self._q[name]):
                    continue
                if best is None or passes[name] < passes[best]:
                    best = name
            q = self._q[best]
            out.append(q[idx[best]])
            idx[best] += 1
            passes[best] += self.policy.classes[best].stride
            remaining -= 1
        return out

    def _catch_up(self, name: str) -> None:
        # A class waking from empty inherits the minimum live pass so
        # a long-idle class cannot replay its idle time as credit.
        live = [self._pass[n] for n, q in self._q.items()
                if q and n != name]
        if live:
            self._pass[name] = max(self._pass[name], min(live))

    # -- list protocol (the Fleet._pending contract) -----------------

    def append(self, req: object) -> None:
        name = self.class_of(req)
        if not self._q[name]:
            self._catch_up(name)
        self._q[name].append(req)

    def remove(self, req: object) -> None:
        name = self.class_of(req)
        try:
            self._q[name].remove(req)
        except ValueError:
            # class tag mutated after enqueue — fall back to a sweep
            for q in self._q.values():
                if req in q:
                    q.remove(req)
                    break
            else:
                raise
        self._pass[name] += self.policy.classes[name].stride \
            if name in self.policy.classes else STRIDE_SCALE

    def __len__(self) -> int:
        return sum(len(q) for q in self._q.values())

    def __iter__(self) -> Iterator[object]:
        return iter(self._order())

    def __getitem__(self, i):
        order = self._order()
        return order[i]

    def __setitem__(self, key, value) -> None:
        # Only the front-requeue idiom ``q[:0] = moved`` is supported;
        # anything else on a scheduled queue is a bug.
        if not (isinstance(key, slice) and key.start is None
                and key.stop == 0 and key.step is None):
            raise TypeError("WfqQueue only supports front-requeue "
                            "slice assignment q[:0] = [...]")
        for req in reversed(list(value)):
            name = self.class_of(req)
            if not self._q[name]:
                self._catch_up(name)
            self._q[name].insert(0, req)

    def __bool__(self) -> bool:
        return len(self) > 0

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (f"WfqQueue({self.class_depths()}, "
                f"passes={self._pass})")
