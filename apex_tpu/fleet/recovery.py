"""Self-healing training: the telemetry→action loop, training side.

The observability plane (PRs 6–10) *reports* — RunSupervisor verdicts,
``checkpoint_saved`` durability watermarks, flight-ring fault events.
This module closes the loop: :class:`ElasticTrainer` drives a
data-parallel training run that SURVIVES a replica death mid-step
instead of 503ing until a human arrives.  On a
:class:`~apex_tpu.fleet.faults.ReplicaFault` (or a configured
supervisor verdict — NaN, stall, divergence) it

1. **shrinks the data axis** to the surviving world size and re-jits
   the step there (``build_step(world)`` — the caller's closure builds
   the mesh over the survivors; ``predivide_factors`` and the DDP
   comm plan rescale automatically at trace time because both read
   the mapped axis size, and the ``ddp_resnet18_o2_hier_world4``
   analysis entry point pins that the shrunk step's collectives lint
   clean against the plan recomputed at the new world);
2. **redistributes ZeRO-1 optimizer shards** onto the survivors
   (:func:`reshard_flat_state`: every flat shard buffer padded for
   the old world is sliced back to its logical length and re-padded
   for the new one);
3. **resumes from the last durable snapshot** — candidates newest
   first, each verified by its content checksum
   (:class:`~apex_tpu.utils.checkpoint.CheckpointCorrupt` skips a
   torn write and falls back), so the ``checkpoint_saved`` events the
   supervisor watermarks are exactly the resume-point oracle;
4. accounts **MTTR** — fault injection to the first committed
   post-recovery step — on the flight ring, the metrics registry, and
   the ``kind: recovery`` JSONL record
   (``observability.exporters.validate_recovery_record``).

While a recovery is in flight the supervisor reports the distinct
degraded-but-live ``recovering`` state
(:meth:`~apex_tpu.observability.supervisor.RunSupervisor.begin_recovery`),
so ``/healthz`` says "being handled" instead of flapping an
orchestrator into a restart loop mid-shrink.

:class:`RecoveryLog` is the shared episode/action/MTTR bookkeeping —
the serving-side controller (:mod:`apex_tpu.fleet.autoscale`) uses the
same log, so both directions of the loop emit one record shape.

Preemption (PR 12).  The most common failure on real TPU fleets is not
a crash but a PLANNED maintenance/preemption event: SIGTERM with a
grace window.  :class:`PreemptionGuard` turns that signal (or a
programmatic :meth:`~PreemptionGuard.preempt` — what the
``TrainingFaults.preemption`` window calls) into a request the trainer
honors at its next STEP BOUNDARY: a coordinated emergency snapshot —
model/optimizer tree plus the data pipeline's exported cursor
(``data_state``) under one content checksum — then a clean exit with
``verdict == "preempted"`` instead of dying mid-write.  A new trainer
built with ``resume=True`` restores the latest durable snapshot AND
the data cursor, so the resumed run's loss trajectory and consumed
sample-index sequence are bitwise-identical to an undisturbed run
(the acceptance pin in tests/test_recovery.py).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .faults import ReplicaFault

__all__ = ["RECOVERY_ROLES", "RECOVERY_ACTION_KINDS", "RECOVERY_CAUSES",
           "RecoveryError", "RecoveryLog", "PreemptionGuard",
           "ElasticConfig", "ElasticTrainer", "reshard_flat_state"]

# both directions of the telemetry→action loop emit the same
# ``kind: recovery`` record; ``role`` says which controller wrote it
RECOVERY_ROLES = ("training", "serving")

# every action a controller may take (exporters.validate_recovery_record
# rejects records naming anything else; a test pins the two tuples
# equal, the RUN_ANOMALY_KINDS discipline):
# training — world_shrink (drop dead replicas from the data axis),
#   resume (restore the last durable snapshot + re-jit), rollback
#   (verdict-triggered restore at the SAME world), preempt_snapshot
#   (the coordinated emergency snapshot a preemption notice triggers
#   at the next step boundary, within the grace budget);
# serving — admission_tighten/relax (the fleet's bounded-queue knob),
#   class_admission_tighten/relax (PR 19: the same knob scoped to ONE
#   QoS class's queue quota — tighten the lowest-priority class first,
#   never rank 0, so interactive admission survives a batch flood),
#   window_shrink/grow (decode window on replicas that support it),
#   drain/undrain (capacity out/in), cooldown_shorten/extend (the
#   breaker's step-counted cooldowns).
RECOVERY_ACTION_KINDS = (
    "world_shrink", "resume", "rollback", "preempt_snapshot",
    "admission_tighten", "admission_relax",
    "class_admission_tighten", "class_admission_relax",
    "window_shrink", "window_grow",
    "drain", "undrain",
    "cooldown_shorten", "cooldown_extend")

# why a recovery/exit happened, when a record says (schema v7):
# fault = an injected/real replica death, verdict = a supervisor
# anomaly triggered the rollback, preemption = a planned SIGTERM /
# maintenance notice honored at a step boundary.  Duplicated
# stdlib-side in observability.exporters (tuple-pinned by a test).
RECOVERY_CAUSES = ("fault", "verdict", "preemption")


class RecoveryError(RuntimeError):
    """Recovery itself failed (no survivors to shrink onto, no durable
    snapshot, recovery budget exhausted) — the point where a human IS
    needed and a loud failure beats a silent loop."""


class RecoveryLog:
    """Episode / action / MTTR bookkeeping shared by both controllers.

    An EPISODE opens on the transition into a sick state (fault caught,
    SLO breached) and closes when the controller declares the system
    recovered; every actuation lands as an ACTION inside the current
    episode.  Actions are bounded per episode by the caller's config —
    the anti-oscillation contract ``tests/ci/chaos_smoke.py`` gates —
    and the retained detail list is bounded like the supervisor's
    anomaly list (counts exact forever, details flight-ring
    discipline).  MTTR is fault-to-first-good-step, fed by the caller
    at the instants it owns."""

    def __init__(self, role: str, subject: str,
                 clock: Callable[[], float] = time.perf_counter,
                 max_actions: int = 256, ring=None, registry=None):
        if role not in RECOVERY_ROLES:
            raise ValueError(f"role must be one of {RECOVERY_ROLES}, "
                             f"got {role!r}")
        if not subject:
            raise ValueError("subject must be non-empty")
        self.role = role
        self.subject = str(subject)
        self._clock = clock
        self._t0 = clock()
        self._ring = ring
        self.registry = registry
        self.episodes = 0
        self.actions_total = 0
        self.max_actions_in_episode = 0
        self._actions_this_episode = 0
        self._episode_open = False
        self._episode_t0: Optional[float] = None
        self._actions: deque = deque(maxlen=max_actions)
        self._mttr_count = 0
        self._mttr_sum = 0.0
        self._mttr_last: Optional[float] = None

    @property
    def ring(self):
        from ..observability import flightrec
        return flightrec.resolve(self._ring)

    def _reg(self):
        from ..observability.metrics import get_registry
        return self.registry if self.registry is not None \
            else get_registry()

    @property
    def in_flight(self) -> bool:
        return self._episode_open

    @property
    def actions_this_episode(self) -> int:
        return self._actions_this_episode

    def open_episode(self, reason: str, **attrs):
        """Transition into a sick state (idempotent while open)."""
        if self._episode_open:
            return
        self._episode_open = True
        self.episodes += 1
        self._actions_this_episode = 0
        self._episode_t0 = self._clock()
        self.ring.append("recovery_started", role=self.role,
                         subject=self.subject, reason=reason,
                         episode=self.episodes, **attrs)
        self._reg().counter(
            "recovery_episodes_total",
            help="telemetry→action recovery episodes opened"
        ).labels(role=self.role).inc()

    def action(self, kind: str, **detail) -> Dict[str, Any]:
        """One actuation inside the current episode."""
        if kind not in RECOVERY_ACTION_KINDS:
            raise ValueError(f"unknown recovery action {kind!r} "
                             f"(known: {RECOVERY_ACTION_KINDS})")
        t = self._clock() - self._t0
        if t < 0:
            # catch the PR 11 gotcha AT THE SOURCE: a negative offset
            # means this log's t0 predates the current clock reading —
            # the fleet/controller/trainer was constructed BEFORE an
            # injected tick clock was reset.  Failing here, with the
            # remedy, beats the validator rejecting the finished
            # record later in validate_recovery_record.
            raise ValueError(
                f"RecoveryLog t_s went negative ({t:.6f}s): the log "
                f"was constructed before its clock was reset (an "
                f"injected tick clock rewound past the log's t0). "
                f"Reset the clock FIRST, then build the fleet and "
                f"controller/trainer — the bench --chaos drive() "
                f"precondition.")
        # an action before ANY episode (e.g. a relax correcting a
        # mis-tuned construction) carries episode=None — stamping a
        # phantom episode 1 into a record declaring zero episodes
        # would fail its own validator
        ev = {"kind": kind,
              "episode": self.episodes if self.episodes else None,
              "t_s": round(t, 6)}
        ev.update({k: v for k, v in detail.items() if v is not None})
        self.actions_total += 1
        if self._episode_open:
            # only in-episode actuation counts toward the per-episode
            # oscillation bound — the relax actions a controller takes
            # AFTER declaring recovery are the unwinding, not the
            # thrashing the bound exists to catch
            self._actions_this_episode += 1
            self.max_actions_in_episode = max(
                self.max_actions_in_episode,
                self._actions_this_episode)
        self._actions.append(ev)
        self.ring.append("recovery_action", role=self.role,
                         subject=self.subject,
                         **{("action" if k == "kind" else k): v
                            for k, v in ev.items()})
        self._reg().counter(
            "recovery_actions_total",
            help="recovery-controller actuations by kind"
        ).labels(role=self.role, kind=kind).inc()
        return ev

    def close_episode(self, mttr_s: Optional[float] = None, **attrs):
        """The system recovered; ``mttr_s`` is fault-to-first-good-step
        when the caller measured one."""
        if not self._episode_open:
            return
        self._episode_open = False
        if mttr_s is not None:
            mttr_s = float(mttr_s)
            self._mttr_count += 1
            self._mttr_sum += mttr_s
            self._mttr_last = mttr_s
            self._reg().histogram(
                "recovery_mttr_seconds",
                help="fault injection to first post-recovery step"
            ).observe(mttr_s)
        self.ring.append("recovery_done", role=self.role,
                         subject=self.subject, episode=self.episodes,
                         actions=self._actions_this_episode,
                         mttr_s=(round(mttr_s, 6)
                                 if mttr_s is not None else None),
                         **attrs)

    def mttr(self) -> Dict[str, Any]:
        return {"last": self._mttr_last,
                "mean": (self._mttr_sum / self._mttr_count
                         if self._mttr_count else None),
                "count": self._mttr_count}

    def record(self, **extra) -> Dict[str, Any]:
        """One ``kind: recovery`` JSONL payload (enrich through
        ``JsonlExporter``; ``exporters.validate_recovery_record`` pins
        the shape)."""
        rec: Dict[str, Any] = {
            "kind": "recovery", "role": self.role,
            "subject": self.subject,
            "episodes": self.episodes,
            "actions_total": self.actions_total,
            "max_actions_in_episode": self.max_actions_in_episode,
            "actions": [dict(a) for a in self._actions],
            "mttr_s": self.mttr(),
            "in_flight": self._episode_open,
            "duration_s": round(self._clock() - self._t0, 6),
        }
        rec.update(extra)
        return rec


class PreemptionGuard:
    """Turn a preemption notice into a step-boundary snapshot request.

    Real TPU fleets preempt with SIGTERM plus a grace window;
    :meth:`install` registers a handler for it (restoring the previous
    handler on :meth:`uninstall` / context exit), and
    :meth:`preempt` is the programmatic entry point — what the handler
    calls, and what ``TrainingFaults(preemption=...)`` calls in tests.
    The guard never acts on its own: it records the request (first one
    wins, later ones are no-ops), stamps the grace clock, appends a
    ``preemption_requested`` flight-ring event and bumps
    ``preemptions_total``; the :class:`ElasticTrainer` polls
    :attr:`requested` at every step boundary and, with grace left,
    writes the coordinated emergency snapshot (tree + ``data_state``)
    before exiting with a ``preempted`` verdict — with the grace
    budget already exhausted it exits WITHOUT starting a write a
    torn-snapshot cleanup would have to mop up."""

    def __init__(self, grace_s: float = 30.0,
                 clock: Callable[[], float] = time.perf_counter,
                 ring=None, registry=None):
        if grace_s < 0:
            raise ValueError(f"grace_s must be >= 0, got {grace_s}")
        self.grace_s = float(grace_s)
        self._clock = clock
        self._ring = ring
        self.registry = registry
        self._reason: Optional[str] = None
        self._t0: Optional[float] = None
        self._installed: Dict[int, Any] = {}

    @property
    def ring(self):
        from ..observability import flightrec
        return flightrec.resolve(self._ring)

    def _reg(self):
        from ..observability.metrics import get_registry
        return self.registry if self.registry is not None \
            else get_registry()

    # -- the request --------------------------------------------------------
    def preempt(self, reason: str = "programmatic") -> None:
        """Request a coordinated shutdown (idempotent: the FIRST
        request starts the grace clock; repeats are no-ops)."""
        if self._reason is not None:
            return
        self._reason = str(reason) or "programmatic"
        self._t0 = self._clock()
        self.ring.append("preemption_requested", reason=self._reason,
                         grace_s=self.grace_s)
        self._reg().counter(
            "preemptions_total",
            help="preemption notices received (signal or programmatic)"
        ).inc()

    @property
    def requested(self) -> bool:
        return self._reason is not None

    @property
    def reason(self) -> Optional[str]:
        return self._reason

    @property
    def requested_at(self) -> Optional[float]:
        """Clock reading of the first :meth:`preempt` call (the MTTR
        window's left edge), ``None`` before any request."""
        return self._t0

    def grace_remaining(self) -> float:
        """Seconds of grace budget left (the full budget before any
        request; clamped at 0)."""
        if self._t0 is None:
            return self.grace_s
        return max(0.0, self.grace_s - (self._clock() - self._t0))

    def reset(self) -> None:
        """Clear the request (a resumed test harness reusing one
        guard; production resumes build a fresh process anyway)."""
        self._reason = None
        self._t0 = None

    # -- the signal surface -------------------------------------------------
    def _handle(self, signum, frame):
        self.preempt(f"signal {signum}")

    def install(self, signals=None) -> "PreemptionGuard":
        """Register the handler (default: SIGTERM — what TPU
        maintenance/preemption sends); previous handlers are kept and
        restored by :meth:`uninstall`.  Main-thread only, per the
        stdlib signal contract."""
        import signal as _signal
        if signals is None:
            signals = (_signal.SIGTERM,)
        for s in signals:
            if s in self._installed:
                # already ours: re-installing would record OUR handler
                # as "previous" and uninstall could never restore the
                # original one
                continue
            self._installed[s] = _signal.signal(s, self._handle)
        return self

    def uninstall(self) -> None:
        import signal as _signal
        for s, prev in self._installed.items():
            _signal.signal(s, prev)
        self._installed = {}

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


def reshard_flat_state(tree: Any, total: int, old_world: int,
                       new_world: int) -> Any:
    """Redistribute ZeRO flat optimizer shards onto a resized world.

    The flat-buffer ZeRO state (``amp.zero_optimizer_specs``) pads
    every 1-D shard buffer — fp32 masters and the elementwise inner
    optimizer's moment buffers — to a multiple of the shard population
    so the device-concat global splits evenly.  ``total`` is the
    logical (unpadded) element count
    (``opt_state.masters.layout.total``); every 1-D leaf of exactly
    the old padded length is sliced back to ``total`` and
    zero-re-padded for the new population.  Scalars and non-flat
    leaves pass through unchanged.  Host-side numpy math — the
    resharded tree is handed to the re-jitted step, whose shard_map
    in_specs place the new shards on the survivors.

    ``old_world`` / ``new_world`` are the shard POPULATIONS, which is
    what the buffers were padded for: the full axis size for ZeRO-1,
    the ICI slice size (``layout.zero_ici``) for ZeRO-2/3 — an 8->4
    world shrink at ici 4->2 resharding stage-2/3 state passes (4, 2)
    here while the ZeRO-1 leg of the same shrink passes (8, 4).  The
    math is identical: stage 2/3 state is replicated across slices, so
    redistributing one slice's padding redistributes them all."""
    if old_world < 1 or new_world < 1:
        raise ValueError(f"world sizes must be >= 1, got {old_world} "
                         f"and {new_world}")
    import jax
    old_pad = total + (-total) % old_world
    new_pad = total + (-total) % new_world

    def fix(leaf):
        arr = np.asarray(leaf)
        if arr.ndim == 1 and arr.shape[0] == old_pad:
            return np.pad(arr[:total], (0, new_pad - total))
        return arr

    return jax.tree_util.tree_map(fix, tree)


class ElasticConfig:
    """Recovery policy knobs.

    - ``checkpoint_every``: snapshot cadence in committed steps (the
      recovery controller can only resume from what was saved);
    - ``shrink_factor`` / ``min_world``: a replica death divides the
      world by ``shrink_factor`` (data-parallel replicas die in
      slices), never below ``min_world`` — shrinking past it raises
      :class:`RecoveryError` instead of limping on;
    - ``max_recoveries``: total recovery budget for the run (a run
      that keeps dying needs a human, not an infinite loop);
    - ``recover_on_verdicts``: supervisor anomaly kinds that trigger a
      rollback-restore (NaN'd loss, stall, replica divergence);
      ``shrink_on_verdict`` additionally shrinks the world on those —
      off by default, since a NaN is usually numerics, not hardware.
    """

    def __init__(self, checkpoint_every: int = 1,
                 shrink_factor: int = 2,
                 min_world: int = 1,
                 max_recoveries: int = 8,
                 recover_on_verdicts=("nan", "stall",
                                      "replica_divergence"),
                 shrink_on_verdict: bool = False):
        if checkpoint_every < 1:
            raise ValueError(f"checkpoint_every must be >= 1, got "
                             f"{checkpoint_every}")
        if shrink_factor < 2:
            raise ValueError(f"shrink_factor must be >= 2, got "
                             f"{shrink_factor}")
        if min_world < 1:
            raise ValueError(f"min_world must be >= 1, got {min_world}")
        if max_recoveries < 1:
            raise ValueError(f"max_recoveries must be >= 1, got "
                             f"{max_recoveries}")
        self.checkpoint_every = checkpoint_every
        self.shrink_factor = shrink_factor
        self.min_world = min_world
        self.max_recoveries = max_recoveries
        self.recover_on_verdicts = tuple(recover_on_verdicts)
        self.shrink_on_verdict = shrink_on_verdict


class ElasticTrainer:
    """Elastic data-parallel run harness: the job survives the fleet.

    The caller supplies the world-parameterized pieces; the harness
    owns the loop, the snapshots, and the recovery policy::

        trainer = ElasticTrainer(
            build_step=build,          # build(world) -> jitted step
            state=state0,              # live state for `world`
            world=8, ckpt_dir=d,
            to_host=to_host,           # state -> canonical host tree
            from_host=from_host,       # (tree, world) -> live state
            supervisor=sup, faults=faults)
        history = trainer.run(steps, data_fn)   # data_fn(i) -> batch

    Contracts:

    - ``build_step(world)`` returns ``step(state, batch) ->
      (new_state, loss)`` jitted over a mesh of the first ``world``
      devices; the harness re-invokes it after every shrink (the
      predivide factors and the comm plan rescale at trace time);
    - ``to_host(state)`` produces a WORLD-INDEPENDENT canonical host
      tree (for ZeRO-1, slice the padded flat shards back to their
      logical length — :func:`reshard_flat_state` composed with the
      identity is the common shape); ``from_host(tree, world)``
      re-shards it for ``world``.  Defaults are plain ``np.asarray``
      round-trips, correct for fully replicated DDP state;
    - the harness calls ``faults.check_step`` AFTER the device math
      but BEFORE committing the result — an injected
      :class:`ReplicaFault` therefore models a mid-step death whose
      partial results are abandoned, exactly what resuming from the
      last durable snapshot assumes;
    - a committed step closes any open MTTR window (fault-to-first-
      good-step), feeds the supervisor (whose configured verdicts
      trigger rollback), and snapshots on the ``checkpoint_every``
      cadence.

    ``history`` rows are ``(step, loss, world)``; ``record()`` emits
    the ``kind: recovery`` JSONL payload with the training extras
    (current world, resumed step, recovery count)."""

    def __init__(self, build_step: Callable[[int], Callable],
                 state: Any, *, world: int, ckpt_dir: str,
                 to_host: Optional[Callable[[Any], Any]] = None,
                 from_host: Optional[Callable[[Any, int], Any]] = None,
                 supervisor=None, faults=None,
                 config: Optional[ElasticConfig] = None,
                 checkpointer=None, run: str = "elastic",
                 clock: Callable[[], float] = time.perf_counter,
                 ring=None, registry=None,
                 data=None, guard: Optional[PreemptionGuard] = None,
                 resume: bool = False):
        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        self.build_step = build_step
        self.world = int(world)
        self.ckpt_dir = ckpt_dir
        self.config = config or ElasticConfig()
        self.supervisor = supervisor
        self.faults = faults
        self._clock = clock
        if checkpointer is None:
            from ..utils import checkpoint as checkpointer
        self._ckpt = checkpointer
        self._to_host = to_host if to_host is not None else (
            lambda st: _np_tree(st))
        self._from_host = from_host if from_host is not None else (
            lambda tree, w: tree)
        self._state = state
        self._step = 0
        self._step_fn = build_step(self.world)
        self.recoveries = 0
        self.resumed_step: Optional[int] = None
        self.history: List[tuple] = []
        self.log = RecoveryLog("training", run, clock=clock,
                               ring=ring, registry=registry)
        self._registry = registry
        self._mttr_t0: Optional[float] = None
        # data pipeline with the state protocol (state_dict /
        # load_state_dict, e.g. apex_tpu.data.DataLoader): its cursor
        # is folded into every snapshot/restore so the sample stream
        # resumes bitwise-identically
        self.data = data
        self.guard = guard
        # the trainer's exit verdict: None while running, "completed"
        # after a full run() call, "preempted" after a guard-honoring
        # exit; cause names why the LAST recovery/exit happened
        self.verdict: Optional[str] = None
        self.cause: Optional[str] = None
        # resume accounting (the bench --chaos preempt leg's line):
        # wall cost of the resume=True restore, and the clock reading
        # of the first COMMITTED step of this trainer — with the
        # guard's requested_at, the preempt→first-good-step MTTR
        self.resume_overhead_s: Optional[float] = None
        self.first_commit_at: Optional[float] = None
        self._last_saved_step: Optional[int] = None
        if (guard is not None and faults is not None
                and getattr(faults, "guard", None) is None):
            # auto-wire: a TrainingFaults preemption window fires into
            # THIS run's guard unless the harness bound its own
            faults.guard = guard
        if resume:
            self._resume_from_disk()

    # -- snapshots ----------------------------------------------------------
    def _save(self):
        self._last_saved_step = self._step
        tree = self._to_host(self._state)
        if self.data is not None:
            # the snapshot names its exact data cursor, under the same
            # content checksum as the tree — tree and stream can never
            # restore out of step with each other
            path = self._ckpt.save_checkpoint(
                self.ckpt_dir, self._step, tree,
                data_state=self.data.state_dict())
        else:
            path = self._ckpt.save_checkpoint(self.ckpt_dir,
                                              self._step, tree)
        if self.faults is not None:
            # torn-write injection happens AFTER the atomic rename —
            # the save-time checkpoint_saved event truthfully named a
            # snapshot that verified; the tear is what restore-time
            # verification exists to catch
            self.faults.after_checkpoint(path)
        return path

    def _restore_latest_durable(self):
        """Newest snapshot that verifies, restored into the canonical
        host template (plus its data_state when a pipeline is
        attached); torn snapshots are skipped with a ring note."""
        template = self._to_host(self._state)
        from ..utils.checkpoint import CheckpointCorrupt
        for step in reversed(self._ckpt.available_steps(self.ckpt_dir)):
            try:
                tree = self._ckpt.restore_checkpoint(
                    self.ckpt_dir, template, step=step)
            except CheckpointCorrupt as e:
                self.log.ring.append("snapshot_skipped", step=step,
                                     reason=str(e))
                continue
            ds = None
            if self.data is not None:
                loader = getattr(self._ckpt, "load_data_state", None)
                ds = loader(self.ckpt_dir, step=step) \
                    if loader is not None else None
                if ds is None:
                    # LOUD, not a silent divergence: a pipeline is
                    # attached but this snapshot cannot say where its
                    # sample stream stood
                    raise RecoveryError(
                        f"snapshot step {step} in {self.ckpt_dir!r} "
                        f"carries no data_state but a data pipeline "
                        f"is attached — the sample stream cannot "
                        f"resume deterministically (save through this "
                        f"trainer, or detach the pipeline)")
            return step, tree, ds
        raise RecoveryError(
            f"no durable snapshot in {self.ckpt_dir!r} — every "
            f"candidate failed content verification")

    def _apply_restore(self, step: int, tree: Any, ds) -> None:
        self._state = self._from_host(tree, self.world)
        self._step = step
        self.resumed_step = step
        if ds is not None:
            self.data.load_state_dict(ds)

    def _resume_from_disk(self) -> bool:
        """``resume=True`` construction: continue from the newest
        durable snapshot (tree + data cursor) when one exists; a fresh
        directory is just a fresh run."""
        if not self._ckpt.available_steps(self.ckpt_dir):
            return False
        t0 = self._clock()
        step, tree, ds = self._restore_latest_durable()
        self._apply_restore(step, tree, ds)
        self.resume_overhead_s = self._clock() - t0
        self.log.action("resume", step=step, world=self.world,
                        resumed_from="disk")
        self._reg_world()
        return True

    # -- recovery -----------------------------------------------------------
    def _recover(self, reason: str, shrink: bool,
                 cause: str = "fault"):
        cfg = self.config
        self.cause = cause
        if self.recoveries >= cfg.max_recoveries:
            raise RecoveryError(
                f"recovery budget exhausted ({cfg.max_recoveries}); "
                f"last failure: {reason}")
        self.recoveries += 1
        self.log.open_episode(reason, world=self.world,
                              step=self._step)
        if self.supervisor is not None:
            self.supervisor.begin_recovery(reason)
        try:
            old_world = self.world
            if shrink:
                new_world = max(cfg.min_world,
                                self.world // cfg.shrink_factor)
                if new_world == self.world:
                    raise RecoveryError(
                        f"no survivors to shrink onto (world "
                        f"{self.world} is already min_world "
                        f"{cfg.min_world}); last failure: {reason}")
                self.world = new_world
                self.log.action("world_shrink", world_from=old_world,
                                world_to=new_world)
            step, tree, ds = self._restore_latest_durable()
            if shrink:
                # the mesh changed: re-jit the step on the survivors
                # (predivide factors + comm plan rescale at trace time)
                self._step_fn = self.build_step(self.world)
            self._apply_restore(step, tree, ds)
            self.log.action("resume" if shrink else "rollback",
                            step=step, world=self.world)
            if self.supervisor is not None:
                # the run rewound: reset the progress watermark so a
                # long replay below the old high-water mark cannot
                # fire a spurious stall verdict (and a second,
                # pointless rollback)
                self.supervisor.rewind(step)
            self._reg_world()
        finally:
            if self.supervisor is not None:
                self.supervisor.end_recovery()

    def _reg_world(self):
        from ..observability.metrics import get_registry
        reg = (self._registry if self._registry is not None
               else get_registry())
        reg.gauge("elastic_world_size",
                  help="current data-parallel world of the elastic run"
                  ).labels(run=self.log.subject).set(float(self.world))

    # -- preemption ---------------------------------------------------------
    def _preempt_exit(self):
        """Honor a preemption request at the step boundary: with grace
        budget left, write the coordinated emergency snapshot (tree +
        data cursor, one checksum) and exit ``preempted``; with the
        budget already gone, exit WITHOUT starting a write — the last
        durable snapshot stays the resume point, and nobody has to
        mop up a torn one."""
        g = self.guard
        left = g.grace_remaining()
        snapshotted = False
        if left > 0:
            # the cadence save at the end of the last iteration may
            # already cover this exact step — don't burn grace-window
            # time re-serializing identical content
            reused = self._last_saved_step == self._step
            if not reused:
                self._save()
            snapshotted = True
            self.log.action("preempt_snapshot", step=self._step,
                            world=self.world,
                            grace_left_s=round(left, 6),
                            reused_cadence_save=reused)
        else:
            self.log.ring.append("preemption_grace_exhausted",
                                 step=self._step, reason=g.reason)
        self.cause = "preemption"
        self.verdict = "preempted"
        if self.supervisor is not None:
            self.supervisor.mark_preempted(step=self._step,
                                           reason=g.reason)
        self.log.ring.append("preempted", step=self._step,
                             world=self.world, reason=g.reason,
                             snapshot=snapshotted)

    # -- the loop -----------------------------------------------------------
    def run(self, num_steps: int,
            data_fn: Optional[Callable[[int], Any]] = None
            ) -> List[tuple]:
        """Drive the run to ``num_steps`` committed steps, recovering
        through any scheduled faults; returns the history rows
        ``(step, loss, world)`` committed by THIS call.

        ``data_fn(i) -> batch`` produces the batch for run-step ``i``;
        when omitted, the attached ``data=`` pipeline feeds the run
        (``next_batch()``; its checkpointed cursor — not the step
        index — is then what makes the stream deterministic across
        preemption, rollback, and elastic world changes).  A
        ``PreemptionGuard`` request is honored at the next step
        boundary: emergency snapshot within the grace budget, then a
        clean exit with ``verdict == "preempted"``."""
        cfg = self.config
        if data_fn is None:
            if self.data is None:
                raise ValueError(
                    "run() needs data_fn or a data= pipeline")
            data_fn = lambda i: self.data.next_batch()[:2]  # noqa: E731
        self.verdict = None
        out: List[tuple] = []
        if not self._ckpt.available_steps(self.ckpt_dir):
            self._save()                  # step-0 fallback snapshot
        while self._step < num_steps:
            if self.guard is not None and self.guard.requested:
                self._preempt_exit()
                return out
            batch = data_fn(self._step)
            t0 = self._clock()
            try:
                new_state, loss = self._step_fn(self._state, batch)
                loss = float(loss)        # host fetch = commit point
                if self.faults is not None:
                    self.faults.check_step(self._step)
            except ReplicaFault as e:
                if self._mttr_t0 is None:
                    # a second death before the first committed
                    # post-recovery step EXTENDS the same MTTR window
                    # (the fleet-side contract) — never restart it
                    self._mttr_t0 = self._clock()
                self._recover(f"replica death: {e}", shrink=True,
                              cause="fault")
                continue
            dt = self._clock() - t0
            self._state = new_state
            if self.first_commit_at is None:
                self.first_commit_at = self._clock()
            row = (self._step, loss, self.world)
            self.history.append(row)
            out.append(row)
            self._step += 1
            if self._mttr_t0 is not None:
                # first committed step after a recovery closes MTTR
                self.log.close_episode(
                    mttr_s=self._clock() - self._mttr_t0,
                    step=self._step, world=self.world)
                self._mttr_t0 = None
            elif self.log.in_flight:
                self.log.close_episode(step=self._step,
                                       world=self.world)
            anomalies = []
            if self.supervisor is not None:
                anomalies = self.supervisor.observe_step(
                    step=self._step, loss=loss, step_time_s=dt)
            trigger = [a for a in anomalies
                       if a.get("kind") in cfg.recover_on_verdicts]
            if trigger:
                # verdict-triggered rollback: do NOT snapshot the sick
                # state — restore the last durable one instead
                if self._mttr_t0 is None:
                    self._mttr_t0 = self._clock()
                self._recover(
                    f"supervisor verdict: "
                    f"{trigger[0].get('kind')}",
                    shrink=cfg.shrink_on_verdict, cause="verdict")
                continue
            if self._step % cfg.checkpoint_every == 0:
                self._save()
        self.verdict = "completed"
        return out

    def record(self, **extra) -> Dict[str, Any]:
        """The training-side ``kind: recovery`` record (schema v7:
        plus ``cause``/``preempted`` and — when a pipeline is
        attached — its ``data_state`` census, so the record names the
        exact sample-stream position the run stood at)."""
        fields: Dict[str, Any] = dict(
            world=self.world, recoveries=self.recoveries,
            resumed_step=self.resumed_step,
            preempted=(self.verdict == "preempted"))
        if self.cause is not None:
            fields["cause"] = self.cause
        if self.data is not None:
            fields["data_state"] = self.data.state_dict()
        fields.update(extra)
        return self.log.record(**fields)


def _np_tree(tree: Any) -> Any:
    import jax
    return jax.tree_util.tree_map(np.asarray, tree)
