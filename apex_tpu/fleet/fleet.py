"""The fleet: N serving replicas behind one submit/step/result API.

``Fleet`` owns the request lifecycle end to end:

- ``submit`` validates nothing about shapes (replicas do that at
  dispatch) but enforces BACKPRESSURE: the fleet queue is bounded, and
  a full queue raises :class:`router.FleetOverloaded` instead of
  growing without bound — the explicit shed the single engine's
  ``_waiting`` list never had.
- ``step()`` is one cooperative fleet tick: breaker cooldowns advance,
  deadlines are enforced, queued requests dispatch through the routing
  policy onto admissible replicas (free slot, or a short per-replica
  queue of depth ``replica_queue_cap`` so engines can admit at their
  own window boundaries), every steppable replica takes one ``step()``
  with latency + errors feeding its :class:`health.ReplicaHealth`,
  finishes are harvested, and a replica whose dispatch raised — or
  that sat silent on live work past the stall watchdog — FAILS OVER:
  its in-flight and queued requests are reclaimed (best-effort
  cancelled on the sick replica) and restarted from their prompts on
  survivors.
- ``result`` returns the request's final tokens from the replica that
  actually finished it.  Because a failed-over request restarts from
  its prompt and greedy / explicitly-seeded sampled decodes are
  request-intrinsic, those final tokens are token-for-token what an
  undisturbed single engine produces (pinned in tests/test_fleet.py).
  ``step()``'s incremental emissions, by contrast, are at-least-once
  across a failover (the restart re-emits from the beginning) —
  consume ``result()`` for exactness, emissions for liveness.

Drain (rolling restart): ``drain(i)`` stops admission, re-enqueues the
replica's waiting queue onto the fleet (→ survivors), and keeps
stepping its in-flight requests until they finish, at which point the
replica parks as ``drained``; ``undrain(i)`` re-enlists it.

Failure is bounded: each dispatch failure or failover consumes one of
``RetryPolicy.max_attempts`` attempts (with exponential-backoff
step delays between dispatch retries), after which — or after a
per-request ``deadline`` passes — the request lands in ``result()`` as
a raised ``RuntimeError`` instead of spinning forever.

Telemetry: a fleet-level :class:`~apex_tpu.observability.MetricsRegistry`
carries ``fleet_retries_total`` / ``fleet_shed_total`` /
``fleet_failover_total`` / ``fleet_drains_total`` (and friends) plus
per-replica labeled gauges; ``stats()`` aggregates the replicas'
own ``stats()``; ``record()`` is the ``kind: fleet`` JSONL record
``observability.exporters.validate_fleet_record`` pins.

Flight recorder (PR 6): every submitted request gets a distributed
trace ("<fleet_trace>/r<rid>") whose lifecycle events — submit, route,
dispatch, fault, reclaim, result — chain causally on the process
:class:`~apex_tpu.observability.SpanRecorder`, with engine-internal
spans (queue/prefill/window-decode) parenting under the dispatch hop
even across the step pool's worker threads; rare operational
transitions (failover/shed/retry/deadline/stall, plus the breaker
moves ``health.ReplicaHealth`` notes and the faults ``faults.
FaultyReplica`` injects) land in a bounded
:class:`~apex_tpu.observability.EventRing`, dumped to
``flight_dump_path`` the moment a replica fails.
"""

from __future__ import annotations

import contextlib
import os
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..observability import MetricsRegistry, flightrec, tracing
from .health import (DEAD, DEGRADED, DRAINED, DRAINING, HEALTHY,
                     STATE_CODES, HealthConfig, ReplicaHealth)
from .qos import QosPolicy, WfqQueue
from .router import FleetOverloaded, RetryPolicy, make_policy
from .slo import SloTracker

__all__ = ["Fleet"]


class _FleetRequest:
    def __init__(self, rid, prompt, max_new, eos, seed, temperature,
                 deadline_at, tenant=None, priority=None,
                 qos_class=None):
        self.rid = rid
        self.prompt = list(prompt)
        self.max_new = max_new
        self.eos = eos
        self.seed = seed
        self.temperature = temperature
        self.deadline_at = deadline_at      # absolute clock time or None
        # tenant is the FOLDED bucket name (SloTracker.tenant_name):
        # every surface that stamps it — spans, ring events, metric
        # labels, per-tenant stats — agrees on the same string even
        # past the cardinality cap
        self.tenant = tenant
        self.priority = priority
        # resolved priority class (QosPolicy.resolve at submit): the
        # WfqQueue keys its per-class FIFOs on this, and preemption
        # direction compares class RANKS, never the raw priority tag
        self.qos_class = qos_class
        self.preemptions = 0                # times evicted mid-decode
        self.assigned: Optional[Tuple[int, int]] = None  # (replica, rrid)
        self.attempts = 0                   # failed dispatches + failovers
        self.next_attempt_step = 0
        self.restarts = 0
        self.generated: List[int] = []
        self.error: Optional[str] = None
        self.t_submit: Optional[float] = None
        self.t_finish: Optional[float] = None
        # distributed-trace spine: trace_id is minted at submit
        # ("<fleet_trace>/r<rid>"); last_span is the causal tail every
        # later lifecycle event parents on.  Both are touched ONLY on
        # the fleet thread (submit/dispatch/harvest/failover), so the
        # chain cannot interleave no matter how the step pool schedules
        self.trace_id: Optional[str] = None
        self.last_span: Optional[int] = None


class Fleet:
    """Front ``replicas`` (Engine / Seq2SeqEngine / FaultyReplica —
    anything with the scheduler surface) behind one API.

    ``policy`` is a name (``"round_robin"`` / ``"least_loaded"`` /
    ``"prefix_affinity"``) or an instance; ``max_queue`` bounds the
    fleet queue (full = shed); ``replica_queue_cap`` bounds how much
    the fleet will queue ON a replica beyond its free slots (0 = admit
    only into free slots); ``retry`` and ``health`` take
    :class:`router.RetryPolicy` / :class:`health.HealthConfig`;
    ``clock`` is injectable for deterministic deadline tests."""

    def __init__(self, replicas: Sequence[Any],
                 policy="least_loaded",
                 max_queue: int = 64,
                 replica_queue_cap: int = 2,
                 retry: Optional[RetryPolicy] = None,
                 health: Optional[HealthConfig] = None,
                 metrics: Optional[MetricsRegistry] = None,
                 clock=None,
                 step_workers: Optional[int] = None,
                 ring=None,
                 trace: bool = True,
                 flight_dump_path: Optional[str] = None,
                 qos: Optional[QosPolicy] = None):
        if not replicas:
            raise ValueError("Fleet needs at least one replica")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if replica_queue_cap < 0:
            raise ValueError(f"replica_queue_cap must be >= 0, got "
                             f"{replica_queue_cap}")
        self.replicas = list(replicas)
        self.policy = make_policy(policy)
        self.max_queue = max_queue
        self.replica_queue_cap = replica_queue_cap
        self.retry = retry or RetryPolicy()
        self.health_config = health or HealthConfig()
        # flight recorder + distributed tracing: the ring holds the
        # rare operational transitions (failover/shed/retry/deadline/
        # stall + the breaker transitions ReplicaHealth notes); with
        # ``trace=True`` every submitted request gets a trace context
        # ("<fleet_trace>/r<rid>") whose lifecycle events land on the
        # process SpanRecorder.  ``flight_dump_path`` dumps the ring
        # there the moment a replica fails — the post-mortem artifact.
        # explicit ring binds here; None resolves the PROCESS ring
        # lazily at every append (via the `ring` property), so an
        # operator swapping obs.set_ring() mid-life moves this fleet's
        # whole story — failover/breaker/shed/fault AND record_scaler's
        # skips — to the new ring together instead of splitting it
        self._ring = ring
        self.tracing = bool(trace)
        self.flight_dump_path = flight_dump_path
        self.trace_id = tracing.new_trace_id("fleet")
        self.health = [ReplicaHealth(self.health_config,
                                     ring=ring,
                                     name=i)
                       for i in range(len(self.replicas))]
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._clock = clock if clock is not None else time.perf_counter
        # replica step() dispatches can overlap across a thread pool:
        # jax releases the GIL inside XLA execution and the device
        # fetch, so replicas backed by SEPARATE devices genuinely run
        # concurrently.  Results are identical either way (replicas
        # never share mutable state); the default only goes parallel
        # when the host has cores beyond what one dispatch's XLA
        # intra-op pool already uses — on a small shared-CPU host,
        # threading replicas OVERSUBSCRIBES those cores and loses
        # ~30% (measured), so serial is the floor, not a fallback.
        if step_workers is None:
            step_workers = max(1, min(len(self.replicas),
                                      (os.cpu_count() or 2) // 2))
        if step_workers < 1:
            raise ValueError(f"step_workers must be >= 1, got "
                             f"{step_workers}")
        self.step_workers = step_workers
        self._pool: Optional[ThreadPoolExecutor] = None
        # QoS plane (PR 19): the pending queue is a WfqQueue — under
        # the default single-class policy its order IS submission
        # order (exact FIFO), so a policy-less fleet behaves
        # byte-for-byte as before; with a multi-class policy it
        # stride-schedules across per-class FIFOs.  ``_qos_active``
        # gates the class stamp on spans/events so untagged fleets
        # keep their pre-QoS event shapes.
        self.qos = qos if qos is not None else QosPolicy.single()
        self._qos_active = len(self.qos.classes) > 1
        self._pending: WfqQueue = WfqQueue(self.qos)
        self._inflight: Dict[Tuple[int, int], _FleetRequest] = {}
        self._results: Dict[int, _FleetRequest] = {}
        # rid -> trace id, retained for the fleet's lifetime like
        # _results (one short string per request); the span events
        # themselves live on the BOUNDED process recorder, so an old
        # request's trace eventually evicts oldest-first
        self._trace_ids: Dict[int, str] = {}
        self._next_rid = 0
        self._step_no = 0
        self._idle_steps = [0] * len(self.replicas)
        self._prefix_map: Dict[tuple, int] = {}
        # fleet-LOCAL totals (registry counters aggregate across fleets
        # sharing a registry; stats() must not — same rule as the
        # engine scheduler)
        self._n_submitted = 0
        self._n_finished = 0
        self._n_failed = 0
        self._n_tokens = 0
        self._n_shed = 0
        # overload episodes are PER CLASS: an admitted interactive
        # request must not end the batch class's shed episode (with
        # the default single class this degenerates to the old global
        # flag — any admit ends the episode)
        self._shedding_classes: set = set()
        self._tick_retry_logged: set = set()  # replicas ring-logged this tick
        self._n_retries = 0
        self._n_failovers = 0
        self._n_drains = 0
        self._n_deadline = 0
        self._n_preempted = 0
        # MTTR accounting (PR 11): a failover opens a recovery window;
        # the first subsequent tick with real progress (tokens emitted
        # or a finish harvested) closes it — fault injection to first
        # post-recovery step, the fleet-side number bench --chaos
        # trends.  ``recovery_in_flight`` is the controllers' flag
        # (SloController / an operator mid-world-shrink): while set,
        # the introspection server's no-steppable-replica check
        # reports the distinct degraded-but-live "recovering" state
        # instead of 503ing an orchestrator into a restart loop.
        self._recover_t0: Optional[float] = None
        self._recovering_rids: set = set()
        self._recovering_tenants: set = set()
        self._recovered_tick = False    # reclaimed work progressed now
        self._mttr_last: Optional[float] = None
        self._mttr_sum = 0.0
        self._mttr_count = 0
        self.recovery_in_flight = False
        # the most recent deadline sweep's aggregate (count + first
        # rids), previously visible only on the flight ring — exposed
        # through stats()/record() so a dashboard need not tail the
        # ring to see WHAT just expired
        self._last_deadline_sweep: Dict[str, Any] = {
            "count": 0, "rids": [], "fleet_step": None}
        m = self.metrics
        # SLO/goodput accounting, fed at the same instants the trace
        # spans record (submit / first dispatch / finish / fail)
        self.slo = SloTracker(m, self._clock)
        self._m_submitted = m.counter("fleet_submitted_total")
        self._m_finished = m.counter("fleet_finished_total")
        self._m_failed = m.counter(
            "fleet_failed_total",
            help="requests failed after retry exhaustion or deadline")
        self._m_tokens = m.counter("fleet_tokens_total")
        self._m_retries = m.counter(
            "fleet_retries_total",
            help="dispatch attempts that failed and were retried")
        self._m_shed = m.counter(
            "fleet_shed_total",
            help="submissions refused with FleetOverloaded (bounded "
                 "queue full)")
        self._m_failover = m.counter(
            "fleet_failover_total",
            help="requests reclaimed from a sick replica and "
                 "restarted on a survivor")
        self._m_drains = m.counter("fleet_drains_total")
        self._m_deadline = m.counter("fleet_deadline_exceeded_total")
        self._m_preempted = m.counter(
            "fleet_preemptions_total",
            help="in-flight requests evicted mid-decode to admit a "
                 "higher-priority class (re-queued from their prompt)")
        self._m_latency = m.histogram(
            "fleet_request_seconds",
            help="submit-to-finish latency per completed request")
        m.gauge("fleet_replicas").set(float(len(self.replicas)))

    @property
    def ring(self):
        """The flight ring this fleet appends to: the one passed at
        construction, else the CURRENT process ring (resolved per
        access, so ``obs.set_ring`` swaps mid-life take effect)."""
        return flightrec.resolve(self._ring)

    # -- submission --------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               eos_token_id: Optional[int] = None,
               seed: Optional[int] = None,
               temperature: Optional[float] = None,
               deadline: Optional[float] = None,
               tenant: Optional[str] = None,
               priority: Optional[int] = None) -> int:
        """Queue a request; returns the fleet request id.  Raises
        :class:`FleetOverloaded` (retriable) when the bounded fleet
        queue is full.  ``deadline`` is seconds from now: a request
        not finished in time fails with a deadline error instead of
        occupying capacity forever.

        ``tenant`` tags the request for per-tenant accounting: SLO /
        goodput tallies, tenant-labeled registry metrics, and the
        tenant stamp on every trace span and ring event the request
        touches (shed / deadline / failover events say WHOSE request
        suffered).  Tenant ids are user-supplied strings — past the
        tracker's cardinality cap new ids fold into the shared
        ``other`` bucket.  ``priority`` is CONSUMED by the QoS plane
        (PR 19): it resolves to a priority class via the fleet's
        :class:`~apex_tpu.fleet.qos.QosPolicy` (explicit priority
        naming a known class wins, then the tenant->class map, then
        the default class), which decides the request's weighted-fair
        dispatch share, its per-class queue quota, its default
        deadline, and whether it may be preempted mid-decode."""
        qcls = self.qos.resolve(tenant, priority)
        # shed against BOTH bounds: the global queue AND the class's
        # own quota (queue_share x max_queue) — a batch flood sheds
        # against its quota long before it can squeeze the
        # interactive class out of the queue
        cap = self.qos.cap(qcls, self.max_queue)
        if (len(self._pending) >= self.max_queue
                or self._pending.depth(qcls) >= cap):
            self._n_shed += 1
            self._m_shed.inc()
            # a shed happens before a rid exists; feed the tenant
            # straight to the tracker (folded name comes back for the
            # ring stamp)
            shed_tenant = self.slo.on_shed(
                tenant, qos_class=qcls if self._qos_active else None)
            if qcls not in self._shedding_classes:
                # one ring event per overload EPISODE (the transition
                # into shedding), not per rejected submit: sustained
                # overload is hundreds of rejections a second, which
                # would wheel the bounded ring past the breaker/
                # failover history a post-mortem needs.
                # fleet_shed_total carries the volume.
                self._shedding_classes.add(qcls)
                self.ring.append("shed",
                                 queue_depth=len(self._pending),
                                 max_queue=self.max_queue,
                                 **({"qos_class": qcls}
                                    if self._qos_active else {}),
                                 **({"tenant": shed_tenant}
                                    if shed_tenant is not None else {}))
            raise FleetOverloaded(len(self._pending), self.max_queue,
                                  qos_class=(qcls if self._qos_active
                                             else None))
        if deadline is not None and deadline <= 0:
            raise ValueError(f"deadline must be > 0 seconds, got "
                             f"{deadline}")
        if deadline is None:
            # per-class default deadline (validated > 0 at policy
            # construction) — interactive classes get their SLO bound
            # without every caller restating it
            deadline = self.qos.deadline_for(qcls)
        rid = self._next_rid
        self._next_rid += 1
        now = self._clock()
        req = _FleetRequest(rid, prompt, max_new_tokens, eos_token_id,
                            seed, temperature,
                            None if deadline is None else now + deadline,
                            tenant=self.slo.tenant_name(tenant),
                            priority=priority,
                            qos_class=qcls)
        req.t_submit = now
        if self.tracing:
            # the root of the request's causal chain; every later
            # lifecycle event (route/dispatch/fault/reclaim/result)
            # parents on the chain's tail
            req.trace_id = f"{self.trace_id}/r{rid}"
            self._trace_ids[rid] = req.trace_id
            req.last_span = tracing.get_recorder().event(
                "fleet_submit", trace_id=req.trace_id, rid=rid,
                prompt_len=len(req.prompt), max_new=max_new_tokens,
                queue_depth=len(self._pending),
                **self._tenant_attrs(req))
        self._pending.append(req)
        # an admitted submit ends THIS class's overload episode
        self._shedding_classes.discard(qcls)
        self._n_submitted += 1
        self._m_submitted.inc()
        # feed the ALREADY-folded name (req.tenant): folding twice
        # would double-count tenants_dropped for over-cap ids
        self.slo.on_submit(rid, now, req.deadline_at,
                           tenant=req.tenant,
                           qos_class=qcls if self._qos_active else None)
        return rid

    def _tenant_attrs(self, req: "_FleetRequest") -> Dict[str, Any]:
        """The tenant/priority/class stamp for spans and ring events;
        empty for untagged requests under the default policy so their
        events keep the pre-tenant shape.  With a multi-class policy
        EVERY request carries its resolved class (untagged traffic
        lands in the default class — the class split must cover 100%
        of traffic or the /tenantz class view lies)."""
        attrs: Dict[str, Any] = {}
        if req.tenant is not None:
            attrs["tenant"] = req.tenant
        if req.priority is not None:
            attrs["priority"] = req.priority
        if self._qos_active and req.qos_class is not None:
            attrs["qos_class"] = req.qos_class
        return attrs

    def _trace_ev(self, req: "_FleetRequest", name: str,
                  **attrs) -> Optional[int]:
        """Append one lifecycle event to the request's trace, chaining
        it on the previous tail; fleet-thread only.  Tagged requests
        carry their tenant/priority on EVERY hop — including the
        fault/reclaim/re-dispatch chain across a failover."""
        if not (self.tracing and req.trace_id):
            return None
        req.last_span = tracing.get_recorder().event(
            name, trace_id=req.trace_id, parent_id=req.last_span,
            rid=req.rid, **{**self._tenant_attrs(req), **attrs})
        return req.last_span

    def register_prefix(self, tokens: Sequence[int],
                        replica: Optional[int] = None) -> int:
        """Prefill ``tokens`` into ONE replica's prefix pool and
        remember the owner: with the ``prefix_affinity`` policy, later
        prompts starting with these tokens route there (KV-splice
        admission).  Returns the owning replica index."""
        if replica is None:
            cands = [i for i in range(len(self.replicas))
                     if self.health[i].admissible()]
            if not cands:
                raise RuntimeError("no admissible replica to own the "
                                   "prefix")
            replica = min(cands, key=lambda i: (
                self.replicas[i].stats()["occupancy"], i))
        self.replicas[replica].register_prefix(tokens)
        self._prefix_map[tuple(int(t) for t in tokens)] = replica
        return replica

    def warmup(self) -> "Fleet":
        """Pre-compile EVERY replica's step closures before traffic
        (one throwaway request through each replica's ``warmup()``).
        Each ``Engine`` instance jits its own closures, so a cold
        N-replica fleet pays N compiles spread across its first timed
        windows — the PR 4 bench gotcha ("cold timed runs measure N
        compiles"), fixed here at the source instead of in a bench
        comment.  After ``warmup()`` the compilation ledger's
        zero-retrace contract applies: steady-state traffic AND a
        failover restarting reclaimed requests on survivors add zero
        traces (pinned in tests/test_fleet.py).  Replicas without a
        ``warmup`` method (stubs, remote proxies) are skipped; a
        fault-harness wrapper delegates to its inner engine without
        advancing its fault windows.  Returns ``self``."""
        for rep in self.replicas:
            fn = getattr(rep, "warmup", None)
            if callable(fn):
                fn()
        self.ring.append("fleet_warmup",
                         replicas=len(self.replicas))
        return self

    def prefix_owner(self, prompt: Sequence[int]) -> Optional[int]:
        """Replica owning the longest registered prefix of ``prompt``,
        or None."""
        pt = tuple(int(t) for t in prompt)
        best, best_len = None, 0
        for pref, owner in self._prefix_map.items():
            if len(pref) > best_len and pt[:len(pref)] == pref:
                best, best_len = owner, len(pref)
        return best

    # -- the fleet tick ----------------------------------------------------
    def step(self) -> Dict[int, List[int]]:
        """One cooperative tick over every replica; returns
        ``{fleet_rid: [tokens]}`` emitted this tick.  Emissions are
        at-least-once across failovers (a restarted request re-emits
        from its first token); ``result()`` is the exactly-once
        surface."""
        self._step_no += 1
        self._tick_retry_logged.clear()
        self._recovered_tick = False
        for h in self.health:
            h.tick()
        self._check_deadlines()
        self._dispatch()
        out: Dict[int, List[int]] = {}
        plan = []
        for i, rep in enumerate(self.replicas):
            mine = [k for k in self._inflight if k[0] == i]
            if self.health[i].steppable() and (mine
                                               or rep.live() > 0):
                plan.append((i, rep, mine))

        def dispatch(item):
            # runs on a pool worker: the span carries the FLEET-run
            # trace and is this thread's ambient parent, so
            # engine-internal spans (window decode) nest under the
            # right replica's dispatch even with step_workers > 1 —
            # pool threads get their own contextvar context and the
            # span resets it on exit, so reused workers never inherit
            # a stale parent (the PR 1 interleaving bug)
            i, rep, _ = item
            t0 = self._clock()
            cm = (tracing.get_recorder().span(
                      "fleet_replica_step", trace_id=self.trace_id,
                      replica=i, fleet_step=self._step_no)
                  if self.tracing else contextlib.nullcontext())
            try:
                with cm:
                    out = rep.step()
                return i, out, self._clock() - t0, None
            except Exception as e:  # noqa: BLE001 — any replica death
                return i, None, self._clock() - t0, e

        if self.step_workers > 1 and len(plan) > 1:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self.step_workers,
                    thread_name_prefix="fleet-step")
            stepped = list(self._pool.map(dispatch, plan))
        else:
            stepped = [dispatch(item) for item in plan]

        # post-processing stays on the fleet thread, in replica order —
        # health, failover and harvest are deterministic regardless of
        # how the pool interleaved the dispatches
        for (i, rep, mine), (_, emitted, dt, exc) in zip(plan, stepped):
            h = self.health[i]
            if exc is not None:
                self._replica_failed(i, f"step raised: {exc}")
                continue
            if mine:
                h.record_success(dt)
            progressed = False
            for rrid, toks in emitted.items():
                req = self._inflight.get((i, rrid))
                if req is None:        # stale pre-failover slot: drop
                    continue
                if toks:
                    progressed = True
                    out.setdefault(req.rid, []).extend(int(t)
                                                       for t in toks)
            for key in mine:
                req = self._inflight.get(key)
                if req is None:
                    continue
                try:
                    done = rep.is_finished(key[1])
                except Exception:
                    done = False
                if done:
                    progressed = True
                    del self._inflight[key]
                    self._finish(req, rep.result(key[1]))
            # no-progress watchdog: live fleet work, zero tokens, zero
            # finishes — a stall or result-dropper that never raises
            if mine and not progressed:
                self._idle_steps[i] += 1
                if self._idle_steps[i] >= self.health_config.stall_steps:
                    self._idle_steps[i] = 0
                    self.ring.append("stall_watchdog", replica=i,
                                     stall_steps=self.health_config
                                     .stall_steps)
                    self._replica_failed(
                        i, f"no progress for "
                           f"{self.health_config.stall_steps} steps "
                           f"(stall watchdog)")
            else:
                self._idle_steps[i] = 0
        for i, h in enumerate(self.health):
            if h.draining and not any(k[0] == i for k in self._inflight):
                h.finish_drain()
        if self._recover_t0 is not None:
            # close the MTTR window at the first tick where reclaimed
            # work makes progress again — a restarted request emits or
            # finishes on a survivor (_finish sets the tick flag
            # before dropping the rid from the watch set).  Windows
            # with nothing left to rescue were already abandoned
            # without an MTTR sample (see _abandon_recovery), so they
            # can never span unrelated idle time.
            recovered = (self._recovered_tick
                         or bool(self._recovering_rids & set(out)))
            if recovered:
                mttr = self._clock() - self._recover_t0
                self._recover_t0 = None
                self._recovering_rids.clear()
                # whose work just recovered — the aggregate carries the
                # window's tenant membership (list, like "failover")
                tenants = sorted(self._recovering_tenants)
                self._recovering_tenants.clear()
                self._mttr_last = mttr
                self._mttr_sum += mttr
                self._mttr_count += 1
                self.ring.append("recovery_done",
                                 mttr_s=round(mttr, 6),
                                 fleet_step=self._step_no,
                                 **({"tenants": tenants}
                                    if tenants else {}))
                self.metrics.histogram(
                    "fleet_mttr_seconds",
                    help="failover to first post-recovery progress of "
                         "reclaimed work"
                ).observe(mttr)
        self._update_gauges()
        return out

    # -- dispatch / routing ------------------------------------------------
    def _candidates(self) -> List[int]:
        cands = []
        for i, rep in enumerate(self.replicas):
            h = self.health[i]
            if not h.admissible():
                continue
            inflight_here = sum(1 for k in self._inflight if k[0] == i)
            if h.circuit == "half_open":
                # half-open admits exactly ONE probe request
                if inflight_here == 0 and rep.free_slots() > 0:
                    cands.append(i)
                continue
            if (rep.free_slots() > 0
                    or rep.queue_depth() < self.replica_queue_cap):
                cands.append(i)
        # prefer healthy replicas — but a half-open replica MUST stay
        # eligible or its recovery probe never dispatches under
        # non-saturating load and it idles degraded forever (the
        # one-probe budget above keeps the risk to a single request)
        preferred = [i for i in cands
                     if self.health[i].state == HEALTHY
                     or self.health[i].circuit == "half_open"]
        return preferred or cands

    def _dispatch(self):
        if not self._pending:
            return
        # candidate capacity only changes when a dispatch lands (or
        # fails), so recompute per outcome, not per queued request —
        # the backlog can be hundreds deep and this loop is per tick.
        # The snapshot is in WFQ order: the stride schedule decides
        # who meets the router first, the router only decides WHERE.
        cands = self._candidates()
        for req in list(self._pending):
            if req.next_attempt_step > self._step_no:
                continue
            if not cands:
                # no capacity anywhere — the QoS escape hatch: a
                # dispatchable high-class request may evict a strictly
                # lower-class in-flight one (decode preemption).  If
                # there is no eligible victim either, capacity is
                # request-independent and the sweep ends.
                if not self._try_preempt(req):
                    break
                cands = self._candidates()
                if not cands:
                    # eviction freed capacity on a replica the breaker
                    # currently refuses — nothing more this tick
                    break
            elif (self._qos_active
                    and not any(self.replicas[j].free_slots() > 0
                                for j in cands)):
                # every candidate would only QUEUE the request behind
                # work already decoding — for a class that outranks an
                # in-flight victim that is a priority inversion, not
                # admission: evict first so the request lands on a
                # real slot.  No victim → fall through and queue.
                if self._try_preempt(req):
                    cands = self._candidates()
                    if not cands:
                        break
            i = self.policy.select(self, cands, req)
            rep = self.replicas[i]
            # routing decision + dispatch attempt on the request's
            # trace; activating the dispatch event around rep.submit
            # parents the engine's own queue/prefill spans under it
            # (submit runs on the fleet thread — ambient is safe here)
            decision = getattr(self.policy, "last_decision", None)
            self._trace_ev(req, "fleet_route", replica=i,
                           policy=getattr(self.policy, "name",
                                          type(self.policy).__name__),
                           attempt=req.attempts,
                           candidates=list(cands),
                           **({"decision": decision} if decision
                              else {}))
            dspan = self._trace_ev(req, "fleet_dispatch", replica=i)
            amb = (tracing.get_recorder().activate(req.trace_id, dspan)
                   if dspan is not None else contextlib.nullcontext())
            # replicas advertising accepts_tenant get the tag so their
            # engine-side spans (queue/prefill) carry it too; stubs and
            # proxies without the flag keep the pre-tenant signature
            tkw = ({"tenant": req.tenant}
                   if req.tenant is not None
                   and getattr(rep, "accepts_tenant", False) else {})
            try:
                with amb:
                    rrid = rep.submit(req.prompt, req.max_new, req.eos,
                                      req.seed, req.temperature, **tkw)
            except ValueError as e:
                # request-shaped rejection (bad prompt length, seed on
                # a greedy engine, ...): the replica is fine and no
                # other replica would take it either — fail, no retry
                self._pending.remove(req)
                self._trace_ev(req, "fleet_reject", replica=i,
                               error=str(e))
                self._fail(req, f"rejected at dispatch: {e}")
                continue
            except Exception as e:      # noqa: BLE001 — replica fault
                self.health[i].record_error()
                self._n_retries += 1
                self._m_retries.inc()
                req.attempts += 1
                # one ring event per (replica, tick): a deep backlog
                # failing dispatch onto one sick replica is a single
                # transition, not len(backlog) of them — the counter
                # carries the volume (same rule as shed/deadline)
                if i not in self._tick_retry_logged:
                    self._tick_retry_logged.add(i)
                    self.ring.append("dispatch_retry", replica=i,
                                     rid=req.rid, attempt=req.attempts,
                                     error=str(e))
                if req.attempts >= self.retry.max_attempts:
                    self._pending.remove(req)
                    self._trace_ev(req, "fleet_retries_exhausted",
                                   replica=i, attempts=req.attempts)
                    self._fail(req, f"dispatch failed after "
                                    f"{req.attempts} attempts; last: "
                                    f"{e}")
                else:
                    req.next_attempt_step = (
                        self._step_no
                        + self.retry.delay_steps(req.attempts - 1))
                    self._trace_ev(req, "fleet_retry_backoff",
                                   replica=i, attempt=req.attempts,
                                   next_attempt_step=
                                   req.next_attempt_step)
                cands = self._candidates()   # health may have tripped
                continue
            self._pending.remove(req)
            req.assigned = (i, rrid)
            self._inflight[(i, rrid)] = req
            # first dispatch closes the request's queue-wait window
            # (a failover's re-dispatch is service time — the tracker
            # keeps only the first)
            self.slo.on_dispatch(req.rid, self._clock())
            cands = self._candidates()       # replica i consumed capacity
        # a reclaimed request can exhaust its budget inside this sweep
        # (rejection or repeated dispatch failure): if that emptied
        # the MTTR watch set, close the window sample-free
        self._abandon_recovery()

    # -- decode preemption -------------------------------------------------
    def _try_preempt(self, req: "_FleetRequest") -> bool:
        """Evict one in-flight request of a STRICTLY lower class to
        make room for ``req``.  The victim is chosen
        deterministically: lowest class first (highest rank number),
        then fewest harvested tokens, then the YOUNGEST request
        (highest rid) — the least sunk work to redo.  Eviction goes
        through the replica's ``preempt()`` when it has one (the
        engine scheduler's eviction API: paged replicas free the
        victim's KV blocks through the in-graph recycling path —
        eager host-side ops, so a warmed fleet preempts with zero new
        traces) and falls back to ``cancel()``.  The evictee
        re-queues at the FRONT of its own class queue and restarts
        from its prompt exactly like a failed-over request, so its
        final ``result()`` stays token-for-token what an undisturbed
        run produces (greedy / explicitly-seeded decodes are
        request-intrinsic).  A preemption is not a failure: the
        victim's retry budget is untouched."""
        if not self._qos_active:
            return False
        rank = self.qos.rank(req.qos_class)
        victims = [(key, r) for key, r in self._inflight.items()
                   if r.qos_class is not None
                   and self.qos.rank(r.qos_class) > rank
                   and self.qos.preemptible(r.qos_class)
                   and self.health[key[0]].admissible()]
        if not victims:
            return False
        key, victim = max(
            victims,
            key=lambda kv: (self.qos.rank(kv[1].qos_class),
                            -len(kv[1].generated), kv[1].rid))
        i, rrid = key
        rep = self.replicas[i]
        try:
            fn = getattr(rep, "preempt", None)
            if callable(fn):
                fn(rrid)
            else:
                rep.cancel(rrid)
        except Exception:               # noqa: BLE001 — best-effort,
            pass                        # like _replica_failed's cancel
        del self._inflight[key]
        victim.assigned = None
        victim.generated = []
        victim.preemptions += 1
        victim.next_attempt_step = self._step_no  # eligible at once
        self._n_preempted += 1
        self._m_preempted.inc()
        self.slo.on_preempt(victim.qos_class)
        # preemption is an aggregate two-party event: the ?tenant=
        # membership filter must find it from EITHER side, so both
        # tenants ride in the ``tenants`` list
        tenants = sorted({t for t in (victim.tenant, req.tenant)
                          if t is not None})
        self.ring.append("preemption", replica=i,
                         evicted_rid=victim.rid,
                         evicted_class=victim.qos_class,
                         admitted_rid=req.rid,
                         admitted_class=req.qos_class,
                         fleet_step=self._step_no,
                         **({"tenants": tenants} if tenants else {}))
        self._trace_ev(victim, "fleet_preempted", replica=i,
                       by_rid=req.rid, by_class=req.qos_class,
                       preemptions=victim.preemptions)
        self._pending[:0] = [victim]
        return True

    # -- failure handling --------------------------------------------------
    def _replica_failed(self, i: int, reason: str):
        """Record the error (the breaker may open) and fail over every
        fleet request on replica ``i`` — reclaimed, best-effort
        cancelled there, and restarted from their prompts on whoever
        the router picks next tick."""
        self.health[i].record_error()
        # a raise mid-step must not carry a previously accumulated
        # stall count into the replica's next life — the watchdog
        # would fire on its first slow tick after recovery
        self._idle_steps[i] = 0
        rep = self.replicas[i]
        keys = sorted((k for k in self._inflight if k[0] == i),
                      key=lambda k: self._inflight[k].rid)
        if self._recover_t0 is None:
            # MTTR opens at the FIRST failure of the episode; a second
            # replica dying mid-recovery extends the same window.  It
            # closes at the first post-recovery progress OF RECLAIMED
            # WORK (the rids collected below) — a survivor's unrelated
            # token does not mean the failed-over requests recovered.
            self._recover_t0 = self._clock()
        # whose requests suffered: the distinct tenants among the
        # reclaimed work (aggregate event, so a list — /flightz's
        # ?tenant= filter matches membership)
        tenants = sorted({self._inflight[k].tenant for k in keys
                          if self._inflight[k].tenant is not None})
        self.ring.append("failover", replica=i, reason=reason,
                         reclaimed=len(keys), fleet_step=self._step_no,
                         **({"tenants": tenants} if tenants else {}))
        moved = []
        for key in keys:
            req = self._inflight.pop(key)
            try:
                rep.cancel(key[1])
            except Exception:           # noqa: BLE001 — sick replica
                pass
            req.assigned = None
            req.restarts += 1
            req.attempts += 1
            req.generated = []
            self._n_failovers += 1
            self._m_failover.inc()
            # the failure hop of the request's causal chain: the fault
            # on the sick replica, then the reclaim that re-queues it
            # for the router — the next fleet_route/fleet_dispatch pair
            # (on a survivor) chains on the reclaim event
            self._trace_ev(req, "fleet_fault", replica=i, reason=reason)
            if req.attempts >= self.retry.max_attempts:
                self._fail(req, f"failed over {req.restarts}x "
                                f"(attempt budget exhausted); replica "
                                f"{i}: {reason}")
            else:
                req.next_attempt_step = self._step_no + 1
                self._trace_ev(req, "fleet_reclaim", replica=i,
                               restarts=req.restarts,
                               attempts=req.attempts)
                moved.append(req)
                self._recovering_rids.add(req.rid)
                if req.tenant is not None:
                    self._recovering_tenants.add(req.tenant)
        # leftovers in the replica's own waiting queue (queued-on-
        # replica dispatches) came back via the keys above; anything
        # else there was submitted behind the fleet's back — drop it
        # back out so the sick replica holds no queued work
        try:
            rep.take_waiting()
        except Exception:               # noqa: BLE001
            pass
        # restarted requests go to the FRONT in submission order: they
        # were admitted before anything still pending
        self._pending[:0] = moved
        # a failover that reclaimed nothing rescuable (idle replica,
        # or every request's budget already spent) closes its MTTR
        # window right here, sample-free
        self._abandon_recovery()
        if self.flight_dump_path:
            # post-mortem artifact the moment something broke — not at
            # process exit, which a wedged replica may never reach
            try:
                self.ring.dump(self.flight_dump_path)
            except OSError:
                pass

    def _abandon_recovery(self):
        """Nothing left to rescue (the dead replica held no fleet
        work, or every reclaimed request resolved as a failure): close
        the MTTR window WITHOUT a sample — letting it wait for
        unrelated future progress would report idle time as recovery
        time and absorb the next real failover into a stale window.
        Called only at the END of a reclaim/deadline/dispatch sweep,
        never mid-loop: a budget-exhausted request failed early in
        ``_replica_failed``'s loop must not abandon the window the
        requests still being reclaimed behind it are about to join."""
        if self._recover_t0 is not None and not self._recovering_rids \
                and not self._recovered_tick:
            self._recover_t0 = None
            self._recovering_tenants.clear()
            self.ring.append("recovery_abandoned",
                             fleet_step=self._step_no)

    def _fail(self, req: _FleetRequest, msg: str,
              deadline_exceeded: bool = False):
        # a reclaimed request that dies (budget/deadline) is resolved,
        # not recovered — drop it from the MTTR watch set (the sweep
        # that called us decides afterwards whether the window is now
        # empty and must be abandoned)
        self._recovering_rids.discard(req.rid)
        req.error = msg
        req.t_finish = self._clock()
        self._results[req.rid] = req
        self._n_failed += 1
        self._m_failed.inc()
        self.slo.on_fail(req.rid, req.t_finish,
                         deadline_exceeded=deadline_exceeded)
        self._trace_ev(req, "fleet_failed", error=msg)

    def _finish(self, req: _FleetRequest, tokens: List[int]):
        if self._recover_t0 is not None \
                and req.rid in self._recovering_rids:
            # a reclaimed request FINISHING is the strongest form of
            # post-recovery progress; flag it before dropping the rid
            # so the end-of-tick close still sees it
            self._recovered_tick = True
        self._recovering_rids.discard(req.rid)
        req.generated = [int(t) for t in tokens]
        req.t_finish = self._clock()
        self._results[req.rid] = req
        self._n_finished += 1
        self._m_finished.inc()
        self._n_tokens += len(req.generated)
        self._m_tokens.inc(len(req.generated))
        self.slo.on_finish(req.rid, req.t_finish, len(req.generated))
        if req.t_submit is not None:
            self._m_latency.observe(req.t_finish - req.t_submit)
        self._trace_ev(req, "fleet_result", tokens=len(req.generated),
                       restarts=req.restarts,
                       latency_s=round(req.t_finish - req.t_submit, 6)
                       if req.t_submit is not None else None)

    def _check_deadlines(self):
        now = self._clock()
        expired: List[_FleetRequest] = []
        for req in [r for r in self._pending
                    if r.deadline_at is not None
                    and now > r.deadline_at]:
            self._pending.remove(req)
            expired.append(req)
        for key, req in list(self._inflight.items()):
            if req.deadline_at is not None and now > req.deadline_at:
                del self._inflight[key]
                try:
                    self.replicas[key[0]].cancel(key[1])
                except Exception:       # noqa: BLE001
                    pass
                expired.append(req)
        if expired:
            # ONE ring event per sweep, like the shed episode: a
            # shared client deadline can expire the whole queue in a
            # single tick, and thousands of per-request events would
            # wheel the bounded ring past the breaker/failover history
            # a post-mortem needs.  The counter carries the volume.
            sweep = {"count": len(expired),
                     "rids": [r.rid for r in expired[:8]],
                     "fleet_step": self._step_no}
            tenants = sorted({r.tenant for r in expired
                              if r.tenant is not None})
            if tenants:
                sweep["tenants"] = tenants
            self._last_deadline_sweep = sweep
            self.ring.append("deadline_exceeded", **sweep)
        for req in expired:
            self._deadline_fail(req)
        if expired:
            self._abandon_recovery()

    def _deadline_fail(self, req: _FleetRequest):
        self._n_deadline += 1
        self._m_deadline.inc()
        self._fail(req, f"deadline exceeded after "
                        f"{self._clock() - req.t_submit:.3f}s",
                   deadline_exceeded=True)

    # -- drain / rolling restart -------------------------------------------
    def drain(self, i: int):
        """Graceful drain of replica ``i``: stop admitting, re-enqueue
        its waiting queue onto the fleet (→ survivors), keep stepping
        its in-flight requests to completion; the replica then parks
        ``drained`` until :meth:`undrain`."""
        h = self.health[i]
        if h.draining or h.drained:
            return
        h.start_drain()
        self._n_drains += 1
        self._m_drains.inc()
        moved = []
        try:
            taken = self.replicas[i].take_waiting()
        except Exception:               # noqa: BLE001
            taken = []
        for rrid, *_ in taken:
            req = self._inflight.pop((i, rrid), None)
            if req is not None:
                req.assigned = None
                req.next_attempt_step = self._step_no
                moved.append(req)
        moved.sort(key=lambda r: r.rid)
        self.ring.append("drain", replica=i, requeued=len(moved),
                         fleet_step=self._step_no)
        for req in moved:
            self._trace_ev(req, "fleet_drain_requeue", replica=i)
        self._pending[:0] = moved
        if not any(k[0] == i for k in self._inflight):
            h.finish_drain()

    def undrain(self, i: int):
        """Re-enlist a drained (or draining) replica with a fresh
        health record — the post-rolling-restart handshake."""
        self.health[i].reset()

    # -- results / introspection -------------------------------------------
    def result(self, rid: int) -> List[int]:
        """Final tokens of a finished request; raises ``KeyError`` if
        unknown/unfinished and ``RuntimeError`` if the request failed
        (retries exhausted, rejected, or deadline exceeded)."""
        req = self._results[rid]
        if req.error is not None:
            raise RuntimeError(f"request {rid} failed: {req.error}")
        return list(req.generated)

    def request_trace_id(self, rid: int) -> Optional[str]:
        """The distributed-trace id minted for request ``rid`` at
        submit ("<fleet_trace>/r<rid>"), or None when tracing is off.
        Feed it to ``observability.get_recorder().trace(...)`` /
        ``trace_record(...)`` for the request's full causal span chain
        (submit → route → dispatch → [fault → reclaim → ...] →
        result)."""
        return self._trace_ids.get(rid)

    def trace_record(self, rid: int) -> Dict[str, Any]:
        """The ``kind: trace`` JSONL record of request ``rid``'s
        flight (``exporters.validate_trace_record`` pins the shape);
        raises ``KeyError`` when the request was never traced."""
        tid = self._trace_ids.get(rid)
        if tid is None:
            raise KeyError(f"request {rid} has no trace (tracing "
                           f"disabled or unknown rid)")
        return tracing.get_recorder().trace_record(tid)

    def close(self):
        """Join the step-worker pool (idempotent).  A later ``step()``
        lazily recreates it, so close when the fleet is retired — the
        pool's threads are non-daemon and otherwise live until
        interpreter exit."""
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def latency(self, rid: int) -> float:
        """Submit-to-finish seconds for a completed (or failed)
        request — the per-request tail-latency surface ``bench.py
        --fleet`` percentiles over; raises ``KeyError`` while the
        request is still in flight."""
        req = self._results[rid]
        return req.t_finish - req.t_submit

    def status(self, rid: int) -> str:
        """``queued`` / ``inflight`` / ``finished`` / ``failed``."""
        if rid in self._results:
            return ("failed" if self._results[rid].error is not None
                    else "finished")
        if any(r.rid == rid for r in self._pending):
            return "queued"
        if any(r.rid == rid for r in self._inflight.values()):
            return "inflight"
        raise KeyError(f"unknown request id {rid}")

    def live(self) -> int:
        """Requests still owed an outcome (queued + in-flight)."""
        return len(self._pending) + len(self._inflight)

    def queue_depth(self) -> int:
        """Fleet-queue depth — the cheap accessor the SLO controller
        reads every control tick (``stats()`` builds histogram
        summaries; this is one ``len``)."""
        return len(self._pending)

    def inflight(self) -> int:
        """In-flight request count (cheap, controller-facing)."""
        return len(self._inflight)

    def mttr(self) -> Dict[str, Any]:
        """Fleet MTTR aggregate: failover → first post-recovery
        progress, ``{last, mean, count}`` seconds (``None`` until a
        recovery completed)."""
        return {"last": self._mttr_last,
                "mean": (self._mttr_sum / self._mttr_count
                         if self._mttr_count else None),
                "count": self._mttr_count}

    def begin_recovery(self, reason: str = ""):
        """Mark an INTENTIONAL recovery in flight (controller world
        shrink, operator intervention): while set, the introspection
        server's no-steppable-replica check reports degraded-but-live
        ``recovering`` instead of 503 — an orchestrator probe must not
        restart-loop a fleet that is being handled."""
        if not self.recovery_in_flight:
            self.recovery_in_flight = True
            self.ring.append("fleet_recovery_begin", reason=reason,
                             fleet_step=self._step_no)

    def end_recovery(self):
        if self.recovery_in_flight:
            self.recovery_in_flight = False
            self.ring.append("fleet_recovery_end",
                             fleet_step=self._step_no)

    def states(self) -> List[str]:
        return [h.state for h in self.health]

    def tenant_stats(self) -> Dict[str, Any]:
        """The per-tenant rollup (``/tenantz``'s fleet source): every
        tenant's SLO/goodput tallies under one goodput window (the
        ``stats()`` discipline: extended to now while work is live),
        the tracker's overflow-fold count, the per-metric label drop
        accounting from the registry cardinality cap, and (PR 19) the
        per-CLASS split the ``?class=`` filter serves."""
        now = self._clock() if self.live() else None
        drops = {m.name: m.labels_dropped
                 for m in self.metrics.collect() if m.labels_dropped}
        return {"tenants": self.slo.tenant_stats(now=now),
                "tenants_dropped": self.slo.tenants_dropped,
                "classes": self._class_block(
                    self.slo.class_stats(now=now)),
                "preemptions": self._n_preempted,
                "label_sets_dropped": drops}

    def _class_block(self, slo_classes: Dict[str, Any]) -> \
            Dict[str, Any]:
        """Merge the tracker's per-class SLO tallies with the queue
        plane (per-class depth, effective quota) and the policy spec
        so one block answers both 'how is the class doing' and 'what
        did we promise it'.  Every POLICY class appears even before
        traffic — a dashboard keying on the interactive class must
        not 404 during the first quiet minute."""
        depths = self._pending.class_depths()
        out: Dict[str, Any] = {}
        for name, cls in self.qos.classes.items():
            b = dict(slo_classes.get(name)
                     or self.slo.zero_class_stats())
            b["queue_depth"] = depths.get(name, 0)
            b["weight"] = cls.weight
            b["queue_cap"] = self.qos.cap(name, self.max_queue)
            b["preemptible"] = cls.preemptible
            out[name] = b
        for name, b in slo_classes.items():   # classes a policy swap
            if name not in out:               # orphaned: keep tallies
                out[name] = dict(b)
        return out

    def _update_gauges(self):
        m = self.metrics
        m.gauge("fleet_queue_depth").set(float(len(self._pending)))
        if self._qos_active:
            g = m.gauge("fleet_class_queue_depth")
            for name, d in self._pending.class_depths().items():
                g.labels(qos_class=name).set(float(d))
        states = self.states()
        for s, g in ((HEALTHY, "fleet_replicas_healthy"),
                     (DEGRADED, "fleet_replicas_degraded"),
                     (DEAD, "fleet_replicas_dead")):
            m.gauge(g).set(float(states.count(s)))
        occ = m.gauge("fleet_replica_occupancy")
        liv = m.gauge("fleet_replica_live")
        qd = m.gauge("fleet_replica_queue_depth")
        st = m.gauge("fleet_replica_state_code",
                     help="0 healthy, 1 degraded, 2 dead, 3 draining, "
                          "4 drained")
        for i, rep in enumerate(self.replicas):
            # cheap accessors, not stats(): this runs every tick and
            # stats() builds five histogram summaries per replica
            lbl = {"replica": i}
            occ.labels(**lbl).set(rep.live() / rep.slots)
            liv.labels(**lbl).set(float(rep.live()))
            qd.labels(**lbl).set(float(rep.queue_depth()))
            st.labels(**lbl).set(float(STATE_CODES[states[i]]))

    def stats(self) -> Dict[str, Any]:
        """Aggregated snapshot: fleet totals, per-replica health
        states (summaries AND full :meth:`health.ReplicaHealth.
        snapshot` records — the ``/statusz`` view), the SLO/goodput
        aggregates (``slo`` + top-level ``goodput_tokens_per_s``), the
        last deadline-sweep aggregate, and every replica's own
        ``stats()``."""
        states = self.states()
        # one window for every goodput figure in this snapshot: extend
        # to now while work is live, freeze at the last finish after
        slo = self.slo.stats(now=self._clock() if self.live()
                             else None)
        return {"replicas": len(self.replicas),
                "policy": getattr(self.policy, "name",
                                  type(self.policy).__name__),
                "queue_depth": len(self._pending),
                "inflight": len(self._inflight),
                "submitted": self._n_submitted,
                "finished": self._n_finished,
                "failed": self._n_failed,
                "tokens_generated": self._n_tokens,
                "shed": self._n_shed,
                "retries": self._n_retries,
                "failovers": self._n_failovers,
                "drains": self._n_drains,
                "deadline_exceeded": self._n_deadline,
                "deadline_last_sweep": dict(self._last_deadline_sweep),
                "preemptions": self._n_preempted,
                "mttr": self.mttr(),
                "recovery_in_flight": self.recovery_in_flight,
                "slo": slo,
                "goodput_tokens_per_s": slo["goodput_tokens_per_s"],
                "tenants": slo["tenants"],
                "tenants_dropped": slo["tenants_dropped"],
                "classes": self._class_block(slo["classes"]),
                "states": states,
                "healthy": states.count(HEALTHY),
                "degraded": states.count(DEGRADED),
                "dead": states.count(DEAD),
                "draining": states.count(DRAINING),
                "drained": states.count(DRAINED),
                "health": [h.snapshot() for h in self.health],
                "request_latency": self._m_latency.summary(),
                "replica_stats": [r.stats() for r in self.replicas]}

    def record(self) -> Dict[str, Any]:
        """The ``kind: fleet`` JSONL record
        (``observability.exporters.validate_fleet_record``); feed it
        through a :class:`~apex_tpu.observability.exporters.JsonlExporter`
        (or ``JsonlExporter.enrich``) to stamp the envelope.  Schema
        v5 adds the SLO/goodput fields and the deadline-sweep
        aggregate (optional in the validator, so archived records
        stay clean); v11 adds the per-tenant block — one compact
        tally per tenant (no histogram summaries; ``/tenantz`` has
        those) plus the overflow-fold count; v14 adds the per-CLASS
        block (same stripping rule) and the fleet preemption total."""
        s = self.stats()
        tenants = {t: {k: v for k, v in b.items()
                       if k not in ("queue_wait", "service_time")}
                   for t, b in s["tenants"].items()}
        classes = {c: {k: v for k, v in b.items()
                       if k not in ("queue_wait", "service_time")}
                   for c, b in s["classes"].items()}
        return {"kind": "fleet", "trace_id": self.trace_id,
                "tenants": tenants,
                "tenants_dropped": s["tenants_dropped"],
                "classes": classes,
                "preemptions": s["preemptions"],
                "replicas": s["replicas"], "policy": s["policy"],
                "healthy": s["healthy"], "degraded": s["degraded"],
                "dead": s["dead"],
                "queue_depth": s["queue_depth"],
                "submitted": s["submitted"], "finished": s["finished"],
                "failed": s["failed"], "shed": s["shed"],
                "retries": s["retries"], "failovers": s["failovers"],
                "drains": s["drains"],
                "tokens": s["tokens_generated"],
                "deadline_exceeded": s["deadline_exceeded"],
                "deadline_last_sweep": s["deadline_last_sweep"],
                "goodput_tokens_per_s": s["goodput_tokens_per_s"],
                "slo_attainment": s["slo"]["slo_attainment"],
                "tokens_within_slo": s["slo"]["goodput_tokens"],
                "mttr": s["mttr"]}
