"""Replica health: EWMA-driven states, circuit breaker, drain flags.

A replica is ``healthy``, ``degraded`` or ``dead`` based on two
exponentially-weighted moving averages the fleet feeds after every
``step()`` dispatch: the error rate (1.0 per raised step, 0.0 per clean
one) and the step latency.  Crossing the dead threshold — or a run of
consecutive errors, which catches a hard crash faster than any decaying
average can — OPENS the circuit breaker: the replica receives no
traffic and is not stepped for ``cooldown_steps`` fleet steps, then
moves to HALF-OPEN, where the fleet routes it exactly one probe
request.  A clean probe closes the circuit (EWMAs reset — the replica
earned a fresh record); a failed probe reopens it with the cooldown
multiplied by ``cooldown_backoff`` (capped), the standard
exponential-backoff breaker.

Cooldowns count FLEET STEPS, not wall seconds: the fleet is a
cooperative step loop, and step-counted state machines are exactly
reproducible under the fault harness (``faults.py``), which is how the
tests pin every transition.

Draining is orthogonal to the breaker: ``start_drain()`` stops
admission while the replica keeps stepping its in-flight requests;
when the fleet sees none left it calls ``finish_drain()`` (state
``drained``, not stepped).  ``reset()`` re-enlists a drained replica —
the rolling-restart handshake.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["HEALTHY", "DEGRADED", "DEAD", "DRAINING", "DRAINED",
           "STATE_CODES", "Ewma", "HealthConfig", "ReplicaHealth"]

HEALTHY = "healthy"
DEGRADED = "degraded"
DEAD = "dead"
DRAINING = "draining"
DRAINED = "drained"

# stable numeric encoding for the per-replica state gauge (a Prometheus
# gauge can't carry a string)
STATE_CODES = {HEALTHY: 0, DEGRADED: 1, DEAD: 2, DRAINING: 3,
               DRAINED: 4}


class Ewma:
    """Exponentially-weighted moving average: ``alpha`` is the weight
    of the newest sample (higher = faster to react, quicker to
    forgive)."""

    def __init__(self, alpha: float, value: float = 0.0):
        if not (0.0 < alpha <= 1.0):
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = alpha
        self.value = float(value)

    def update(self, x: float) -> float:
        self.value = self.alpha * float(x) + (1 - self.alpha) * self.value
        return self.value

    def reset(self, value: float = 0.0):
        self.value = float(value)


class HealthConfig:
    """Thresholds and breaker timings (all step-counted).

    - ``degraded_error_rate`` / ``dead_error_rate``: error-EWMA levels
      at which a replica is deprioritized / circuit-broken;
    - ``dead_consecutive``: hard-crash fast path — this many raises in
      a row opens the circuit regardless of the EWMA;
    - ``degraded_latency_s``: step-latency EWMA above this marks the
      replica degraded (None = latency never degrades — the right
      default when replica step times legitimately vary, e.g. mixed
      window sizes);
    - ``cooldown_steps`` → half-open after that many fleet steps;
      each failed probe multiplies the next cooldown by
      ``cooldown_backoff`` up to ``max_cooldown_steps``;
    - ``stall_steps``: the fleet's no-progress watchdog — a replica
      with live work that emits nothing for this many consecutive
      steps counts as erroring (catches stalls and result-droppers
      that never raise).
    """

    def __init__(self, error_alpha: float = 0.3,
                 latency_alpha: float = 0.3,
                 degraded_error_rate: float = 0.2,
                 dead_error_rate: float = 0.6,
                 dead_consecutive: int = 3,
                 degraded_latency_s: Optional[float] = None,
                 cooldown_steps: int = 8,
                 cooldown_backoff: float = 2.0,
                 max_cooldown_steps: int = 64,
                 stall_steps: int = 6):
        if not (0.0 < degraded_error_rate <= dead_error_rate <= 1.0):
            raise ValueError(
                f"need 0 < degraded_error_rate <= dead_error_rate <= 1,"
                f" got {degraded_error_rate}, {dead_error_rate}")
        if dead_consecutive < 1 or cooldown_steps < 1 or stall_steps < 1:
            raise ValueError("dead_consecutive, cooldown_steps and "
                             "stall_steps must be >= 1")
        self.error_alpha = error_alpha
        self.latency_alpha = latency_alpha
        self.degraded_error_rate = degraded_error_rate
        self.dead_error_rate = dead_error_rate
        self.dead_consecutive = dead_consecutive
        self.degraded_latency_s = degraded_latency_s
        self.cooldown_steps = cooldown_steps
        self.cooldown_backoff = cooldown_backoff
        self.max_cooldown_steps = max_cooldown_steps
        self.stall_steps = stall_steps


class ReplicaHealth:
    """Per-replica health record the fleet owns and feeds.

    ``ring`` (an :class:`~apex_tpu.observability.EventRing`; ``None``
    resolves the CURRENT process ring per note, the same default as
    every other flight-recorder producer) receives
    one flight-recorder event per state-machine TRANSITION —
    ``breaker_open`` / ``breaker_half_open`` / ``breaker_close`` /
    ``breaker_reopen``, ``drain_start`` / ``drain_finish``,
    ``health_reset`` — tagged ``replica=name`` (the fleet passes the
    int replica INDEX, so breaker events join the fleet's own ring
    events on the same ``ev["replica"]`` key).  Transitions are rare
    by construction, so the ring holds the breaker's whole recent
    history at post-mortem time."""

    def __init__(self, config: Optional[HealthConfig] = None,
                 ring=None, name=None):
        self.config = config or HealthConfig()
        self.ring = ring
        self.name = name
        self.error_rate = Ewma(self.config.error_alpha)
        self.latency = Ewma(self.config.latency_alpha)
        self.consecutive_errors = 0
        self.circuit = "closed"              # closed | open | half_open
        self._cooldown = self.config.cooldown_steps
        self._cooldown_left = 0
        self.draining = False
        self.drained = False
        self.errors_total = 0

    def _note(self, kind: str, **attrs):
        from ..observability import flightrec
        flightrec.resolve(self.ring).append(kind, replica=self.name,
                                            **attrs)

    # -- fleet feed --------------------------------------------------------
    def record_success(self, latency_s: float):
        """A step dispatch with fleet-assigned work came back clean."""
        self.consecutive_errors = 0
        self.error_rate.update(0.0)
        self.latency.update(latency_s)
        if self.circuit == "half_open":
            # the probe survived: close, and the replica earns a fresh
            # record (a decaying 0.9 error EWMA would re-kill it on the
            # next single hiccup)
            self.circuit = "closed"
            self._cooldown = self.config.cooldown_steps
            self.error_rate.reset()
            self.latency.reset(latency_s)
            self._note("breaker_close")

    def record_error(self):
        """A step/prefill raised (or the stall watchdog fired)."""
        self.errors_total += 1
        self.consecutive_errors += 1
        self.error_rate.update(1.0)
        if self.circuit == "half_open":
            # failed probe: reopen with exponential backoff
            self._cooldown = min(
                int(self._cooldown * self.config.cooldown_backoff),
                self.config.max_cooldown_steps)
            self._open("breaker_reopen")
        elif self.circuit == "closed" and (
                self.consecutive_errors >= self.config.dead_consecutive
                or self.error_rate.value >= self.config.dead_error_rate):
            self._open("breaker_open")

    def _open(self, kind: str = "breaker_open"):
        self.circuit = "open"
        self._cooldown_left = self._cooldown
        self._note(kind, cooldown_steps=self._cooldown,
                   consecutive_errors=self.consecutive_errors,
                   error_rate=round(self.error_rate.value, 4))

    def set_cooldown(self, steps: int,
                     remaining: Optional[int] = None):
        """Actuator surface (PR 11, ``fleet.autoscale``): retune the
        breaker's step-counted cooldowns.  ``steps`` seeds the NEXT
        cooldown (capped at ``max_cooldown_steps``); ``remaining``,
        when the circuit is currently open, rewrites the steps left
        before the half-open probe — shortening it re-probes a broken
        replica sooner when the fleet is starved for capacity,
        lengthening it stops wasting probes on a replica that keeps
        failing them."""
        if steps < 1:
            raise ValueError(f"steps must be >= 1, got {steps}")
        self._cooldown = min(int(steps),
                             self.config.max_cooldown_steps)
        if remaining is not None and self.circuit == "open":
            self._cooldown_left = max(1, int(remaining))
        self._note("cooldown_set", cooldown_steps=self._cooldown,
                   remaining=(self._cooldown_left
                              if self.circuit == "open" else None))

    def tick(self):
        """Advance one fleet step of breaker time."""
        if self.circuit == "open":
            self._cooldown_left -= 1
            if self._cooldown_left <= 0:
                self.circuit = "half_open"
                self._note("breaker_half_open")

    # -- drain lifecycle ---------------------------------------------------
    def start_drain(self):
        self.draining = True
        self.drained = False
        self._note("drain_start")

    def finish_drain(self):
        self.draining = False
        self.drained = True
        self._note("drain_finish")

    def reset(self):
        """Re-enlist (post rolling-restart): fresh record, closed
        circuit, admission back on."""
        self.__init__(self.config, ring=self.ring, name=self.name)
        self._note("health_reset")

    # -- queries -----------------------------------------------------------
    def snapshot(self) -> dict:
        """Plain-python view of the whole health record — what
        ``Fleet.stats()`` lists per replica and the introspection
        server's ``/statusz`` serves: the derived state plus the
        breaker internals a 3am triage actually wants (how many
        consecutive errors, how much cooldown is left, what the EWMAs
        say)."""
        return {"replica": self.name,
                "state": self.state,
                "circuit": self.circuit,
                "error_rate": round(self.error_rate.value, 6),
                "latency_ewma_s": round(self.latency.value, 6),
                "consecutive_errors": self.consecutive_errors,
                "errors_total": self.errors_total,
                "cooldown_steps_left": (self._cooldown_left
                                        if self.circuit == "open"
                                        else 0),
                "next_cooldown_steps": self._cooldown,
                "draining": self.draining,
                "drained": self.drained}

    @property
    def cooldown_left(self) -> int:
        """Steps left before the half-open probe (0 unless open) —
        the public face of the breaker's clock for the autoscale
        controller and ``snapshot()``."""
        return self._cooldown_left if self.circuit == "open" else 0

    @property
    def state(self) -> str:
        if self.drained:
            return DRAINED
        if self.draining:
            return DRAINING
        if self.circuit == "open":
            return DEAD
        if self.circuit == "half_open":
            return DEGRADED
        c = self.config
        if self.error_rate.value >= c.degraded_error_rate or (
                c.degraded_latency_s is not None
                and self.latency.value >= c.degraded_latency_s):
            return DEGRADED
        return HEALTHY

    def admissible(self) -> bool:
        """May this replica receive NEW requests?  Half-open passes —
        the fleet itself enforces the one-probe budget (it knows the
        in-flight count; this record does not)."""
        return (not self.draining and not self.drained
                and self.circuit != "open")

    def steppable(self) -> bool:
        """Should the fleet call step() on this replica at all?"""
        return not self.drained and self.circuit != "open"
