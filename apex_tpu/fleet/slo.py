"""SLO / goodput accounting: what the fleet delivered *in time*.

Raw throughput (``fleet_tokens_total / wall``) counts every token the
same; a serving fleet's users do not.  A token delivered after its
request's deadline bought nothing — the request already failed its SLO
— so the number that tracks user-visible capacity is **goodput**:
tokens from requests that finished within their deadline, per second
of serving.  The same per-request timeline also answers the first
triage question of any latency page: did the time go to **queue wait**
(submit → first dispatch: the fleet had no capacity) or to **service**
(dispatch → finish: the replica was slow)?

:class:`SloTracker` is fed by the fleet at the exact instants its
distributed-trace spans already record — submit, first dispatch,
finish/fail (``tracing``'s ``fleet_submit`` / ``fleet_dispatch`` /
``fleet_result`` events) — so the split it accounts and the split a
trace record shows are the same measurement; :func:`split_from_trace`
derives the latter from a ``kind: trace`` record and the tests pin the
two against each other.

Conventions:

- a request with **no deadline has no SLO**: it can neither attain nor
  miss one (it is excluded from ``slo_attainment``'s denominator), but
  its tokens still count toward goodput — they were delivered within
  every promise that was made;
- a request that **failed** (retries exhausted, rejected, deadline
  exceeded) delivers zero goodput tokens; if it carried a deadline it
  counts as an SLO miss;
- queue wait is submit → **first** dispatch: a failover's re-dispatch
  is service-side reality (the request was being served and had to be
  rescued), not queue starvation.

Registry metrics: ``fleet_queue_wait_seconds`` /
``fleet_service_seconds`` histograms, ``fleet_goodput_tokens_total`` /
``fleet_slo_miss_total`` counters, the ``fleet_slo_attainment`` and
``fleet_goodput_tokens_per_s`` gauges.  ``Fleet.stats()`` exposes the
same numbers fleet-locally under ``slo`` (plus top-level
``goodput_tokens_per_s``), and ``Fleet.record()`` carries them onto
the ``kind: fleet`` JSONL record
(``observability.exporters.validate_fleet_record`` pins the optional
fields).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..observability.metrics import (DEFAULT_MAX_LABEL_SETS,
                                     OVERFLOW_LABEL_VALUE)

__all__ = ["SloTracker", "split_from_trace"]

# sub-ms dispatch ticks up to multi-second waits under backlog
_WAIT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                 1.0, 2.5, 5.0, 10.0, 30.0)


def _new_tenant_bucket() -> Dict[str, Any]:
    # per-tenant cumulative tallies; t_first/t_last bound the tenant's
    # own goodput window the way the tracker-wide pair bounds the
    # fleet's
    return {"submitted": 0, "finished": 0, "failed": 0, "shed": 0,
            "deadline_exceeded": 0, "slo_misses": 0,
            "goodput_tokens": 0, "with_deadline": 0,
            "within_deadline": 0, "t_first": None, "t_last": None}


def _new_class_bucket() -> Dict[str, Any]:
    # per-QoS-class tallies: the tenant shape plus the preemption
    # count (only classes can be preempted — eviction direction is a
    # class-rank decision, so the tally lives here, not per tenant)
    b = _new_tenant_bucket()
    b["preempted"] = 0
    return b


class SloTracker:
    """Per-request deadline-attainment, queue-wait/service split, and
    goodput, owned and fed by one :class:`~apex_tpu.fleet.Fleet`.

    All numbers are fleet-local (the registry metrics aggregate across
    fleets sharing a registry; :meth:`stats` must not — the engine-
    scheduler rule).

    Requests may carry a ``tenant`` tag (``Fleet.submit(tenant=...)``):
    every tally above is then ALSO accounted per tenant — goodput
    tokens, attainment, shed and deadline-miss counts, and
    tenant-labeled children of the registry metrics
    (``fleet_goodput_tokens_total{tenant=...}``, the queue-wait /
    service histograms).  Tenant ids are user-supplied strings, so
    distinct tenants are capped at ``max_tenants``: past the cap a new
    tenant folds into the shared ``other`` bucket and
    ``tenants_dropped`` counts the fold — the same bound (and the same
    overflow value) the metrics registry applies to label sets.
    Untagged requests stay out of the per-tenant map; their numbers
    live only in the fleet-wide tallies."""

    def __init__(self, metrics, clock,
                 max_tenants: int = DEFAULT_MAX_LABEL_SETS):
        self._clock = clock
        self.max_tenants = max_tenants
        self._m_queue_wait = metrics.histogram(
            "fleet_queue_wait_seconds",
            help="submit to first dispatch per request (fleet had no "
                 "capacity)", buckets=_WAIT_BUCKETS)
        self._m_service = metrics.histogram(
            "fleet_service_seconds",
            help="first dispatch to finish per completed request",
            buckets=_WAIT_BUCKETS)
        self._m_goodput = metrics.counter(
            "fleet_goodput_tokens_total",
            help="tokens from requests that finished within their "
                 "deadline (no-deadline requests count: no SLO was "
                 "broken)")
        self._m_miss = metrics.counter(
            "fleet_slo_miss_total",
            help="deadlined requests that failed or finished late")
        self._m_attainment = metrics.gauge(
            "fleet_slo_attainment",
            help="within-deadline fraction of resolved deadlined "
                 "requests")
        self._m_goodput_rate = metrics.gauge(
            "fleet_goodput_tokens_per_s",
            help="goodput tokens over the submit-to-last-finish window")
        # rid -> [t_submit, t_first_dispatch|None, deadline_at|None,
        #         tenant-bucket-name|None, qos-class-name|None]
        self._open: Dict[int, list] = {}
        self._with_deadline = 0         # resolved requests that had one
        self._within = 0                # ... and finished in time
        self._goodput_tokens = 0
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        self._tenants: Dict[str, Dict[str, Any]] = {}
        self._tenants_dropped = 0
        # per-QoS-class tallies (PR 19): class names come from the
        # fleet's QosPolicy — a small operator-declared set, so no
        # cardinality fold is needed (unlike tenant ids)
        self._classes: Dict[str, Dict[str, Any]] = {}

    # -- per-tenant plumbing ------------------------------------------------
    def _tenant_bucket(self, tenant: Optional[str]
                       ) -> Optional[Dict[str, Any]]:
        """Resolve (and lazily create) a tenant's tally bucket; past
        ``max_tenants`` distinct ids the shared overflow bucket absorbs
        the newcomer and the fold is counted — mirrors
        ``metrics._Metric.labels``."""
        if tenant is None:
            return None
        t = str(tenant)
        bucket = self._tenants.get(t)
        if bucket is None:
            if len(self._tenants) >= self.max_tenants:
                self._tenants_dropped += 1
                t = OVERFLOW_LABEL_VALUE
                bucket = self._tenants.get(t)
            if bucket is None:
                bucket = _new_tenant_bucket()
                bucket["tenant"] = t
                self._tenants[t] = bucket
        return bucket

    def tenant_name(self, tenant: Optional[str]) -> Optional[str]:
        """The bucket name a tenant id folds to (the id itself below
        the cap, ``other`` past it) — what the fleet stamps on spans,
        ring events and metric labels so every surface agrees."""
        b = self._tenant_bucket(tenant)
        return None if b is None else b["tenant"]

    def _class_bucket(self, qos_class: Optional[str]
                      ) -> Optional[Dict[str, Any]]:
        if qos_class is None:
            return None
        bucket = self._classes.get(qos_class)
        if bucket is None:
            bucket = _new_class_bucket()
            self._classes[qos_class] = bucket
        return bucket

    # -- fleet feed (same instants as the trace spans) ---------------------
    def on_submit(self, rid: int, now: float,
                  deadline_at: Optional[float],
                  tenant: Optional[str] = None,
                  qos_class: Optional[str] = None):
        b = self._tenant_bucket(tenant)
        c = self._class_bucket(qos_class)
        self._open[rid] = [now, None, deadline_at,
                           None if b is None else b["tenant"],
                           qos_class]
        if self._t_first is None:
            self._t_first = now
        if b is not None:
            b["submitted"] += 1
            if b["t_first"] is None:
                b["t_first"] = now
        if c is not None:
            c["submitted"] += 1
            if c["t_first"] is None:
                c["t_first"] = now

    def on_shed(self, tenant: Optional[str] = None,
                qos_class: Optional[str] = None) -> Optional[str]:
        """A shed happens before a rid exists, so the fleet feeds the
        tenant directly; untagged sheds live only in the fleet-wide
        counter the fleet already keeps.  Returns the folded bucket
        name (for the ring-event stamp) or None."""
        c = self._class_bucket(qos_class)
        if c is not None:
            c["shed"] += 1
        b = self._tenant_bucket(tenant)
        if b is None:
            return None
        b["shed"] += 1
        return b["tenant"]

    def on_preempt(self, qos_class: Optional[str] = None):
        """One mid-decode eviction charged to the victim's class (the
        per-class needle the runbook pairs against queue_wait: rising
        preemptions with flat queue_wait means the batch class is
        paying for interactive admission, not starving in line)."""
        c = self._class_bucket(qos_class)
        if c is not None:
            c["preempted"] += 1

    def on_dispatch(self, rid: int, now: float):
        """First dispatch only: queue wait = submit → first dispatch;
        a failover's re-dispatch is service time, not queue time."""
        rec = self._open.get(rid)
        if rec is None or rec[1] is not None:
            return
        rec[1] = now
        wait = now - rec[0]
        self._m_queue_wait.observe(wait)
        if rec[3] is not None:
            self._m_queue_wait.labels(tenant=rec[3]).observe(wait)
        if rec[4] is not None:
            self._m_queue_wait.labels(qos_class=rec[4]).observe(wait)

    def _resolve(self, rid: int, now: float):
        rec = self._open.pop(rid, None)
        if rec is None:
            return None
        self._t_last = now
        return rec

    def on_finish(self, rid: int, now: float, tokens: int):
        rec = self._resolve(rid, now)
        if rec is None:
            return
        t_submit, t_dispatch, deadline_at, tenant, qos_class = rec
        b = None if tenant is None else self._tenants.get(tenant)
        c = None if qos_class is None else self._classes.get(qos_class)
        service = now - (t_dispatch if t_dispatch is not None
                         else t_submit)
        self._m_service.observe(service)
        if tenant is not None:
            self._m_service.labels(tenant=tenant).observe(service)
        if qos_class is not None:
            self._m_service.labels(qos_class=qos_class).observe(service)
        within = deadline_at is None or now <= deadline_at
        if deadline_at is not None:
            self._with_deadline += 1
            if b is not None:
                b["with_deadline"] += 1
            if c is not None:
                c["with_deadline"] += 1
            if within:
                self._within += 1
                if b is not None:
                    b["within_deadline"] += 1
                if c is not None:
                    c["within_deadline"] += 1
            else:
                self._m_miss.inc()
                if b is not None:
                    b["slo_misses"] += 1
                    self._m_miss.labels(tenant=tenant).inc()
                if c is not None:
                    c["slo_misses"] += 1
                    self._m_miss.labels(qos_class=qos_class).inc()
        if within:
            self._goodput_tokens += int(tokens)
            self._m_goodput.inc(int(tokens))
            if b is not None:
                b["goodput_tokens"] += int(tokens)
                self._m_goodput.labels(tenant=tenant).inc(int(tokens))
            if c is not None:
                c["goodput_tokens"] += int(tokens)
                self._m_goodput.labels(
                    qos_class=qos_class).inc(int(tokens))
        if b is not None:
            b["finished"] += 1
            b["t_last"] = now
        if c is not None:
            c["finished"] += 1
            c["t_last"] = now
        self._fold_gauges()

    def on_fail(self, rid: int, now: float,
                deadline_exceeded: bool = False):
        """Failed requests (retries exhausted, rejected, deadline
        exceeded) deliver no goodput; a deadlined one is an SLO miss.
        ``deadline_exceeded`` marks the sweep-kill case so the tenant's
        miss is attributed to the deadline, not a replica fault."""
        rec = self._resolve(rid, now)
        if rec is None:
            return
        tenant, qos_class = rec[3], rec[4]
        b = None if tenant is None else self._tenants.get(tenant)
        c = None if qos_class is None else self._classes.get(qos_class)
        if rec[2] is not None:
            self._with_deadline += 1
            self._m_miss.inc()
            if b is not None:
                b["with_deadline"] += 1
                b["slo_misses"] += 1
                self._m_miss.labels(tenant=tenant).inc()
            if c is not None:
                c["with_deadline"] += 1
                c["slo_misses"] += 1
                self._m_miss.labels(qos_class=qos_class).inc()
        if b is not None:
            b["failed"] += 1
            if deadline_exceeded:
                b["deadline_exceeded"] += 1
            b["t_last"] = now
        if c is not None:
            c["failed"] += 1
            if deadline_exceeded:
                c["deadline_exceeded"] += 1
            c["t_last"] = now
        self._fold_gauges()

    # -- aggregates ---------------------------------------------------------
    @property
    def slo_attainment(self) -> Optional[float]:
        """Within-deadline fraction over resolved deadlined requests;
        None while no deadlined request has resolved (an attainment of
        a promise nobody made would read as a perfect score)."""
        if self._with_deadline == 0:
            return None
        return self._within / self._with_deadline

    def goodput_tokens_per_s(self,
                             now: Optional[float] = None) -> float:
        """Goodput tokens over the first-submit → last-finish window
        (``now`` extends the window for a still-running fleet)."""
        if self._t_first is None:
            return 0.0
        ends = [t for t in (self._t_last, now) if t is not None]
        if not ends:
            return 0.0                   # nothing resolved yet
        dt = max(ends) - self._t_first
        return self._goodput_tokens / dt if dt > 0 else 0.0

    @staticmethod
    def _tenant_attainment(b: Dict[str, Any]) -> Optional[float]:
        if b["with_deadline"] == 0:
            return None
        return b["within_deadline"] / b["with_deadline"]

    @staticmethod
    def _tenant_rate(b: Dict[str, Any],
                     now: Optional[float] = None) -> float:
        if b["t_first"] is None:
            return 0.0
        ends = [t for t in (b["t_last"], now) if t is not None]
        if not ends:
            return 0.0
        dt = max(ends) - b["t_first"]
        return b["goodput_tokens"] / dt if dt > 0 else 0.0

    def _fold_gauges(self):
        att = self.slo_attainment
        if att is not None:
            self._m_attainment.set(att)
        self._m_goodput_rate.set(self.goodput_tokens_per_s())
        for t, b in self._tenants.items():
            ta = self._tenant_attainment(b)
            if ta is not None:
                self._m_attainment.labels(tenant=t).set(ta)
            self._m_goodput_rate.labels(tenant=t).set(
                self._tenant_rate(b))
        for cname, c in self._classes.items():
            ca = self._tenant_attainment(c)
            if ca is not None:
                self._m_attainment.labels(qos_class=cname).set(ca)
            self._m_goodput_rate.labels(qos_class=cname).set(
                self._tenant_rate(c))

    @property
    def tenants_dropped(self) -> int:
        """Fold events: submissions/sheds whose over-cap tenant id was
        absorbed by the ``other`` bucket (mirrors the per-call
        semantics of ``metrics._Metric.labels_dropped``)."""
        return self._tenants_dropped

    def tenant_stats(self, now: Optional[float] = None
                     ) -> Dict[str, Dict[str, Any]]:
        """Per-tenant rollup: the tally bucket plus derived attainment
        / goodput rate and the tenant-labeled queue-wait / service
        summaries (labeled children of the registry histograms — the
        one per-tenant number that is registry- rather than
        fleet-scoped when fleets share a registry)."""
        out: Dict[str, Dict[str, Any]] = {}
        for t, b in sorted(self._tenants.items()):
            entry = {k: v for k, v in b.items()
                     if k not in ("t_first", "t_last", "tenant")}
            entry["slo_attainment"] = self._tenant_attainment(b)
            entry["goodput_tokens_per_s"] = round(
                self._tenant_rate(b, now=now), 4)
            entry["queue_wait"] = self._m_queue_wait.labels(
                tenant=t).summary()
            entry["service_time"] = self._m_service.labels(
                tenant=t).summary()
            out[t] = entry
        return out

    @staticmethod
    def zero_class_stats() -> Dict[str, Any]:
        """The derived-stats shape of a class that saw no traffic —
        what ``Fleet._class_block`` emits for a policy class before
        its first request (so dashboards keyed on a class never 404)."""
        entry = {k: v for k, v in _new_class_bucket().items()
                 if k not in ("t_first", "t_last")}
        entry["slo_attainment"] = None
        entry["goodput_tokens_per_s"] = 0.0
        return entry

    def class_stats(self, now: Optional[float] = None
                    ) -> Dict[str, Dict[str, Any]]:
        """Per-QoS-class rollup, shaped like :meth:`tenant_stats`
        (same derived attainment/rate, same labeled histogram
        summaries) plus the per-class ``preempted`` count."""
        out: Dict[str, Dict[str, Any]] = {}
        for cname, c in sorted(self._classes.items()):
            entry = {k: v for k, v in c.items()
                     if k not in ("t_first", "t_last")}
            entry["slo_attainment"] = self._tenant_attainment(c)
            entry["goodput_tokens_per_s"] = round(
                self._tenant_rate(c, now=now), 4)
            entry["queue_wait"] = self._m_queue_wait.labels(
                qos_class=cname).summary()
            entry["service_time"] = self._m_service.labels(
                qos_class=cname).summary()
            out[cname] = entry
        return out

    def stats(self, now: Optional[float] = None) -> Dict[str, Any]:
        """``now`` extends the goodput window for a still-running
        fleet (``Fleet.stats()`` passes its clock while work is live,
        so every goodput figure in one snapshot uses ONE window)."""
        return {
            "with_deadline": self._with_deadline,
            "within_deadline": self._within,
            "slo_attainment": self.slo_attainment,
            "goodput_tokens": self._goodput_tokens,
            "goodput_tokens_per_s": round(
                self.goodput_tokens_per_s(now=now), 4),
            "queue_wait": self._m_queue_wait.summary(),
            "service_time": self._m_service.summary(),
            "tenants": self.tenant_stats(now=now),
            "tenants_dropped": self._tenants_dropped,
            "classes": self.class_stats(now=now),
        }


def split_from_trace(trace_record: Dict[str, Any]
                     ) -> Optional[Dict[str, float]]:
    """Queue-wait / service split of ONE request derived from its
    ``kind: trace`` record (the spans ``Fleet`` already emits):
    ``fleet_submit`` → first ``fleet_dispatch`` is queue wait,
    first dispatch → ``fleet_result``/``fleet_failed`` is service.
    Returns ``{queue_wait_s, service_s, total_s}`` (seconds; span
    timestamps are µs) or None when the record lacks the needed hops
    — the cross-check that pins :class:`SloTracker`'s accounting to
    the trace timeline."""
    t_submit = t_dispatch = t_end = None
    for sp in trace_record.get("spans", ()):
        name, ts = sp.get("name"), sp.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        if name == "fleet_submit" and t_submit is None:
            t_submit = ts
        elif name == "fleet_dispatch" and t_dispatch is None:
            t_dispatch = ts
        elif name in ("fleet_result", "fleet_failed"):
            t_end = ts                   # last one wins
    if t_submit is None or t_end is None:
        return None
    anchor = t_dispatch if t_dispatch is not None else t_end
    return {"queue_wait_s": max(anchor - t_submit, 0.0) / 1e6,
            "service_s": max(t_end - anchor, 0.0) / 1e6,
            "total_s": max(t_end - t_submit, 0.0) / 1e6}
