"""Seeded, deterministic fault injection around one replica.

:class:`FaultyReplica` wraps an ``Engine``/``Seq2SeqEngine`` (or
anything exposing the same surface) and misbehaves ON SCHEDULE: every
fault is a half-open step-count window ``(start, stop)`` over the
wrapper's own ``step()`` counter, so a test that says "the replica
dies at step 3" gets exactly that, every run.  An optional seeded
``p_error`` adds random step failures that are still deterministic per
seed — soak-style tests without flakiness.

Fault kinds (all composable):

- ``raise_on_step`` — ``step()`` raises :class:`ReplicaFault` BEFORE
  touching the wrapped engine, which therefore stays internally
  consistent (no half-donated buffers); this is the crash/failover
  fault the exactness tests lean on.
- ``raise_on_prefill`` — ``add_request``/``submit`` raise instead of
  admitting; exercises dispatch-retry.
- ``stall`` — ``step()`` returns ``{}`` without stepping the engine
  (optionally sleeping ``stall_s`` first): the hang that never raises.
  Only the fleet's no-progress watchdog can catch it.
- ``slow`` — ``step()`` sleeps ``slow_s`` then steps normally: correct
  results at degraded latency; feeds the latency EWMA.
- ``drop_results`` — the engine steps (state advances!) but the
  emitted tokens are swallowed.  The wrapped engine will still finish
  the requests internally; a fleet that relies on per-step emissions
  for liveness sees silence — watchdog territory again.

Everything else (``stats``, ``result``, ``cancel``, ``take_waiting``,
``free_slots``, …) proxies straight through, so a ``FaultyReplica`` is
a drop-in fleet member.

:class:`TrainingFaults` (PR 11) brings the same half-open
``[start, stop)`` step-window discipline to TRAINING-shaped failures —
replica death mid-step, torn/partial checkpoint writes, and
slow-straggler windows — for the elastic recovery harness
(``fleet.recovery.ElasticTrainer``).  Its windows count OBSERVED
steps (``check_step`` calls), which advance monotonically across
recoveries: a death armed at observed step 5 fires exactly once even
though the run, after resuming from an earlier snapshot, replays the
same *run*-step index again.
"""

from __future__ import annotations

import os
import time
from typing import Optional, Tuple

import numpy as np

__all__ = ["ReplicaFault", "FaultyReplica", "TrainingFaults"]


class ReplicaFault(RuntimeError):
    """An injected failure (never raised by real engines)."""


def _windows(spec) -> Tuple[Tuple[int, Optional[int]], ...]:
    """Normalize a window spec: None/() = never; True = always;
    (start, stop) or a sequence of such pairs; stop None = forever."""
    if spec is None or spec == ():
        return ()
    if spec is True:
        return ((0, None),)
    if (isinstance(spec, (tuple, list)) and len(spec) == 2
            and all(isinstance(x, int) or x is None for x in spec)):
        return (tuple(spec),)
    return tuple(tuple(w) for w in spec)


def _in(windows, t: int) -> bool:
    return any(s <= t and (e is None or t < e) for s, e in windows)


def _arm_windows(obj, known, steps: int, relative: bool, kinds: dict):
    """Shared ``arm()`` body for both fault harnesses: validate the
    kind names, parse each window spec, rebase relative offsets onto
    the harness's current step counter, and store as ``_<kind>``."""
    unknown = set(kinds) - set(known)
    if unknown:
        raise TypeError(f"unknown fault kind(s) {sorted(unknown)}; "
                        f"known: {list(known)}")
    for kind in known:
        if kind not in kinds:
            continue
        ws = _windows(kinds[kind])
        if relative:
            ws = tuple((s + steps, None if e is None else e + steps)
                       for s, e in ws)
        setattr(obj, "_" + kind, ws)


class FaultyReplica:
    """Deterministic misbehaving proxy around ``replica``.

    All windows are half-open ``[start, stop)`` intervals of the
    wrapper's step counter (``stop=None`` = forever); ``p_error``
    raises on a seeded coin flip per step, on top of any windows."""

    def __init__(self, replica, *,
                 raise_on_step=(), raise_on_prefill=(), stall=(),
                 slow=(), drop_results=(),
                 slow_s: float = 0.05, stall_s: float = 0.0,
                 p_error: float = 0.0, seed: int = 0, ring=None):
        self._inner = replica
        self._raise_on_step = _windows(raise_on_step)
        self._raise_on_prefill = _windows(raise_on_prefill)
        self._stall = _windows(stall)
        self._slow = _windows(slow)
        self._drop_results = _windows(drop_results)
        self.slow_s = slow_s
        self.stall_s = stall_s
        self.p_error = p_error
        self._rng = np.random.RandomState(seed)
        self.steps = 0                  # step() calls observed
        self.faults_fired = 0
        # flight-recorder trail: every injected fault lands in the ring
        # (default: the CURRENT process ring, resolved per append so a
        # set_ring swap moves the whole story together), so a
        # post-mortem dump shows the injected cause right next to the
        # breaker/failover transitions it provoked
        self._ring = ring

    @property
    def ring(self):
        from ..observability import flightrec
        return flightrec.resolve(self._ring)

    def _fired(self, kind: str, step: int):
        self.faults_fired += 1
        self.ring.append("fault_injected", fault=kind, step=step)

    # -- faulted surface ---------------------------------------------------
    def step(self):
        t = self.steps
        self.steps += 1
        if _in(self._stall, t):
            self._fired("stall", t)
            if self.stall_s:
                time.sleep(self.stall_s)
            return {}
        if _in(self._raise_on_step, t):
            self._fired("raise_on_step", t)
            raise ReplicaFault(f"injected step fault at step {t}")
        if self.p_error > 0.0 and self._rng.uniform() < self.p_error:
            # label the probabilistic fault as what it is — a
            # post-mortem reading the ring must not conclude a
            # deterministic window was configured at this step
            self._fired("p_error", t)
            raise ReplicaFault(f"injected step fault at step {t}")
        if _in(self._slow, t):
            self._fired("slow", t)
            time.sleep(self.slow_s)
        out = self._inner.step()
        if _in(self._drop_results, t):
            self._fired("drop_results", t)
            return {}
        return out

    def _check_prefill_fault(self):
        if _in(self._raise_on_prefill, self.steps):
            self._fired("raise_on_prefill", self.steps)
            raise ReplicaFault(
                f"injected prefill fault at step {self.steps}")

    def add_request(self, *a, **kw):
        self._check_prefill_fault()
        return self._inner.add_request(*a, **kw)

    def submit(self, *a, **kw):
        self._check_prefill_fault()
        return self._inner.submit(*a, **kw)

    def arm(self, *, relative: bool = True, **kinds):
        """(Re)program fault windows at runtime.  With ``relative=True``
        (default) window offsets count from the CURRENT step counter —
        ``arm(raise_on_step=(6, None))`` means "die 6 steps from now",
        which is how a bench arms a mid-run death AFTER its warmup
        traffic (a constructor window would fire during warmup).
        Passing ``()`` clears a fault kind."""
        _arm_windows(self, ("raise_on_step", "raise_on_prefill",
                            "stall", "slow", "drop_results"),
                     self.steps, relative, kinds)

    # -- transparent proxy -------------------------------------------------
    def __getattr__(self, name):
        # only reached for names not defined on the wrapper: stats,
        # result, cancel, take_waiting, free_slots, is_finished,
        # register_prefix, slots, metrics, ...
        return getattr(self._inner, name)


class TrainingFaults:
    """Seeded, deterministic training-shaped fault schedule.

    The elastic run harness calls :meth:`check_step` once per
    *attempted* training step (after the device math, BEFORE the
    result is committed) and :meth:`after_checkpoint` once per
    snapshot save.  All windows are half-open ``[start, stop)``
    intervals over the schedule's own OBSERVED-step counter — the
    count of ``check_step`` calls, which is monotonic across
    recoveries — so fault timelines stay exact in tests even when the
    run replays run-step indices after resuming from a snapshot.

    Fault kinds:

    - ``replica_death`` — :meth:`check_step` raises
      :class:`ReplicaFault` before the step result commits, the
      mid-step crash the recovery controller shrinks the world for
      (the in-memory state the harness holds stays consistent; the
      device state is abandoned and recovery resumes from the last
      durable snapshot anyway);
    - ``torn_checkpoint`` — :meth:`after_checkpoint` truncates the
      just-written snapshot file to ``torn_fraction`` of its bytes
      (out-of-band corruption AFTER the atomic rename: the save-time
      ``checkpoint_saved`` event truthfully named a snapshot that
      verified; restore-time checksum verification is what catches
      the tear);
    - ``straggler`` — :meth:`check_step` sleeps ``straggle_s`` (the
      slow window that degrades throughput without failing anything —
      supervisor ``throughput_regression`` territory);
    - ``preemption`` — the PLANNED failure real TPU fleets see most:
      a maintenance/preemption notice (SIGTERM with a grace window).
      :meth:`check_step` does not raise — it calls
      ``guard.preempt(...)`` on the attached
      :class:`~apex_tpu.fleet.recovery.PreemptionGuard` (the same
      entry point the real SIGTERM handler uses), and the run exits
      with a ``preempted`` verdict at the next step boundary after a
      coordinated emergency snapshot;
    - ``p_death`` — seeded random deaths per observed step, on top of
      any windows (soak-style, deterministic per seed).

    Every injected fault lands a ``fault_injected`` flight-ring event
    (``FaultyReplica`` discipline), so a post-mortem dump shows the
    cause next to the recovery actions it provoked.
    """

    def __init__(self, *, replica_death=(), torn_checkpoint=(),
                 straggler=(), preemption=(),
                 straggle_s: float = 0.01,
                 torn_fraction: float = 0.6,
                 p_death: float = 0.0, seed: int = 0, ring=None,
                 guard=None):
        if not (0.0 < torn_fraction < 1.0):
            raise ValueError(f"torn_fraction must be in (0, 1), got "
                             f"{torn_fraction}")
        self._replica_death = _windows(replica_death)
        self._torn_checkpoint = _windows(torn_checkpoint)
        self._straggler = _windows(straggler)
        self._preemption = _windows(preemption)
        # the PreemptionGuard the preemption fault notifies (the
        # ElasticTrainer auto-wires its own guard here when the
        # harness left it unset)
        self.guard = guard
        self.straggle_s = straggle_s
        self.torn_fraction = torn_fraction
        self.p_death = p_death
        self._rng = np.random.RandomState(seed)
        self.steps = 0                   # check_step calls observed
        self.faults_fired = 0
        self.torn_paths: list = []
        self._ring = ring

    @property
    def ring(self):
        from ..observability import flightrec
        return flightrec.resolve(self._ring)

    def _fired(self, kind: str, step: int, **attrs):
        self.faults_fired += 1
        self.ring.append("fault_injected", fault=kind, step=step,
                         **attrs)

    def check_step(self, run_step: Optional[int] = None) -> None:
        """One observed training step: straggle if scheduled, then die
        if scheduled.  ``run_step`` (the run's own step index, which
        can repeat across recoveries) only annotates the ring event —
        the windows are over the observed counter."""
        t = self.steps
        self.steps += 1
        if _in(self._straggler, t):
            self._fired("straggler", t, run_step=run_step,
                        straggle_s=self.straggle_s)
            if self.straggle_s:
                time.sleep(self.straggle_s)
        if _in(self._preemption, t):
            # a planned preemption notice, not a crash: notify the
            # guard (idempotent) and keep stepping — the run exits at
            # its next step boundary after an emergency snapshot
            self._fired("preemption", t, run_step=run_step)
            if self.guard is not None:
                self.guard.preempt(
                    f"injected preemption at observed step {t}")
        if _in(self._replica_death, t):
            self._fired("replica_death", t, run_step=run_step)
            raise ReplicaFault(
                f"injected replica death at observed step {t}"
                + (f" (run step {run_step})"
                   if run_step is not None else ""))
        if self.p_death > 0.0 and self._rng.uniform() < self.p_death:
            self._fired("p_death", t, run_step=run_step)
            raise ReplicaFault(
                f"injected replica death (seeded) at observed step {t}")

    def after_checkpoint(self, path: str) -> bool:
        """Tear the snapshot at ``path`` if the CURRENT observed step
        sits in a torn window (truncate to ``torn_fraction`` of its
        bytes — a partial write frozen mid-flight).  Returns True when
        the file was torn."""
        # the window is evaluated at the observed step of the save,
        # i.e. the steps counter AFTER the step that triggered it
        t = self.steps
        if not _in(self._torn_checkpoint, t):
            return False
        size = os.path.getsize(path)
        keep = max(1, int(size * self.torn_fraction))
        with open(path, "rb+") as f:
            f.truncate(keep)
        self.torn_paths.append(path)
        self._fired("torn_checkpoint", t, path=path,
                    bytes_kept=keep, bytes_total=size)
        return True

    def arm(self, *, relative: bool = True, **kinds):
        """(Re)program fault windows at runtime, ``FaultyReplica.arm``
        semantics: with ``relative=True`` offsets count from the
        current observed step; ``()`` clears a kind."""
        _arm_windows(self, ("replica_death", "torn_checkpoint",
                            "straggler", "preemption"),
                     self.steps, relative, kinds)
