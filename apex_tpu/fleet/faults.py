"""Seeded, deterministic fault injection around one replica.

:class:`FaultyReplica` wraps an ``Engine``/``Seq2SeqEngine`` (or
anything exposing the same surface) and misbehaves ON SCHEDULE: every
fault is a half-open step-count window ``(start, stop)`` over the
wrapper's own ``step()`` counter, so a test that says "the replica
dies at step 3" gets exactly that, every run.  An optional seeded
``p_error`` adds random step failures that are still deterministic per
seed — soak-style tests without flakiness.

Fault kinds (all composable):

- ``raise_on_step`` — ``step()`` raises :class:`ReplicaFault` BEFORE
  touching the wrapped engine, which therefore stays internally
  consistent (no half-donated buffers); this is the crash/failover
  fault the exactness tests lean on.
- ``raise_on_prefill`` — ``add_request``/``submit`` raise instead of
  admitting; exercises dispatch-retry.
- ``stall`` — ``step()`` returns ``{}`` without stepping the engine
  (optionally sleeping ``stall_s`` first): the hang that never raises.
  Only the fleet's no-progress watchdog can catch it.
- ``slow`` — ``step()`` sleeps ``slow_s`` then steps normally: correct
  results at degraded latency; feeds the latency EWMA.
- ``drop_results`` — the engine steps (state advances!) but the
  emitted tokens are swallowed.  The wrapped engine will still finish
  the requests internally; a fleet that relies on per-step emissions
  for liveness sees silence — watchdog territory again.

Everything else (``stats``, ``result``, ``cancel``, ``take_waiting``,
``free_slots``, …) proxies straight through, so a ``FaultyReplica`` is
a drop-in fleet member.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

import numpy as np

__all__ = ["ReplicaFault", "FaultyReplica"]


class ReplicaFault(RuntimeError):
    """An injected failure (never raised by real engines)."""


def _windows(spec) -> Tuple[Tuple[int, Optional[int]], ...]:
    """Normalize a window spec: None/() = never; True = always;
    (start, stop) or a sequence of such pairs; stop None = forever."""
    if spec is None or spec == ():
        return ()
    if spec is True:
        return ((0, None),)
    if (isinstance(spec, (tuple, list)) and len(spec) == 2
            and all(isinstance(x, int) or x is None for x in spec)):
        return (tuple(spec),)
    return tuple(tuple(w) for w in spec)


def _in(windows, t: int) -> bool:
    return any(s <= t and (e is None or t < e) for s, e in windows)


class FaultyReplica:
    """Deterministic misbehaving proxy around ``replica``.

    All windows are half-open ``[start, stop)`` intervals of the
    wrapper's step counter (``stop=None`` = forever); ``p_error``
    raises on a seeded coin flip per step, on top of any windows."""

    def __init__(self, replica, *,
                 raise_on_step=(), raise_on_prefill=(), stall=(),
                 slow=(), drop_results=(),
                 slow_s: float = 0.05, stall_s: float = 0.0,
                 p_error: float = 0.0, seed: int = 0, ring=None):
        self._inner = replica
        self._raise_on_step = _windows(raise_on_step)
        self._raise_on_prefill = _windows(raise_on_prefill)
        self._stall = _windows(stall)
        self._slow = _windows(slow)
        self._drop_results = _windows(drop_results)
        self.slow_s = slow_s
        self.stall_s = stall_s
        self.p_error = p_error
        self._rng = np.random.RandomState(seed)
        self.steps = 0                  # step() calls observed
        self.faults_fired = 0
        # flight-recorder trail: every injected fault lands in the ring
        # (default: the CURRENT process ring, resolved per append so a
        # set_ring swap moves the whole story together), so a
        # post-mortem dump shows the injected cause right next to the
        # breaker/failover transitions it provoked
        self._ring = ring

    @property
    def ring(self):
        from ..observability import flightrec
        return flightrec.resolve(self._ring)

    def _fired(self, kind: str, step: int):
        self.faults_fired += 1
        self.ring.append("fault_injected", fault=kind, step=step)

    # -- faulted surface ---------------------------------------------------
    def step(self):
        t = self.steps
        self.steps += 1
        if _in(self._stall, t):
            self._fired("stall", t)
            if self.stall_s:
                time.sleep(self.stall_s)
            return {}
        if _in(self._raise_on_step, t):
            self._fired("raise_on_step", t)
            raise ReplicaFault(f"injected step fault at step {t}")
        if self.p_error > 0.0 and self._rng.uniform() < self.p_error:
            # label the probabilistic fault as what it is — a
            # post-mortem reading the ring must not conclude a
            # deterministic window was configured at this step
            self._fired("p_error", t)
            raise ReplicaFault(f"injected step fault at step {t}")
        if _in(self._slow, t):
            self._fired("slow", t)
            time.sleep(self.slow_s)
        out = self._inner.step()
        if _in(self._drop_results, t):
            self._fired("drop_results", t)
            return {}
        return out

    def _check_prefill_fault(self):
        if _in(self._raise_on_prefill, self.steps):
            self._fired("raise_on_prefill", self.steps)
            raise ReplicaFault(
                f"injected prefill fault at step {self.steps}")

    def add_request(self, *a, **kw):
        self._check_prefill_fault()
        return self._inner.add_request(*a, **kw)

    def submit(self, *a, **kw):
        self._check_prefill_fault()
        return self._inner.submit(*a, **kw)

    def arm(self, *, relative: bool = True, **kinds):
        """(Re)program fault windows at runtime.  With ``relative=True``
        (default) window offsets count from the CURRENT step counter —
        ``arm(raise_on_step=(6, None))`` means "die 6 steps from now",
        which is how a bench arms a mid-run death AFTER its warmup
        traffic (a constructor window would fire during warmup).
        Passing ``()`` clears a fault kind."""
        known = ("raise_on_step", "raise_on_prefill", "stall", "slow",
                 "drop_results")
        unknown = set(kinds) - set(known)
        if unknown:
            raise TypeError(f"unknown fault kind(s) {sorted(unknown)}; "
                            f"known: {list(known)}")
        for kind in known:
            if kind not in kinds:
                continue
            ws = _windows(kinds[kind])
            if relative:
                ws = tuple((s + self.steps,
                            None if e is None else e + self.steps)
                           for s, e in ws)
            setattr(self, "_" + kind, ws)

    # -- transparent proxy -------------------------------------------------
    def __getattr__(self, name):
        # only reached for names not defined on the wrapper: stats,
        # result, cancel, take_waiting, free_slots, is_finished,
        # register_prefix, slots, metrics, ...
        return getattr(self._inner, name)
