"""Serving fleet: multi-replica orchestration over the engines.

The single :class:`~apex_tpu.serving.Engine` solved continuous
batching on one device program; this package is the host-side layer
the ROADMAP's "heavy traffic" goal needs above it — the inference-side
sibling of ``apex.parallel.DistributedDataParallel``'s replica model:

- :class:`Fleet` (fleet.py): N replicas behind one
  submit/step/result API, bounded-queue backpressure
  (:class:`FleetOverloaded`), failover that restarts reclaimed
  requests on survivors with the exactness contract intact;
- routing policies (router.py): :class:`RoundRobin`,
  :class:`LeastLoaded`, :class:`PrefixAffinity`, plus
  :class:`RetryPolicy` (exponential backoff, seeded jitter);
- health (health.py): EWMA-driven ``healthy`` / ``degraded`` /
  ``dead`` states, a circuit breaker with half-open probing, and
  graceful drain for rolling restarts;
- faults (faults.py): :class:`FaultyReplica`, the seeded
  deterministic fault-injection harness the tests use to prove the
  failover story instead of asserting it;
- SLO/goodput (slo.py): :class:`SloTracker`, per-request
  deadline-attainment, the queue-wait vs service split (fed at the
  same instants the distributed-trace spans record), and
  ``goodput_tokens_per_s`` — tokens delivered *within* SLO — on
  ``Fleet.stats()``/``record()``;
- recovery (recovery.py, PR 11): the telemetry→action loop, training
  side — :class:`ElasticTrainer` shrinks the data axis on a replica
  death, redistributes ZeRO-1 shards (:func:`reshard_flat_state`),
  resumes from the last checksum-durable snapshot, and accounts MTTR
  in ``kind: recovery`` records; :class:`RecoveryLog` is the shared
  episode/action bookkeeping;
- autoscale (autoscale.py, PR 11): the loop's serving side —
  :class:`SloController` reads the SLO tracker's per-tick deltas and
  actuates the admission bound (per CLASS under a multi-class QoS
  policy), decode windows, drain/undrain and the breaker's cooldowns
  with hysteresis and bounded actuation (``tests/ci/chaos_smoke.py``
  gates the no-oscillation contract);
- QoS (qos.py, PR 19): :class:`QosPolicy` (priority classes: weight,
  default deadline, queue share, preemptibility, tenant->class map)
  and :class:`WfqQueue` — the deterministic stride-scheduled pending
  queue replacing FIFO admission, plus the fleet-side decode
  preemption it enables (evict a low class mid-decode, re-queue from
  the prompt, exactness intact).

Attach the live introspection server with one call
(``apex_tpu.observability.server.serve(fleet=fleet)``): ``/statusz``
serves ``Fleet.stats()``, ``/metricsz`` the fleet registry,
``/flightz`` the fleet's flight ring.  See docs/fleet.md.
"""

from .fleet import Fleet
from .health import (DEAD, DEGRADED, DRAINED, DRAINING, HEALTHY,
                     STATE_CODES, Ewma, HealthConfig, ReplicaHealth)
from .router import (FleetOverloaded, LeastLoaded, PrefixAffinity,
                     RetryPolicy, RoundRobin, make_policy)
from .faults import FaultyReplica, ReplicaFault, TrainingFaults
from .slo import SloTracker, split_from_trace
from .recovery import (RECOVERY_ACTION_KINDS, RECOVERY_CAUSES,
                       RECOVERY_ROLES, ElasticConfig, ElasticTrainer,
                       PreemptionGuard, RecoveryError, RecoveryLog,
                       reshard_flat_state)
from .autoscale import AutoscaleConfig, SloController
from .qos import QosClass, QosPolicy, WfqQueue
from . import qos, slo

__all__ = ["Fleet", "FleetOverloaded", "RetryPolicy", "RoundRobin",
           "QosClass", "QosPolicy", "WfqQueue", "qos",
           "LeastLoaded", "PrefixAffinity", "make_policy",
           "HealthConfig", "ReplicaHealth", "Ewma", "HEALTHY",
           "DEGRADED", "DEAD", "DRAINING", "DRAINED", "STATE_CODES",
           "FaultyReplica", "ReplicaFault", "TrainingFaults",
           "SloTracker", "split_from_trace", "slo",
           "RECOVERY_ROLES", "RECOVERY_ACTION_KINDS",
           "RECOVERY_CAUSES", "RecoveryError", "RecoveryLog",
           "PreemptionGuard", "ElasticConfig", "ElasticTrainer",
           "reshard_flat_state", "AutoscaleConfig", "SloController"]
