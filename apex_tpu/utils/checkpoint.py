"""Checkpoint / resume for whole training states.

The reference relies on raw torch ``state_dict`` conventions and ships the
"option 2" pattern — fp32 masters and loss-scaler state saved alongside
the half model weights (fp16_utils/fp16_optimizer.py:298-359;
examples/imagenet/main_amp.py:170-185 epoch/best-prec resume).  SURVEY.md
§5 flags that the reference's new amp API *lacks* an ``amp.state_dict``;
apex_tpu closes that gap: ``amp.state_dict`` exists, and this module
persists any training-state pytree — params, optimizer state (masters
included, they are ordinary optimizer-state leaves here), BN running
stats, scaler state, step counters — to one atomic file.

Format: a single ``.npz`` holding every leaf keyed by its pytree keypath
string.  Restore is template-shaped: you pass the pytree you want filled
(built the same way as at save time), so no pickled treedefs are needed
and the format is stable across sessions and jax versions.

    ckpt.save_checkpoint(dir, step, {"params": params, "opt": opt_state,
                                     "bn": bn_state, "amp": amp_sd})
    state = ckpt.restore_checkpoint(dir, template)          # latest
    state = ckpt.restore_checkpoint(dir, template, step=7)  # specific
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import time
import zlib
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["CheckpointCorrupt", "save_checkpoint", "restore_checkpoint",
           "latest_step", "available_steps", "latest_durable_step",
           "verify_checkpoint", "load_data_state", "tree_bytes",
           "tree_checksum", "record_checkpoint_io"]

_FMT = "ckpt_{step:08d}.npz"
_RE = re.compile(r"ckpt_(\d{8})\.npz$")

# reserved npz keys; never pytree keypaths (keystr always starts with a
# bracket/quote).  __checksum__ carries the snapshot's content
# checksum; __data_state__ carries the optional data-pipeline cursor
# blob (a JSON dict stored as uint8 bytes) so a snapshot names its
# exact sample-stream position — the preemption-safe resume contract.
# The data-state blob sits UNDER the checksum: it is part of the leaf
# dict the crc covers, so a torn or tampered cursor fails verification
# like any other leaf.
_CHECKSUM_KEY = "__checksum__"
_DATA_STATE_KEY = "__data_state__"


class CheckpointCorrupt(RuntimeError):
    """A snapshot failed content verification (torn/partial write, bit
    rot, truncation).  Restore raises this instead of silently loading
    garbage; the recovery controller catches it and falls back to the
    previous durable snapshot (``latest_durable_step``)."""

# seconds; local-disk npz snapshots up to multi-minute sharded
# TensorStore writes
_CKPT_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                 30.0, 60.0, 120.0, 300.0)


def tree_bytes(tree: Any) -> int:
    """In-memory bytes of one state tree's leaves (what a snapshot
    persists, pre-compression) — the ``checkpoint_snapshot_bytes``
    gauge."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        n = getattr(leaf, "nbytes", None)
        total += int(n) if n is not None else np.asarray(leaf).nbytes
    return total


def record_checkpoint_io(op: str, seconds: float, step=None,
                         nbytes: Optional[int] = None,
                         path: Optional[str] = None,
                         async_save: bool = False,
                         registry=None, ring=None) -> None:
    """Checkpoint telemetry shared by the npz and Orbax paths: fold
    one save/restore into the metrics registry (latency histogram,
    op counter, snapshot-bytes gauge) and — for saves — append the
    ``checkpoint_saved`` flight-ring event the training-run
    supervisor's progress watermark consumes (a run that is writing
    checkpoints is making durable progress).  ``op`` is ``"save"`` or
    ``"restore"``; defaults resolve the process registry/ring per
    call, the same rule as every other producer."""
    if op not in ("save", "restore"):
        raise ValueError(f"op must be 'save' or 'restore', got {op!r}")
    from ..observability import flightrec
    from ..observability.metrics import get_registry
    reg = registry if registry is not None else get_registry()
    reg.histogram(f"checkpoint_{op}_seconds",
                  help=f"wall seconds per checkpoint {op}",
                  buckets=_CKPT_BUCKETS).observe(float(seconds))
    reg.counter(f"checkpoint_{op}s_total").inc()
    if nbytes is not None:
        reg.gauge("checkpoint_snapshot_bytes",
                  help="leaf bytes of the last checkpointed state tree"
                  ).set(float(nbytes))
    if op == "save":
        flightrec.resolve(ring).append(
            "checkpoint_saved",
            step=int(step) if step is not None else None,
            bytes=nbytes, path=path, async_save=bool(async_save),
            duration_s=round(float(seconds), 6))


def tree_checksum(leaves: dict) -> int:
    """Order-independent-by-construction content checksum of a leaf
    dict (``{keypath: np.ndarray}``): crc32 chained over the sorted
    keys, each leaf's dtype/shape, and its raw bytes.  Shared by the
    npz path (embedded under ``__checksum__``) and the Orbax path
    (sidecar file) so one verifier serves both."""
    crc = 0
    for key in sorted(leaves):
        arr = np.asarray(leaves[key])
        crc = zlib.crc32(key.encode(), crc)
        crc = zlib.crc32(str(arr.dtype).encode(), crc)
        crc = zlib.crc32(str(tuple(arr.shape)).encode(), crc)
        crc = zlib.crc32(np.ascontiguousarray(arr).tobytes(), crc)
    return crc & 0xFFFFFFFF


def _leaf_dict(tree: Any) -> dict:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        if key in out:
            raise ValueError(f"duplicate keypath {key!r}")
        arr = np.asarray(leaf)
        if arr.dtype.kind == "V" or arr.dtype.name in ("bfloat16",):
            # npz has no bfloat16/fp8; fp32 holds them exactly, and restore
            # casts back to the template dtype
            arr = np.asarray(leaf, np.float32)
        out[key] = arr
    return out


def save_checkpoint(ckpt_dir: str, step: int, tree: Any,
                    keep: Optional[int] = None,
                    data_state: Optional[dict] = None) -> str:
    """Write ``tree`` for ``step``; atomic (write-temp + rename).  With
    ``keep``, retain only the newest ``keep`` checkpoints.
    ``data_state`` is an optional JSON-serializable dict (e.g.
    ``DataLoader.state_dict()``) persisted alongside the tree under the
    content checksum, so the snapshot names its exact data cursor;
    read it back with :func:`load_data_state`."""
    if keep is not None and keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    os.makedirs(ckpt_dir, exist_ok=True)
    t0 = time.perf_counter()
    leaves = _leaf_dict(tree)
    for reserved in (_CHECKSUM_KEY, _DATA_STATE_KEY):
        if reserved in leaves:
            raise ValueError(f"{reserved!r} is a reserved key")
    if data_state is not None:
        blob = json.dumps(data_state, sort_keys=True).encode()
        leaves[_DATA_STATE_KEY] = np.frombuffer(blob, np.uint8)
    # content checksum over exactly the arrays being written: restore
    # recomputes it from what it read, so a torn/partial write (or
    # later bit rot) can never load silently.  Because the checksum is
    # computed from the data in hand and the file lands by atomic
    # rename, the checkpoint_saved event below only ever names a
    # snapshot that verifies.
    leaves[_CHECKSUM_KEY] = np.uint32(tree_checksum(leaves))
    path = os.path.join(ckpt_dir, _FMT.format(step=step))
    fd, tmp = tempfile.mkstemp(dir=ckpt_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **leaves)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    # telemetry only after the rename: a failed write must not emit a
    # checkpoint_saved event the supervisor would count as progress
    record_checkpoint_io("save", time.perf_counter() - t0, step=step,
                         nbytes=tree_bytes(tree), path=path)
    if keep is not None:
        for s in available_steps(ckpt_dir)[:-keep]:
            os.unlink(os.path.join(ckpt_dir, _FMT.format(step=s)))
    return path


def available_steps(ckpt_dir: str) -> list:
    steps = []
    if os.path.isdir(ckpt_dir):
        for name in os.listdir(ckpt_dir):
            m = _RE.match(name)
            if m:
                steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(ckpt_dir: str) -> Optional[int]:
    steps = available_steps(ckpt_dir)
    return steps[-1] if steps else None


def _load_verified(path: str) -> dict:
    """Read one snapshot and verify its content checksum; raises
    :class:`CheckpointCorrupt` on a torn/truncated/corrupted file.
    Pre-checksum snapshots (no ``__checksum__`` entry) load as-is —
    they predate verification and are trusted like before."""
    import zipfile
    try:
        with np.load(path) as data:
            stored = dict(data)
    except (OSError, ValueError, EOFError, KeyError,
            zipfile.BadZipFile) as e:
        # a torn npz fails in the zip layer (BadZipFile on a truncated
        # central directory, KeyError on a missing member) or in the
        # per-array header parse — all corruption
        raise CheckpointCorrupt(f"{path}: unreadable snapshot ({e})")
    want = stored.pop(_CHECKSUM_KEY, None)
    if want is not None:
        got = tree_checksum(stored)
        if int(want) != got:
            raise CheckpointCorrupt(
                f"{path}: content checksum mismatch (stored "
                f"{int(want):#010x}, recomputed {got:#010x}) — torn "
                f"write or bit rot; fall back to an earlier snapshot")
    return stored


def verify_checkpoint(ckpt_dir: str, step: int) -> None:
    """Verify one snapshot's content checksum without restoring it;
    raises :class:`CheckpointCorrupt` (or ``FileNotFoundError``)."""
    path = os.path.join(ckpt_dir, _FMT.format(step=step))
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    _load_verified(path)


def load_data_state(ckpt_dir: str,
                    step: Optional[int] = None) -> Optional[dict]:
    """Read the snapshot's data-pipeline cursor blob (what
    ``save_checkpoint(..., data_state=...)`` persisted), verified under
    the same content checksum as the tree.  ``None`` when the snapshot
    carries no data state (it predates the field, or the run had no
    checkpointable pipeline)."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {ckpt_dir!r}")
    path = os.path.join(ckpt_dir, _FMT.format(step=step))
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    stored = _load_verified(path)
    blob = stored.get(_DATA_STATE_KEY)
    if blob is None:
        return None
    return json.loads(np.asarray(blob, np.uint8).tobytes().decode())


def latest_durable_step(ckpt_dir: str) -> Optional[int]:
    """Newest snapshot step that VERIFIES — the recovery controller's
    resume-point oracle: torn snapshots are skipped (newest first)
    until one passes its content check; ``None`` when none do."""
    for step in reversed(available_steps(ckpt_dir)):
        try:
            verify_checkpoint(ckpt_dir, step)
            return step
        except CheckpointCorrupt:
            continue
    return None


def restore_checkpoint(ckpt_dir: str, template: Any,
                       step: Optional[int] = None) -> Any:
    """Return ``template`` with every leaf replaced by the stored value
    (cast to the template leaf's dtype, shapes must match).  ``step=None``
    loads the newest checkpoint; raises FileNotFoundError if none and
    :class:`CheckpointCorrupt` when the snapshot fails its content
    checksum (torn write)."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {ckpt_dir!r}")
    path = os.path.join(ckpt_dir, _FMT.format(step=step))
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    t0 = time.perf_counter()
    stored = _load_verified(path)
    stored.pop(_DATA_STATE_KEY, None)   # read via load_data_state
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    out = []
    for kp, leaf in flat:
        key = jax.tree_util.keystr(kp)
        if key not in stored:
            raise KeyError(
                f"checkpoint {path} has no entry for {key!r} — template "
                "structure does not match the saved state")
        arr = stored[key]
        if hasattr(leaf, "shape") and tuple(arr.shape) != tuple(leaf.shape):
            raise ValueError(
                f"shape mismatch for {key!r}: checkpoint {arr.shape} vs "
                f"template {leaf.shape}")
        dtype = getattr(leaf, "dtype", arr.dtype)
        out.append(jnp.asarray(arr, dtype))
    restored = jax.tree_util.tree_unflatten(treedef, out)
    record_checkpoint_io("restore", time.perf_counter() - t0,
                         step=step, nbytes=tree_bytes(restored),
                         path=path)
    return restored
