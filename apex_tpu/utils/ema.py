"""Exponential moving average of parameters (Polyak averaging).

Standard eval-time smoothing (beyond the 2019 reference's scope, but
table stakes for a training toolkit): keep a decayed running average of
the param pytree and evaluate/serve with it.  Functional state —
``(avg, step)`` — so it rides the jit train step like optimizer state::

    ema_state = ema.init(params)
    ...inside the step...
    ema_state = ema.update(ema_state, params, decay=0.999)
    ...at eval...
    eval_params = ema.value(ema_state, decay=0.999)   # debiased

``value`` divides by ``1 - decay**step`` (Adam-style debias), so early
checkpoints are unbiased instead of shrunk toward the zero init.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["EmaState", "init", "update", "value"]


class EmaState(NamedTuple):
    avg: Any          # pytree matching params, fp32
    step: jax.Array   # int32; number of updates applied


def init(params: Any) -> EmaState:
    return EmaState(
        avg=jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=jnp.float32), params),
        step=jnp.zeros((), jnp.int32))


def update(state: EmaState, params: Any, decay: float = 0.999
           ) -> EmaState:
    avg = jax.tree_util.tree_map(
        lambda a, p: decay * a + (1.0 - decay) * p.astype(jnp.float32),
        state.avg, params)
    return EmaState(avg=avg, step=state.step + 1)


def value(state: EmaState, decay: float = 0.999) -> Any:
    """Debiased average, cast back to nothing (fp32 tree) — cast to the
    model dtype at the call site if needed."""
    corr = 1.0 - jnp.power(jnp.asarray(decay, jnp.float32),
                           state.step.astype(jnp.float32))
    corr = jnp.maximum(corr, 1e-12)
    return jax.tree_util.tree_map(lambda a: a / corr, state.avg)
