"""HuggingFace checkpoint interop: torch state_dicts -> apex_tpu params.

A user switching from the reference stack brings torch-ecosystem
weights; these converters map ``transformers`` BERT / GPT-2 / Llama / ResNet state_dicts
onto apex_tpu's param trees, and the tests prove output parity against
the HF torch implementations themselves (random-init models, so no
network access is needed — the proof is architectural, and a real
pretrained checkpoint converts the same way).

    hf = transformers.BertModel(hf_cfg)          # or .from_pretrained
    cfg, params = hf_interop.bert_from_hf(hf)
    model = apex_tpu.models.BertModel(cfg)
    seq, pooled = model(params, ids, token_type_ids=tt)

Conventions handled: HF's separate q/k/v projections fuse into the
(3E, E) qkv weight (head-major row order matches), GPT-2's Conv1D
weights transpose into Linear layout, and BERT's exact-erf gelu is
selected via ``hidden_act="gelu_exact"``.
"""

from __future__ import annotations

from typing import Any, Tuple

import numpy as np


def _t(x):
    return np.asarray(x.detach().cpu().numpy())


def _lin(sd, prefix):
    return {"weight": _t(sd[f"{prefix}.weight"]),
            "bias": _t(sd[f"{prefix}.bias"])}


_ln = _lin      # LayerNorm params share the weight/bias naming


def bert_from_hf(hf_model) -> Tuple[Any, Any]:
    """(BertConfig, params) for apex_tpu.models.BertModel from a
    transformers.BertModel."""
    from ..models import BertConfig
    hc = hf_model.config
    if hc.hidden_act != "gelu":
        raise ValueError(
            f"unsupported source activation {hc.hidden_act!r}: the "
            f"converter maps HF's default 'gelu' (exact erf); other "
            f"activations would silently diverge")
    cfg = BertConfig(vocab_size=hc.vocab_size,
                     hidden_size=hc.hidden_size,
                     num_hidden_layers=hc.num_hidden_layers,
                     num_attention_heads=hc.num_attention_heads,
                     intermediate_size=hc.intermediate_size,
                     max_position_embeddings=hc.max_position_embeddings,
                     type_vocab_size=hc.type_vocab_size,
                     hidden_dropout_prob=hc.hidden_dropout_prob,
                     attention_probs_dropout_prob=(
                         hc.attention_probs_dropout_prob),
                     layer_norm_eps=hc.layer_norm_eps,
                     hidden_act="gelu_exact")
    sd = hf_model.state_dict()
    layers = {}
    for i in range(hc.num_hidden_layers):
        b = f"encoder.layer.{i}"
        q = _lin(sd, f"{b}.attention.self.query")
        k = _lin(sd, f"{b}.attention.self.key")
        v = _lin(sd, f"{b}.attention.self.value")
        layers[str(i)] = {
            "attention": {
                # fused qkv: rows [q; k; v] — matches the (B,T,3,H,D)
                # reshape order of BertSelfAttention
                "qkv": {"weight": np.concatenate(
                            [q["weight"], k["weight"], v["weight"]], 0),
                        "bias": np.concatenate(
                            [q["bias"], k["bias"], v["bias"]], 0)},
                "out": _lin(sd, f"{b}.attention.output.dense"),
            },
            "attention_ln": _ln(sd, f"{b}.attention.output.LayerNorm"),
            "intermediate": _lin(sd, f"{b}.intermediate.dense"),
            "output": _lin(sd, f"{b}.output.dense"),
            "output_ln": _ln(sd, f"{b}.output.LayerNorm"),
        }
    params = {
        "word_embeddings": {
            "weight": _t(sd["embeddings.word_embeddings.weight"])},
        "position_embeddings": {
            "weight": _t(sd["embeddings.position_embeddings.weight"])},
        "token_type_embeddings": {
            "weight": _t(sd["embeddings.token_type_embeddings.weight"])},
        "embeddings_ln": _ln(sd, "embeddings.LayerNorm"),
        "layer": layers,
        "pooler": _lin(sd, "pooler.dense"),
    }
    return cfg, _to_jnp(params)


def gpt_from_hf(hf_model) -> Tuple[Any, Any]:
    """(GPTConfig, params) for apex_tpu.models.GPT from a
    transformers.GPT2Model.  GPT-2's Conv1D stores (in, out); Linear
    wants (out, in) — transposed here."""
    from ..models import GPTConfig
    hc = hf_model.config
    if hc.activation_function != "gelu_new":
        raise ValueError(
            f"unsupported source activation "
            f"{hc.activation_function!r}: the converter maps GPT-2's "
            f"default 'gelu_new' (tanh)")
    if not (hc.resid_pdrop == hc.attn_pdrop == hc.embd_pdrop):
        raise ValueError(
            f"GPTConfig has one dropout rate; the source has "
            f"resid={hc.resid_pdrop} attn={hc.attn_pdrop} "
            f"embd={hc.embd_pdrop} — make them equal (or zero for "
            f"inference) before converting")
    cfg = GPTConfig(vocab_size=hc.vocab_size,
                    block_size=hc.n_positions, n_layer=hc.n_layer,
                    n_head=hc.n_head, n_embd=hc.n_embd,
                    dropout=hc.resid_pdrop,
                    layer_norm_eps=hc.layer_norm_epsilon)
    sd = hf_model.state_dict()

    def conv1d(prefix):
        return {"weight": _t(sd[f"{prefix}.weight"]).T,
                "bias": _t(sd[f"{prefix}.bias"])}

    h = {}
    for i in range(hc.n_layer):
        b = f"h.{i}"
        h[str(i)] = {
            "ln_1": _ln(sd, f"{b}.ln_1"),
            "attn": {"qkv": conv1d(f"{b}.attn.c_attn"),
                     "out": conv1d(f"{b}.attn.c_proj")},
            "ln_2": _ln(sd, f"{b}.ln_2"),
            "fc": conv1d(f"{b}.mlp.c_fc"),
            "proj": conv1d(f"{b}.mlp.c_proj"),
        }
    params = {
        "wte": {"weight": _t(sd["wte.weight"])},
        "wpe": {"weight": _t(sd["wpe.weight"])},
        "h": h,
        "ln_f": _ln(sd, "ln_f"),
    }
    return cfg, _to_jnp(params)


def _to_jnp(tree):
    import jax.numpy as jnp
    import jax
    return jax.tree_util.tree_map(lambda a: jnp.asarray(a, jnp.float32),
                                  tree)


def resnet_from_hf(hf_model):
    """(model, params, state) for apex_tpu from a transformers
    ResNetModel / ResNetForImageClassification.

    HF's default geometry (``downsample_in_bottleneck=False``, stride in
    the bottleneck's 3x3) matches this repo's torchvision-v1.5-shaped
    ResNet (models/resnet.py), so the mapping is pure renaming:
    embedder -> conv1/bn1, encoder.stages.{s}.layers.{l} ->
    layer{s+1}.{l}, shortcut -> downsample, classifier -> fc.  BN
    running stats land in the separate state tree (dotted keys, the
    checkpoint convention).  Output parity vs the HF torch forward is
    pinned in tests/test_hf_interop.py."""
    from ..models import ResNet, BasicBlock, Bottleneck

    hc = hf_model.config
    if getattr(hc, "downsample_in_first_stage", False):
        raise ValueError("downsample_in_first_stage=True has no "
                         "equivalent in the torchvision-shaped ResNet")
    if getattr(hc, "downsample_in_bottleneck", False):
        raise ValueError("downsample_in_bottleneck=True (v1.0 geometry) "
                         "is not supported; this ResNet strides in the "
                         "3x3 (v1.5, HF default)")
    if hc.layer_type == "bottleneck":
        block, exp = Bottleneck, 4
    elif hc.layer_type == "basic":
        block, exp = BasicBlock, 1
    else:
        raise ValueError(f"unknown layer_type {hc.layer_type!r}")
    if hc.embedding_size != 64 or hc.hidden_act != "relu":
        raise ValueError("only the standard embedding_size=64 / relu "
                         "geometry maps onto models.ResNet")
    expected = [64 * exp * (2 ** i) for i in range(len(hc.depths))]
    if list(hc.hidden_sizes) != expected or len(hc.depths) != 4:
        raise ValueError(f"hidden_sizes {hc.hidden_sizes} do not match "
                         f"the standard progression {expected}")

    sd = hf_model.state_dict()
    n_classes = getattr(hc, "num_labels", None) or 1000
    model = ResNet(block, list(hc.depths), num_classes=n_classes)

    def bn_params(prefix):
        return _lin(sd, prefix)

    def bn_state(prefix):
        return {"running_mean": _t(sd[f"{prefix}.running_mean"]),
                "running_var": _t(sd[f"{prefix}.running_var"]),
                "num_batches_tracked": _t(
                    sd[f"{prefix}.num_batches_tracked"])}

    # ForImageClassification nests the backbone under "resnet."
    if "embedder.embedder.convolution.weight" not in sd:
        sd = {(k[len("resnet."):] if k.startswith("resnet.") else k): v
              for k, v in sd.items()}
    params = {
        "conv1": {"weight": _t(
            sd["embedder.embedder.convolution.weight"])},
        "bn1": bn_params("embedder.embedder.normalization"),
    }
    state = {"bn1": bn_state("embedder.embedder.normalization")}

    nconvs = 3 if block is Bottleneck else 2
    for s, depth in enumerate(hc.depths):
        stage = {}
        for l in range(depth):
            hfp = f"encoder.stages.{s}.layers.{l}"
            blk = {}
            for j in range(nconvs):
                blk[f"conv{j+1}"] = {"weight": _t(
                    sd[f"{hfp}.layer.{j}.convolution.weight"])}
                blk[f"bn{j+1}"] = bn_params(f"{hfp}.layer.{j}.normalization")
                state[f"layer{s+1}.{l}.bn{j+1}"] = bn_state(
                    f"{hfp}.layer.{j}.normalization")
            if f"{hfp}.shortcut.convolution.weight" in sd:
                blk["downsample"] = {
                    "0": {"weight": _t(
                        sd[f"{hfp}.shortcut.convolution.weight"])},
                    "1": bn_params(f"{hfp}.shortcut.normalization")}
                state[f"layer{s+1}.{l}.downsample.1"] = bn_state(
                    f"{hfp}.shortcut.normalization")
            stage[str(l)] = blk
        params[f"layer{s+1}"] = stage
    if "classifier.1.weight" in sd:
        params["fc"] = {"weight": _t(sd["classifier.1.weight"]),
                        "bias": _t(sd["classifier.1.bias"])}
    else:  # base model: head stays at init (caller replaces or ignores)
        import numpy as _np
        D = expected[-1]
        params["fc"] = {"weight": _np.zeros((n_classes, D), _np.float32),
                        "bias": _np.zeros((n_classes,), _np.float32)}
    # state keeps integer leaves integer (num_batches_tracked is a
    # counter the BN train path increments; a float32 version would
    # diverge from init-produced state trees in dtype)
    import jax
    import jax.numpy as jnp

    def leaf(a):
        a = np.asarray(a)
        return jnp.asarray(a) if np.issubdtype(a.dtype, np.integer) \
            else jnp.asarray(a, jnp.float32)

    return (model, _to_jnp(params),
            jax.tree_util.tree_map(leaf, state))


def llama_from_hf(hf_model):
    """(LlamaConfig, params) for apex_tpu.models.Llama from a
    transformers LlamaModel / LlamaForCausalLM.  Same-layout renaming
    (separate q/k/v stay separate; RoPE is positional, no weights);
    greedy-generation parity is pinned in tests/test_llama.py."""
    from ..models import LlamaConfig

    hc = hf_model.config
    if getattr(hc, "hidden_act", "silu") != "silu":
        raise ValueError(f"unsupported activation {hc.hidden_act!r}")
    if getattr(hc, "attention_bias", False):
        raise ValueError("attention_bias=True is not mapped")
    if getattr(hc, "mlp_bias", False):
        raise ValueError("mlp_bias=True is not mapped (gate/up/down "
                         "biases would be silently dropped)")
    if getattr(hc, "rope_scaling", None):
        raise ValueError(
            f"rope_scaling={hc.rope_scaling!r} is not implemented "
            f"(apex_tpu's RoPE uses unscaled theta frequencies; a "
            f"Llama-3.1-style scaled checkpoint would convert cleanly "
            f"but generate silently wrong logits)")
    cfg = LlamaConfig(
        vocab_size=hc.vocab_size, hidden_size=hc.hidden_size,
        intermediate_size=hc.intermediate_size,
        num_hidden_layers=hc.num_hidden_layers,
        num_attention_heads=hc.num_attention_heads,
        num_key_value_heads=hc.num_key_value_heads,
        max_position_embeddings=hc.max_position_embeddings,
        rms_norm_eps=hc.rms_norm_eps, rope_theta=hc.rope_theta,
        tie_word_embeddings=hc.tie_word_embeddings)
    sd = hf_model.state_dict()
    if "model.embed_tokens.weight" in sd:       # ForCausalLM nesting
        base = "model."
    else:
        base = ""

    def w(name):
        return {"weight": _t(sd[f"{name}.weight"])}

    layers = {}
    for i in range(hc.num_hidden_layers):
        b = f"{base}layers.{i}"
        layers[str(i)] = {
            "input_layernorm": w(f"{b}.input_layernorm"),
            "self_attn": {k: w(f"{b}.self_attn.{k}")
                          for k in ("q_proj", "k_proj", "v_proj",
                                    "o_proj")},
            "post_attention_layernorm": w(
                f"{b}.post_attention_layernorm"),
            "mlp": {k: w(f"{b}.mlp.{k}")
                    for k in ("gate_proj", "up_proj", "down_proj")},
        }
    params = {
        "embed_tokens": w(f"{base}embed_tokens"),
        "layers": layers,
        "norm": w(f"{base}norm"),
    }
    if not hc.tie_word_embeddings:
        if "lm_head.weight" in sd:
            params["lm_head"] = {"weight": _t(sd["lm_head.weight"])}
        else:   # bare LlamaModel: head stays at init
            import numpy as _np
            params["lm_head"] = {"weight": _np.zeros(
                (hc.vocab_size, hc.hidden_size), _np.float32)}
    return cfg, _to_jnp(params)


def mixtral_from_hf(hf_model):
    """(MixtralConfig, params) for apex_tpu.models.Mixtral from a
    transformers MixtralModel / MixtralForCausalLM.

    The attention/norm/embedding mapping is Llama's; each expert's
    ``w1/w3/w2`` (gate/up/down, stored out-features-major) transposes
    into the stacked ``w_gate/w_in/w_out`` (E, d, h)/(E, h, d) banks,
    and the router ``gate.weight`` (E, d) transposes to (d, E).

    ``capacity_factor`` is set to ``num_local_experts`` so routing is
    dropless — HF Mixtral has no capacity limit, and exact logits
    parity needs every token to reach both its experts.  Lower it for
    capacity-bounded training throughput.
    """
    import numpy as _np
    from ..models import MixtralConfig

    hc = hf_model.config
    if getattr(hc, "hidden_act", "silu") != "silu":
        raise ValueError(f"unsupported activation {hc.hidden_act!r}")
    if getattr(hc, "attention_bias", False):
        raise ValueError("attention_bias=True is not mapped")
    cfg = MixtralConfig(
        vocab_size=hc.vocab_size, hidden_size=hc.hidden_size,
        intermediate_size=hc.intermediate_size,
        num_hidden_layers=hc.num_hidden_layers,
        num_attention_heads=hc.num_attention_heads,
        num_key_value_heads=hc.num_key_value_heads,
        max_position_embeddings=hc.max_position_embeddings,
        rms_norm_eps=hc.rms_norm_eps, rope_theta=hc.rope_theta,
        tie_word_embeddings=hc.tie_word_embeddings,
        num_local_experts=hc.num_local_experts,
        num_experts_per_tok=hc.num_experts_per_tok,
        router_aux_loss_coef=hc.router_aux_loss_coef,
        capacity_factor=float(hc.num_local_experts))
    sd = hf_model.state_dict()
    base = "model." if "model.embed_tokens.weight" in sd else ""

    def w(name):
        return {"weight": _t(sd[f"{name}.weight"])}

    def stack_T(names):
        return _np.stack([_np.asarray(_t(sd[n])).T for n in names])

    layers = {}
    for i in range(hc.num_hidden_layers):
        b = f"{base}layers.{i}"
        moe = f"{b}.block_sparse_moe"
        E = hc.num_local_experts
        layers[str(i)] = {
            "input_layernorm": w(f"{b}.input_layernorm"),
            "self_attn": {k: w(f"{b}.self_attn.{k}")
                          for k in ("q_proj", "k_proj", "v_proj",
                                    "o_proj")},
            "post_attention_layernorm": w(
                f"{b}.post_attention_layernorm"),
            "mlp": {
                "router": _np.asarray(
                    _t(sd[f"{moe}.gate.weight"])).T,      # (d, E)
                "w_gate": stack_T(
                    [f"{moe}.experts.{e}.w1.weight" for e in range(E)]),
                "w_in": stack_T(
                    [f"{moe}.experts.{e}.w3.weight" for e in range(E)]),
                "w_out": stack_T(
                    [f"{moe}.experts.{e}.w2.weight" for e in range(E)]),
            },
        }
    params = {
        "embed_tokens": w(f"{base}embed_tokens"),
        "layers": layers,
        "norm": w(f"{base}norm"),
    }
    if not hc.tie_word_embeddings:
        if "lm_head.weight" in sd:
            params["lm_head"] = {"weight": _t(sd["lm_head.weight"])}
        else:
            params["lm_head"] = {"weight": _np.zeros(
                (hc.vocab_size, hc.hidden_size), _np.float32)}
    return cfg, _to_jnp(params)


def mistral_from_hf(hf_model):
    """(LlamaConfig, params) for apex_tpu.models.Llama from a
    transformers MistralModel / MistralForCausalLM.

    Mistral is the Llama architecture with sliding-window attention;
    the state_dict layout is identical, so this reuses the Llama key
    mapping and sets ``LlamaConfig(sliding_window=...)`` (None for
    full-window v0.2+ checkpoints).  The KV cache stays full-length —
    HF's rolling buffer is a memory optimization with the same
    semantics."""
    from ..models import LlamaConfig

    hc = hf_model.config
    if getattr(hc, "hidden_act", "silu") != "silu":
        raise ValueError(f"unsupported activation {hc.hidden_act!r}")
    cfg = LlamaConfig(
        vocab_size=hc.vocab_size, hidden_size=hc.hidden_size,
        intermediate_size=hc.intermediate_size,
        num_hidden_layers=hc.num_hidden_layers,
        num_attention_heads=hc.num_attention_heads,
        num_key_value_heads=hc.num_key_value_heads,
        max_position_embeddings=hc.max_position_embeddings,
        rms_norm_eps=hc.rms_norm_eps, rope_theta=hc.rope_theta,
        tie_word_embeddings=hc.tie_word_embeddings,
        sliding_window=getattr(hc, "sliding_window", None))
    # layer/key layout is Llama's: borrow its mapping wholesale
    _, params = llama_from_hf(_LlamaShim(hf_model, hc))
    return cfg, params


class _LlamaShim:
    """Adapter presenting a Mistral model to llama_from_hf (same
    state_dict keys; strips the Mistral-only config fields the Llama
    validation would not recognize)."""

    def __init__(self, model, cfg):
        self._model = model
        self.config = cfg

    def state_dict(self):
        return self._model.state_dict()


def qwen2_from_hf(hf_model):
    """(LlamaConfig, params) for apex_tpu.models.Llama from a
    transformers Qwen2Model / Qwen2ForCausalLM.

    Qwen2 is the Llama architecture with biases on the Q/K/V
    projections (o_proj and the MLP stay bias-free) and an optional
    sliding window — both expressed as LlamaConfig options
    (``attention_bias=True``, ``sliding_window=...``)."""
    import numpy as _np
    from ..models import LlamaConfig

    hc = hf_model.config
    if getattr(hc, "hidden_act", "silu") != "silu":
        raise ValueError(f"unsupported activation {hc.hidden_act!r}")
    window = None
    if getattr(hc, "use_sliding_window", False):
        # HF applies SWA only to layers >= max_window_layers
        # (config.layer_types); apex_tpu's sliding_window is global —
        # map only uniform configurations, raise on mixed ones rather
        # than silently banding full-attention layers
        lt = getattr(hc, "layer_types", None) or []
        swa = [t == "sliding_attention" for t in lt]
        if swa and all(swa):
            window = hc.sliding_window
        elif any(swa):
            raise ValueError(
                "per-layer sliding window (max_window_layers="
                f"{hc.max_window_layers} < num_hidden_layers="
                f"{hc.num_hidden_layers}) is not mapped; apex_tpu's "
                "sliding_window applies to every layer")
    cfg = LlamaConfig(
        vocab_size=hc.vocab_size, hidden_size=hc.hidden_size,
        intermediate_size=hc.intermediate_size,
        num_hidden_layers=hc.num_hidden_layers,
        num_attention_heads=hc.num_attention_heads,
        num_key_value_heads=hc.num_key_value_heads,
        max_position_embeddings=hc.max_position_embeddings,
        rms_norm_eps=hc.rms_norm_eps, rope_theta=hc.rope_theta,
        tie_word_embeddings=hc.tie_word_embeddings,
        attention_bias=True, sliding_window=window)
    sd = hf_model.state_dict()
    base = "model." if "model.embed_tokens.weight" in sd else ""

    def w(name, bias=False):
        out = {"weight": _t(sd[f"{name}.weight"])}
        if bias:
            out["bias"] = _t(sd[f"{name}.bias"])
        return out

    layers = {}
    for i in range(hc.num_hidden_layers):
        b = f"{base}layers.{i}"
        layers[str(i)] = {
            "input_layernorm": w(f"{b}.input_layernorm"),
            "self_attn": {
                "q_proj": w(f"{b}.self_attn.q_proj", bias=True),
                "k_proj": w(f"{b}.self_attn.k_proj", bias=True),
                "v_proj": w(f"{b}.self_attn.v_proj", bias=True),
                "o_proj": w(f"{b}.self_attn.o_proj"),
            },
            "post_attention_layernorm": w(
                f"{b}.post_attention_layernorm"),
            "mlp": {k: w(f"{b}.mlp.{k}")
                    for k in ("gate_proj", "up_proj", "down_proj")},
        }
    params = {
        "embed_tokens": w(f"{base}embed_tokens"),
        "layers": layers,
        "norm": w(f"{base}norm"),
    }
    if not hc.tie_word_embeddings:
        if "lm_head.weight" in sd:
            params["lm_head"] = {"weight": _t(sd["lm_head.weight"])}
        else:
            params["lm_head"] = {"weight": _np.zeros(
                (hc.vocab_size, hc.hidden_size), _np.float32)}
    return cfg, _to_jnp(params)


def gemma_from_hf(hf_model):
    """(LlamaConfig, params) for apex_tpu.models.Llama from a
    transformers GemmaModel / GemmaForCausalLM.

    Gemma on the Llama backbone = four config knobs: decoupled
    ``head_dim`` (gemma-7b: 16 heads x 256 over hidden 3072), GeGLU
    (``mlp_act="gelu_tanh"`` — HF's gelu_pytorch_tanh), ``(1 + w)``
    RMSNorm scaling (checkpoints store w), and the sqrt(hidden)
    embedding scale.  The state_dict key layout is Llama's."""
    import numpy as _np
    from ..models import LlamaConfig

    hc = hf_model.config
    act = getattr(hc, "hidden_act", None) \
        or getattr(hc, "hidden_activation", None)
    if act not in ("gelu", "gelu_pytorch_tanh"):
        raise ValueError(f"unsupported activation {act!r}")
    cfg = LlamaConfig(
        vocab_size=hc.vocab_size, hidden_size=hc.hidden_size,
        intermediate_size=hc.intermediate_size,
        num_hidden_layers=hc.num_hidden_layers,
        num_attention_heads=hc.num_attention_heads,
        num_key_value_heads=hc.num_key_value_heads,
        max_position_embeddings=hc.max_position_embeddings,
        rms_norm_eps=hc.rms_norm_eps, rope_theta=hc.rope_theta,
        tie_word_embeddings=True, head_dim=hc.head_dim,
        mlp_act="gelu_tanh", rms_unit_offset=True, embed_scale=True)
    sd = hf_model.state_dict()
    base = "model." if "model.embed_tokens.weight" in sd else ""

    def w(name):
        return {"weight": _t(sd[f"{name}.weight"])}

    layers = {}
    for i in range(hc.num_hidden_layers):
        b = f"{base}layers.{i}"
        layers[str(i)] = {
            "input_layernorm": w(f"{b}.input_layernorm"),
            "self_attn": {k: w(f"{b}.self_attn.{k}")
                          for k in ("q_proj", "k_proj", "v_proj",
                                    "o_proj")},
            "post_attention_layernorm": w(
                f"{b}.post_attention_layernorm"),
            "mlp": {k: w(f"{b}.mlp.{k}")
                    for k in ("gate_proj", "up_proj", "down_proj")},
        }
    params = {
        "embed_tokens": w(f"{base}embed_tokens"),
        "layers": layers,
        "norm": w(f"{base}norm"),
    }
    return cfg, _to_jnp(params)


def gpt_neox_from_hf(hf_model):
    """(LlamaConfig, params) for apex_tpu.models.Llama from a
    transformers GPTNeoXModel / GPTNeoXForCausalLM (Pythia et al.).

    GPT-NeoX on the Llama backbone = LayerNorm blocks
    (``norm_type="layernorm"``), parallel residual, partial rotary
    (``rotary_pct``), biased fused QKV + output dense
    (``attention_bias``/``attention_out_bias``), and the biased
    2-layer GeLU MLP (``mlp_type="gelu_mlp"``).  The fused
    ``query_key_value`` weight interleaves q/k/v PER HEAD — rows view
    as (H, 3, D, hidden) and de-interleave into separate projections.
    """
    import numpy as _np
    from ..models import LlamaConfig

    hc = hf_model.config
    if getattr(hc, "hidden_act", "gelu") != "gelu":
        raise ValueError(f"unsupported activation {hc.hidden_act!r}")
    if not getattr(hc, "use_parallel_residual", True):
        raise ValueError("use_parallel_residual=False NeoX variants "
                         "are not mapped")
    cfg = LlamaConfig(
        vocab_size=hc.vocab_size, hidden_size=hc.hidden_size,
        intermediate_size=hc.intermediate_size,
        num_hidden_layers=hc.num_hidden_layers,
        num_attention_heads=hc.num_attention_heads,
        max_position_embeddings=hc.max_position_embeddings,
        rms_norm_eps=hc.layer_norm_eps,
        rope_theta=getattr(hc, "rotary_emb_base", 10000.0),
        tie_word_embeddings=hc.tie_word_embeddings,
        norm_type="layernorm", parallel_residual=True,
        rotary_pct=getattr(hc, "rotary_pct", 1.0),
        mlp_type="gelu_mlp", attention_bias=True,
        attention_out_bias=True)
    sd = hf_model.state_dict()
    base = ("gpt_neox."
            if "gpt_neox.embed_in.weight" in sd else "")
    H = hc.num_attention_heads
    D = hc.hidden_size // H

    def wb(name):
        return {"weight": _t(sd[f"{name}.weight"]),
                "bias": _t(sd[f"{name}.bias"])}

    def split_qkv(prefix):
        w = _np.asarray(_t(sd[f"{prefix}.weight"]))   # (3E, E)
        b = _np.asarray(_t(sd[f"{prefix}.bias"]))     # (3E,)
        wv = w.reshape(H, 3, D, hc.hidden_size)
        bv = b.reshape(H, 3, D)
        out = {}
        for j, k in enumerate(("q_proj", "k_proj", "v_proj")):
            out[k] = {"weight": wv[:, j].reshape(H * D, hc.hidden_size),
                      "bias": bv[:, j].reshape(H * D)}
        return out

    layers = {}
    for i in range(hc.num_hidden_layers):
        b = f"{base}layers.{i}"
        at = split_qkv(f"{b}.attention.query_key_value")
        at["o_proj"] = wb(f"{b}.attention.dense")
        layers[str(i)] = {
            "input_layernorm": wb(f"{b}.input_layernorm"),
            "self_attn": at,
            "post_attention_layernorm": wb(
                f"{b}.post_attention_layernorm"),
            "mlp": {"dense_h_to_4h": wb(f"{b}.mlp.dense_h_to_4h"),
                    "dense_4h_to_h": wb(f"{b}.mlp.dense_4h_to_h")},
        }
    params = {
        "embed_tokens": {"weight": _t(sd[f"{base}embed_in.weight"])},
        "layers": layers,
        "norm": wb(f"{base}final_layer_norm"),
    }
    if not hc.tie_word_embeddings:
        if "embed_out.weight" in sd:
            params["lm_head"] = {"weight": _t(sd["embed_out.weight"])}
        else:
            params["lm_head"] = {"weight": _np.zeros(
                (hc.vocab_size, hc.hidden_size), _np.float32)}
    return cfg, _to_jnp(params)


def llama_to_hf(cfg, params):
    """Inverse of ``llama_from_hf``: a ``transformers``-layout
    state_dict (numpy arrays, ``model.``-prefixed + ``lm_head``) from
    an apex_tpu Llama param tree — so checkpoints trained here load
    straight into ``LlamaForCausalLM.load_state_dict`` (round-trip
    pinned in tests/test_hf_export.py).  Plain-Llama trees only (no
    TP rename, no NeoX/Gemma knobs — those checkpoints belong to their
    own HF classes)."""
    import numpy as _np

    def t(x):
        import torch
        # copy=True: jnp arrays expose read-only buffers and torch
        # warns on (and could break with) non-writable views
        return torch.from_numpy(
            _np.array(x, dtype=_np.float32, copy=True))

    sd = {"model.embed_tokens.weight": t(params["embed_tokens"]["weight"]),
          "model.norm.weight": t(params["norm"]["weight"])}
    for i in range(cfg.num_hidden_layers):
        blk = params["layers"][str(i)]
        b = f"model.layers.{i}"
        sd[f"{b}.input_layernorm.weight"] = t(
            blk["input_layernorm"]["weight"])
        sd[f"{b}.post_attention_layernorm.weight"] = t(
            blk["post_attention_layernorm"]["weight"])
        for k in ("q_proj", "k_proj", "v_proj", "o_proj"):
            sd[f"{b}.self_attn.{k}.weight"] = t(
                blk["self_attn"][k]["weight"])
            if "bias" in blk["self_attn"][k]:
                sd[f"{b}.self_attn.{k}.bias"] = t(
                    blk["self_attn"][k]["bias"])
        for k in ("gate_proj", "up_proj", "down_proj"):
            sd[f"{b}.mlp.{k}.weight"] = t(blk["mlp"][k]["weight"])
    if not cfg.tie_word_embeddings and "lm_head" in params:
        sd["lm_head.weight"] = t(params["lm_head"]["weight"])
    return sd


def t5_from_hf(hf_model):
    """(T5Config, params) for apex_tpu.models.T5 from a transformers
    T5Model / T5ForConditionalGeneration (t5 relu or v1.1 gated-gelu).
    Same-layout renaming; the layer-0 relative-attention-bias tables
    map per stack."""
    import numpy as _np
    from ..models import T5Config

    hc = hf_model.config
    ff = hc.feed_forward_proj
    if ff not in ("relu", "gated-gelu"):
        raise ValueError(f"unsupported feed_forward_proj {ff!r}")
    cfg = T5Config(
        vocab_size=hc.vocab_size, d_model=hc.d_model, d_kv=hc.d_kv,
        d_ff=hc.d_ff, num_layers=hc.num_layers,
        num_decoder_layers=hc.num_decoder_layers,
        num_heads=hc.num_heads,
        relative_attention_num_buckets=
        hc.relative_attention_num_buckets,
        relative_attention_max_distance=
        hc.relative_attention_max_distance,
        layer_norm_epsilon=hc.layer_norm_epsilon,
        dropout_rate=hc.dropout_rate, feed_forward_proj=ff,
        tie_word_embeddings=hc.tie_word_embeddings,
        decoder_start_token_id=hc.decoder_start_token_id or 0)
    sd = hf_model.state_dict()

    def w(name):
        return {"weight": _t(sd[f"{name}.weight"])}

    def attn(prefix, with_bias_table):
        out = {k: w(f"{prefix}.{k}") for k in ("q", "k", "v", "o")}
        if with_bias_table:
            out["relative_attention_bias"] = w(
                f"{prefix}.relative_attention_bias")
        return out

    def ff_params(prefix):
        if ff == "gated-gelu":
            return {"wi_0": w(f"{prefix}.wi_0"),
                    "wi_1": w(f"{prefix}.wi_1"),
                    "wo": w(f"{prefix}.wo")}
        return {"wi": w(f"{prefix}.wi"), "wo": w(f"{prefix}.wo")}

    enc = {}
    for i in range(hc.num_layers):
        b = f"encoder.block.{i}"
        enc[str(i)] = {
            "ln_attn": w(f"{b}.layer.0.layer_norm"),
            "attn": attn(f"{b}.layer.0.SelfAttention", i == 0),
            "ln_ff": w(f"{b}.layer.1.layer_norm"),
            "ff": ff_params(f"{b}.layer.1.DenseReluDense"),
        }
    dec = {}
    for i in range(hc.num_decoder_layers):
        b = f"decoder.block.{i}"
        dec[str(i)] = {
            "ln_self": w(f"{b}.layer.0.layer_norm"),
            "self_attn": attn(f"{b}.layer.0.SelfAttention", i == 0),
            "ln_cross": w(f"{b}.layer.1.layer_norm"),
            "cross_attn": attn(f"{b}.layer.1.EncDecAttention", False),
            "ln_ff": w(f"{b}.layer.2.layer_norm"),
            "ff": ff_params(f"{b}.layer.2.DenseReluDense"),
        }
    params = {
        "shared": w("shared"),
        "enc_blocks": enc,
        "enc_norm": w("encoder.final_layer_norm"),
        "dec_blocks": dec,
        "dec_norm": w("decoder.final_layer_norm"),
    }
    if not hc.tie_word_embeddings:
        if "lm_head.weight" in sd:
            params["lm_head"] = {"weight": _t(sd["lm_head.weight"])}
        else:
            params["lm_head"] = {"weight": _np.zeros(
                (hc.vocab_size, hc.d_model), _np.float32)}
    return cfg, _to_jnp(params)
