"""HuggingFace checkpoint interop: torch state_dicts -> apex_tpu params.

A user switching from the reference stack brings torch-ecosystem
weights; these converters map ``transformers`` BERT / GPT-2 state_dicts
onto apex_tpu's param trees, and the tests prove output parity against
the HF torch implementations themselves (random-init models, so no
network access is needed — the proof is architectural, and a real
pretrained checkpoint converts the same way).

    hf = transformers.BertModel(hf_cfg)          # or .from_pretrained
    cfg, params = hf_interop.bert_from_hf(hf)
    model = apex_tpu.models.BertModel(cfg)
    seq, pooled = model(params, ids, token_type_ids=tt)

Conventions handled: HF's separate q/k/v projections fuse into the
(3E, E) qkv weight (head-major row order matches), GPT-2's Conv1D
weights transpose into Linear layout, and BERT's exact-erf gelu is
selected via ``hidden_act="gelu_exact"``.
"""

from __future__ import annotations

from typing import Any, Tuple

import numpy as np


def _t(x):
    return np.asarray(x.detach().cpu().numpy())


def _lin(sd, prefix):
    return {"weight": _t(sd[f"{prefix}.weight"]),
            "bias": _t(sd[f"{prefix}.bias"])}


_ln = _lin      # LayerNorm params share the weight/bias naming


def bert_from_hf(hf_model) -> Tuple[Any, Any]:
    """(BertConfig, params) for apex_tpu.models.BertModel from a
    transformers.BertModel."""
    from ..models import BertConfig
    hc = hf_model.config
    if hc.hidden_act != "gelu":
        raise ValueError(
            f"unsupported source activation {hc.hidden_act!r}: the "
            f"converter maps HF's default 'gelu' (exact erf); other "
            f"activations would silently diverge")
    cfg = BertConfig(vocab_size=hc.vocab_size,
                     hidden_size=hc.hidden_size,
                     num_hidden_layers=hc.num_hidden_layers,
                     num_attention_heads=hc.num_attention_heads,
                     intermediate_size=hc.intermediate_size,
                     max_position_embeddings=hc.max_position_embeddings,
                     type_vocab_size=hc.type_vocab_size,
                     hidden_dropout_prob=hc.hidden_dropout_prob,
                     attention_probs_dropout_prob=(
                         hc.attention_probs_dropout_prob),
                     layer_norm_eps=hc.layer_norm_eps,
                     hidden_act="gelu_exact")
    sd = hf_model.state_dict()
    layers = {}
    for i in range(hc.num_hidden_layers):
        b = f"encoder.layer.{i}"
        q = _lin(sd, f"{b}.attention.self.query")
        k = _lin(sd, f"{b}.attention.self.key")
        v = _lin(sd, f"{b}.attention.self.value")
        layers[str(i)] = {
            "attention": {
                # fused qkv: rows [q; k; v] — matches the (B,T,3,H,D)
                # reshape order of BertSelfAttention
                "qkv": {"weight": np.concatenate(
                            [q["weight"], k["weight"], v["weight"]], 0),
                        "bias": np.concatenate(
                            [q["bias"], k["bias"], v["bias"]], 0)},
                "out": _lin(sd, f"{b}.attention.output.dense"),
            },
            "attention_ln": _ln(sd, f"{b}.attention.output.LayerNorm"),
            "intermediate": _lin(sd, f"{b}.intermediate.dense"),
            "output": _lin(sd, f"{b}.output.dense"),
            "output_ln": _ln(sd, f"{b}.output.LayerNorm"),
        }
    params = {
        "word_embeddings": {
            "weight": _t(sd["embeddings.word_embeddings.weight"])},
        "position_embeddings": {
            "weight": _t(sd["embeddings.position_embeddings.weight"])},
        "token_type_embeddings": {
            "weight": _t(sd["embeddings.token_type_embeddings.weight"])},
        "embeddings_ln": _ln(sd, "embeddings.LayerNorm"),
        "layer": layers,
        "pooler": _lin(sd, "pooler.dense"),
    }
    return cfg, _to_jnp(params)


def gpt_from_hf(hf_model) -> Tuple[Any, Any]:
    """(GPTConfig, params) for apex_tpu.models.GPT from a
    transformers.GPT2Model.  GPT-2's Conv1D stores (in, out); Linear
    wants (out, in) — transposed here."""
    from ..models import GPTConfig
    hc = hf_model.config
    if hc.activation_function != "gelu_new":
        raise ValueError(
            f"unsupported source activation "
            f"{hc.activation_function!r}: the converter maps GPT-2's "
            f"default 'gelu_new' (tanh)")
    if not (hc.resid_pdrop == hc.attn_pdrop == hc.embd_pdrop):
        raise ValueError(
            f"GPTConfig has one dropout rate; the source has "
            f"resid={hc.resid_pdrop} attn={hc.attn_pdrop} "
            f"embd={hc.embd_pdrop} — make them equal (or zero for "
            f"inference) before converting")
    cfg = GPTConfig(vocab_size=hc.vocab_size,
                    block_size=hc.n_positions, n_layer=hc.n_layer,
                    n_head=hc.n_head, n_embd=hc.n_embd,
                    dropout=hc.resid_pdrop,
                    layer_norm_eps=hc.layer_norm_epsilon)
    sd = hf_model.state_dict()

    def conv1d(prefix):
        return {"weight": _t(sd[f"{prefix}.weight"]).T,
                "bias": _t(sd[f"{prefix}.bias"])}

    h = {}
    for i in range(hc.n_layer):
        b = f"h.{i}"
        h[str(i)] = {
            "ln_1": _ln(sd, f"{b}.ln_1"),
            "attn": {"qkv": conv1d(f"{b}.attn.c_attn"),
                     "out": conv1d(f"{b}.attn.c_proj")},
            "ln_2": _ln(sd, f"{b}.ln_2"),
            "fc": conv1d(f"{b}.mlp.c_fc"),
            "proj": conv1d(f"{b}.mlp.c_proj"),
        }
    params = {
        "wte": {"weight": _t(sd["wte.weight"])},
        "wpe": {"weight": _t(sd["wpe.weight"])},
        "h": h,
        "ln_f": _ln(sd, "ln_f"),
    }
    return cfg, _to_jnp(params)


def _to_jnp(tree):
    import jax.numpy as jnp
    import jax
    return jax.tree_util.tree_map(lambda a: jnp.asarray(a, jnp.float32),
                                  tree)
