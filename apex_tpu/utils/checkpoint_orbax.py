"""Sharded/async checkpointing via Orbax — the TPU-native alternative to
the npz path in :mod:`apex_tpu.utils.checkpoint`.

The npz checkpointer (utils/checkpoint.py) gathers every leaf to host —
correct and dependency-free, but on a pod that funnels the whole model
through one host and blocks the step loop.  Orbax writes each shard from
the process that owns it (TensorStore/OCDBT) and can do so
asynchronously, which is how large sharded TP/PP state is checkpointed
in practice.  This module is a thin adapter keeping the same call shape
as the npz API:

    from apex_tpu.utils import checkpoint_orbax as ckpt
    ckpt.save_checkpoint(dir, step, {"params": params, "opt": opt_state})
    state = ckpt.restore_checkpoint(dir, template)          # latest
    state = ckpt.restore_checkpoint(dir, template, step=7)

``template`` supplies structure/shape/dtype AND SHARDING: pass the live
state (or equivalently shaped abstract arrays with shardings) so every
restored leaf lands already-sharded on its devices — no host round trip.
Restore-time reshard is supported: a template with a different mesh
layout restores into that layout.

Falls back cleanly when orbax is unavailable (import guarded); callers
needing the guaranteed-present path use the npz module.

Telemetry (shared with the npz path via
:func:`~apex_tpu.utils.checkpoint.record_checkpoint_io`): every save /
restore lands in the process registry's
``checkpoint_save_seconds`` / ``checkpoint_restore_seconds``
histograms and the ``checkpoint_snapshot_bytes`` gauge, and every
**durable** save appends a ``checkpoint_saved`` flight-ring event —
for a sync save at return, for an async save at the join (``wait()``
or the next save), because only then has the write actually succeeded
and only then may the training-run supervisor's progress watermark
consume it.
"""

from __future__ import annotations

import json
import os
import re
import time
import zlib
from typing import Any, Optional

import jax
import numpy as np

from .checkpoint import (CheckpointCorrupt, record_checkpoint_io,
                         tree_bytes, tree_checksum)

__all__ = ["CheckpointCorrupt", "save_checkpoint", "restore_checkpoint",
           "latest_step", "available_steps", "load_data_state"]

_STEP_RE = re.compile(r"^step_(\d+)$")

# content-checksum sidecar inside each step dir (Orbax owns the tree
# layout, so the checksum rides alongside rather than inside): written
# only once the save is DURABLE (sync: at return; async: at the join).
# A torn background write leaves no sidecar — but so does a genuinely
# old (pre-checksum) snapshot, so every save ALSO drops a pending
# marker NEXT TO the step dir (Orbax's force=True clears the target
# dir itself) before the write starts and removes it at the join:
# marker-without-sidecar = a save that never joined = corruption;
# neither file = legacy = trusted like before.
_CHECKSUM_FILE = "_apex_checksum.json"
_PENDING_FMT = "_apex_pending_step_{step}.json"


def _keyed_leaves(tree: Any) -> dict:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {jax.tree_util.keystr(path): leaf for path, leaf in flat}


def _chain_data_state(crc: int, data_state: Optional[dict]) -> int:
    """Fold the data-state blob into the content crc (the npz path
    gets this for free by storing the blob as a checksummed leaf):
    a tampered or torn cursor fails verification like any leaf."""
    if data_state is None:
        return crc
    blob = json.dumps(data_state, sort_keys=True).encode()
    return zlib.crc32(blob, crc) & 0xFFFFFFFF


def _write_checksum(path: str, crc: int, nbytes: int, dtypes: dict,
                    data_state: Optional[dict] = None) -> None:
    side = os.path.join(path, _CHECKSUM_FILE)
    tmp = side + ".tmp"
    with open(tmp, "w") as f:
        # the per-leaf dtypes the crc was computed over: a restore
        # into a template with DIFFERENT dtypes casts the leaves
        # (supported by contract), and a checksum over the cast bytes
        # cannot match — the verifier uses this map to know when
        # content verification is possible at all.  data_state (the
        # optional pipeline cursor) rides in the sidecar and is
        # chained into the crc, so it shares the durability story:
        # written only at the join, verified on read.
        meta = {"crc32": int(crc), "tree_bytes": int(nbytes),
                "dtypes": dtypes}
        if data_state is not None:
            meta["data_state"] = data_state
            # a crc over the blob ALONE, so load_data_state can verify
            # the cursor without restoring (and re-checksumming) the
            # whole tree the chained crc32 above binds it to
            meta["data_state_crc32"] = _chain_data_state(0, data_state)
        json.dump(meta, f)
    os.replace(tmp, side)


def _mgr_dir(ckpt_dir: str) -> str:
    return os.path.abspath(ckpt_dir)


def _prune(ckpt_dir: str, keep: int) -> None:
    import shutil
    for s in available_steps(ckpt_dir)[:-keep]:
        shutil.rmtree(os.path.join(_mgr_dir(ckpt_dir), f"step_{s}"),
                      ignore_errors=True)


def save_checkpoint(ckpt_dir: str, step: int, tree: Any,
                    keep: Optional[int] = None,
                    async_save: bool = False,
                    data_state: Optional[dict] = None) -> str:
    """Write ``tree`` under ``ckpt_dir/step_N`` (sharded, per-process).

    ``async_save=True`` returns while the write completes in the
    background (call :func:`wait` or save again to join — a new save
    first joins any pending one, so write errors always surface).
    ``keep`` prunes to the most recent N steps after the save has
    actually SUCCEEDED (for an async save, at join time)."""
    import orbax.checkpoint as ocp
    if keep is not None and keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    wait()                        # join + surface any pending async save
    path = os.path.join(_mgr_dir(ckpt_dir), f"step_{int(step)}")
    t0 = time.perf_counter()
    nbytes = tree_bytes(tree)
    # content checksum of the tree being written (host gather — the
    # price of verifiable snapshots; restore recomputes it from what
    # it read back).  Computed BEFORE the background write starts so
    # it describes exactly the intended content.
    leaves = _keyed_leaves(tree)
    crc = _chain_data_state(tree_checksum(leaves), data_state)
    dtypes = {k: str(np.asarray(v).dtype) for k, v in leaves.items()}
    # pending marker BEFORE the write starts: a process dying mid-save
    # leaves marker-without-sidecar, which restore distinguishes from
    # a legacy (pre-checksum) snapshot and flags as corrupt
    os.makedirs(_mgr_dir(ckpt_dir), exist_ok=True)
    pending = os.path.join(_mgr_dir(ckpt_dir),
                           _PENDING_FMT.format(step=int(step)))
    with open(pending, "w") as f:
        json.dump({"step": int(step)}, f)
    ckptr = (ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
             if async_save
             else ocp.Checkpointer(ocp.StandardCheckpointHandler()))
    ckptr.save(path, tree, force=True)
    if not async_save:
        ckptr.close()
        _write_checksum(path, crc, nbytes, dtypes, data_state)
        os.unlink(pending)
        record_checkpoint_io("save", time.perf_counter() - t0,
                             step=int(step), nbytes=nbytes, path=path)
        if keep is not None:
            _prune(ckpt_dir, keep)
    else:
        global _pending
        # pruning AND the checkpoint_saved telemetry are deferred to
        # the join: a failed background write can't have already
        # deleted the older good checkpoints, and must not have
        # emitted a progress event for a snapshot that never landed.
        # The checksum sidecar is deferred the same way: only a
        # JOINED (durable) save gets one, so a torn background write
        # is visibly unverified.
        _pending = (ckptr, ckpt_dir, keep, int(step), path, nbytes,
                    crc, dtypes, data_state, t0)
    return path


_pending = None


def wait() -> None:
    """Join an in-flight async save (then apply its deferred pruning
    and emit its deferred ``checkpoint_saved`` telemetry — the save is
    only durable now)."""
    global _pending
    if _pending is not None:
        (ckptr, ckpt_dir, keep, step, path, nbytes, crc, dtypes,
         data_state, t0) = _pending
        _pending = None
        ckptr.wait_until_finished()
        ckptr.close()
        _write_checksum(path, crc, nbytes, dtypes, data_state)
        try:
            os.unlink(os.path.join(
                _mgr_dir(ckpt_dir), _PENDING_FMT.format(step=step)))
        except OSError:
            pass
        record_checkpoint_io("save", time.perf_counter() - t0,
                             step=step, nbytes=nbytes, path=path,
                             async_save=True)
        if keep is not None:
            _prune(ckpt_dir, keep)


def available_steps(ckpt_dir: str) -> list:
    d = _mgr_dir(ckpt_dir)
    if not os.path.isdir(d):
        return []
    out = []
    for name in os.listdir(d):
        m = _STEP_RE.match(name)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def latest_step(ckpt_dir: str) -> Optional[int]:
    steps = available_steps(ckpt_dir)
    return steps[-1] if steps else None


def restore_checkpoint(ckpt_dir: str, template: Any,
                       step: Optional[int] = None) -> Any:
    """Restore into ``template``'s structure, dtypes, AND shardings.

    Leaves come back as jax.Arrays sharded like the template's (live
    arrays or ShapeDtypeStructs with ``.sharding``); a different mesh
    layout in the template reshards on read."""
    import orbax.checkpoint as ocp
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {ckpt_dir}")
    path = os.path.join(_mgr_dir(ckpt_dir), f"step_{int(step)}")

    def to_abstract(leaf):
        if hasattr(leaf, "sharding"):
            return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                        sharding=leaf.sharding)
        return jax.ShapeDtypeStruct(jax.numpy.asarray(leaf).shape,
                                    jax.numpy.asarray(leaf).dtype)

    t0 = time.perf_counter()
    abstract = jax.tree_util.tree_map(to_abstract, template)
    try:
        with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
            restored = ckptr.restore(path, abstract)
    except (FileNotFoundError, ValueError, KeyError) as e:
        # a torn step dir (interrupted write, missing TensorStore
        # files) fails inside Orbax's own readers — surface it as the
        # corruption it is so the recovery controller's fallback loop
        # treats both backends the same way
        raise CheckpointCorrupt(f"{path}: unreadable snapshot ({e})")
    # content verification against the durability sidecar.  A pending
    # marker WITHOUT a sidecar means the save never joined (process
    # died mid-async-write): the step dir may be readable yet stale or
    # partial, and must not restore silently — this is what makes a
    # torn write distinguishable from a genuinely pre-checksum legacy
    # snapshot (neither file), which loads as-is.
    side = os.path.join(path, _CHECKSUM_FILE)
    pending = os.path.join(_mgr_dir(ckpt_dir),
                           _PENDING_FMT.format(step=int(step)))
    if not os.path.exists(side) and os.path.exists(pending):
        raise CheckpointCorrupt(
            f"{path}: save was never joined (pending marker present, "
            f"no durability sidecar) — torn async write")
    if os.path.exists(side):
        try:
            with open(side) as f:
                meta = json.load(f)
            want = meta["crc32"]
        except (OSError, ValueError, KeyError) as e:
            raise CheckpointCorrupt(f"{side}: unreadable checksum "
                                    f"sidecar ({e})")
        leaves = _keyed_leaves(restored)
        # the sidecar crc was computed over the SAVED dtypes; a
        # template with different dtypes casts the restore (supported
        # by contract), and bytes after a cast cannot match — only
        # verify when every leaf came back at its recorded dtype
        saved_dt = meta.get("dtypes")
        comparable = saved_dt is None or all(
            str(np.asarray(v).dtype) == saved_dt.get(k)
            for k, v in leaves.items())
        if comparable:
            got = _chain_data_state(tree_checksum(leaves),
                                    meta.get("data_state"))
            if int(want) != got:
                raise CheckpointCorrupt(
                    f"{path}: content checksum mismatch (sidecar "
                    f"{int(want):#010x}, recomputed {got:#010x})")
    record_checkpoint_io("restore", time.perf_counter() - t0,
                         step=int(step), nbytes=tree_bytes(restored),
                         path=path)
    return restored


def load_data_state(ckpt_dir: str,
                    step: Optional[int] = None) -> Optional[dict]:
    """Read the snapshot's data-pipeline cursor blob from the
    durability sidecar (written only at the join, crc-chained — same
    contract as the npz path's :func:`~.checkpoint.load_data_state`).
    ``None`` when the snapshot carries none."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {ckpt_dir}")
    path = os.path.join(_mgr_dir(ckpt_dir), f"step_{int(step)}")
    if not os.path.isdir(path):
        raise FileNotFoundError(path)
    side = os.path.join(path, _CHECKSUM_FILE)
    if not os.path.exists(side):
        return None
    try:
        with open(side) as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorrupt(f"{side}: unreadable checksum "
                                f"sidecar ({e})")
    ds = meta.get("data_state")
    if ds is not None:
        want = meta.get("data_state_crc32")
        got = _chain_data_state(0, ds)
        if want is not None and int(want) != got:
            raise CheckpointCorrupt(
                f"{side}: data_state checksum mismatch (stored "
                f"{int(want):#010x}, recomputed {got:#010x}) — torn "
                f"sidecar or tampered cursor; resuming it would "
                f"silently diverge the sample stream")
    return ds
