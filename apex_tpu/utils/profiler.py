"""Tracing / profiling utilities (NVTX-range parity for TPU).

Two annotation layers, matching what the reference's nvtx ranges gave it:

- **Trace-time** (``jax.named_scope``): names the HLO emitted while the
  scope is active, so XLA profiles, HLO dumps, and xprof op breakdowns
  attribute time to framework phases ("syncbn_fwd", "allreduce", ...).
- **Host-time** (``jax.profiler.TraceAnnotation``): a real wall-clock range
  on the host timeline for eager sections (data loading, checkpointing).

``range_push/range_pop`` mirror torch.cuda.nvtx.range_push/pop
(reference sync_batchnorm.py:69,87); ``start_profile/stop_profile`` mirror
the cudaProfilerStart/Stop window of examples/imagenet/main_amp.py:325-352
on top of ``jax.profiler.start_trace/stop_trace``.
"""

from __future__ import annotations

import contextlib
import functools
import itertools
import os
import threading
from typing import Optional

import jax

__all__ = ["range_push", "range_pop", "nvtx_range", "annotate",
           "start_profile", "stop_profile", "profile", "profiling_active",
           "current_capture_dir", "last_capture_dir", "AverageMeter"]

_tls = threading.local()


def _stack():
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


def range_push(name: str) -> int:
    """Open a named range (torch.cuda.nvtx.range_push parity).  Returns the
    new nesting depth.  Opens both a named_scope (HLO attribution when
    tracing) and a host profiler annotation (timeline range)."""
    scope = jax.named_scope(name)
    ann = jax.profiler.TraceAnnotation(name)
    scope.__enter__()
    ann.__enter__()
    _stack().append((scope, ann))
    return len(_stack())


def range_pop() -> int:
    """Close the innermost range (torch.cuda.nvtx.range_pop parity)."""
    stack = _stack()
    if not stack:
        raise RuntimeError("range_pop() without matching range_push()")
    scope, ann = stack.pop()
    ann.__exit__(None, None, None)
    scope.__exit__(None, None, None)
    return len(stack)


@contextlib.contextmanager
def nvtx_range(name: str):
    """Context-manager form; exception-safe (prefer over push/pop)."""
    range_push(name)
    try:
        yield
    finally:
        range_pop()


def annotate(name: Optional[str] = None):
    """Decorator: run the function under a named range."""
    def deco(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with nvtx_range(label):
                return fn(*args, **kwargs)
        return wrapped
    return deco


# Trace-window state: jax.profiler.start_trace is a process-wide
# singleton, so concurrent/nested windows must be refcounted under a
# lock — the bare `_trace_active` bool raced two threads into a double
# start_trace (RuntimeError) and a nested profile() used to stop the
# OUTER window on inner exit.
_trace_lock = threading.Lock()
_trace_depth = 0
# Every outermost window captures into a UNIQUE subdirectory of the
# requested logdir: start_trace names its session dir by wall-clock
# SECOND, so repeated captures into one shared logdir used to land in
# the same session dir and overwrite each other's trace files — the
# timeline parser (observability.timeline) needs unambiguous capture
# dirs.  pid + a process-local counter keeps the names unique across
# forks and across captures.
_capture_dir: Optional[str] = None
_capture_seq = itertools.count()


def start_profile(logdir: str = "/tmp/apex_tpu_profile") -> str:
    """Begin an xprof trace window (cudaProfilerStart parity,
    main_amp.py:329).  Reentrant: only the outermost call starts the
    trace; nested calls increment the window refcount and no-op.
    Returns the window's unique capture directory (a fresh
    ``capture_<pid>_<n>`` subdirectory of ``logdir`` per outermost
    window); a nested call joins the outer window and returns ITS
    directory — the nested ``logdir`` argument is ignored, exactly as
    its start/stop always was."""
    global _trace_depth, _capture_dir
    with _trace_lock:
        if _trace_depth == 0:
            cap = os.path.join(
                logdir, f"capture_{os.getpid()}_{next(_capture_seq):04d}")
            os.makedirs(cap, exist_ok=True)
            # start first, increment after: a failed start_trace (e.g. a
            # foreign trace already active) must not leave a phantom
            # refcount that makes every later call a silent no-op —
            # nor an orphaned empty capture dir (a monitor retrying
            # /profilez against a long-lived foreign trace would grow
            # one per attempt)
            try:
                jax.profiler.start_trace(cap)
            except BaseException:
                try:
                    os.rmdir(cap)       # still empty: nothing traced
                except OSError:
                    pass
                raise
            _capture_dir = cap
        _trace_depth += 1
        return _capture_dir


def stop_profile() -> Optional[str]:
    """End the trace window (cudaProfilerStop parity, main_amp.py:351).
    Only the outermost matching call stops the trace (and returns the
    finished window's capture directory); an inner or unmatched stop is
    a no-op returning None."""
    global _trace_depth
    with _trace_lock:
        if _trace_depth == 0:
            return None
        _trace_depth -= 1
        if _trace_depth == 0:
            jax.profiler.stop_trace()
            return _capture_dir
        return None


def profiling_active() -> bool:
    """True while a trace window is open (any nesting depth)."""
    with _trace_lock:
        return _trace_depth > 0


def current_capture_dir() -> Optional[str]:
    """The ACTIVE window's unique capture directory (None when no
    window is open)."""
    with _trace_lock:
        return _capture_dir if _trace_depth > 0 else None


def last_capture_dir() -> Optional[str]:
    """The most recent window's capture directory — still set after
    ``stop_profile``, which is when the trace file exists and the
    timeline parser wants it.  None before the first window."""
    with _trace_lock:
        return _capture_dir


@contextlib.contextmanager
def profile(logdir: str = "/tmp/apex_tpu_profile"):
    """Context-manager trace window; nesting-safe — an inner profile()
    joins the outer window instead of racing jax.profiler.start_trace
    or closing the outer window early.  Yields the window's unique
    capture directory (parse it with
    ``observability.timeline.analyze_capture`` AFTER the block exits —
    the trace file is written at stop)."""
    cap = start_profile(logdir)
    try:
        yield cap
    finally:
        stop_profile()


class AverageMeter:
    """Running average tracker (reference examples/imagenet/main_amp.py:
    415-430); used by the examples for loss/throughput reporting."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.val = 0.0
        self.avg = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val: float, n: int = 1):
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)
