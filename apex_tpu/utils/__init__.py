"""apex_tpu.utils — profiling/tracing shims and small training utilities.

The reference annotates hot boundaries with NVTX ranges
(apex/parallel/sync_batchnorm.py:69,87,132; examples/imagenet/main_amp.py:
325-352 gates cudaProfilerStart/Stop windows behind ``--prof``).  The TPU
equivalents are ``jax.named_scope`` (names HLO ops so XLA profiles/dumps
carry them) and ``jax.profiler`` trace annotations (host-side timeline
ranges); this module provides both behind the reference's push/pop shape.
"""

from .profiler import (range_push, range_pop, nvtx_range, annotate,
                       start_profile, stop_profile, profile,
                       profiling_active, current_capture_dir,
                       last_capture_dir, AverageMeter)
from .checkpoint import (save_checkpoint, restore_checkpoint, latest_step,
                         available_steps)
from . import ema

__all__ = ["ema", "range_push", "range_pop", "nvtx_range", "annotate",
           "start_profile", "stop_profile", "profile", "profiling_active",
           "current_capture_dir", "last_capture_dir",
           "AverageMeter", "save_checkpoint", "restore_checkpoint",
           "latest_step", "available_steps"]
