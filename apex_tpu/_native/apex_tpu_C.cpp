// apex_tpu_C: native host runtime for apex_tpu.
//
// TPU-native counterpart of the reference's apex_C extension
// (csrc/flatten_unflatten.cpp:5-13) plus the host-side pieces that matter
// on TPU: on TPU the *device* flatten is free (XLA fuses concatenates),
// but host-side staging — assembling fused fp32 buffers from numpy arrays,
// planning DDP buckets, and preprocessing input batches — sits on the
// critical path of the input pipeline and is implemented here in C++ with
// a small thread pool.
//
// Exposed via a plain C ABI and loaded with ctypes (the environment has no
// pybind11); every entry point has a pure-Python fallback in
// apex_tpu/_native/__init__.py, mirroring the reference's graceful
// degradation (README.md:90-95).

#include <array>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <numeric>
#include <queue>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Minimal thread pool (shared by flatten and preprocessing).
// ---------------------------------------------------------------------------
class ThreadPool {
 public:
  explicit ThreadPool(int n) : stop_(false) {
    for (int i = 0; i < n; ++i) {
      workers_.emplace_back([this] {
        for (;;) {
          std::function<void()> task;
          {
            std::unique_lock<std::mutex> lock(mu_);
            cv_.wait(lock, [this] { return stop_ || !tasks_.empty(); });
            if (stop_ && tasks_.empty()) return;
            task = std::move(tasks_.front());
            tasks_.pop();
          }
          task();
          done_.fetch_add(1, std::memory_order_release);
        }
      });
    }
  }

  ~ThreadPool() {
    {
      std::lock_guard<std::mutex> lock(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& w : workers_) w.join();
  }

  void Submit(std::function<void()> f) {
    {
      std::lock_guard<std::mutex> lock(mu_);
      tasks_.push(std::move(f));
    }
    submitted_.fetch_add(1, std::memory_order_acq_rel);
    cv_.notify_one();
  }

  // Monotonic counters, never reset: Wait() snapshots the submit count at
  // entry and blocks until that many tasks have completed.  Concurrent
  // callers sharing the singleton pool may over-wait (for each other's
  // tasks) but can never under-wait or deadlock — no data race.
  void Wait() {
    uint64_t target = submitted_.load(std::memory_order_acquire);
    while (done_.load(std::memory_order_acquire) < target) {
      std::this_thread::yield();
    }
  }

  static ThreadPool& Get() {
    static ThreadPool pool(
        std::max(1u, std::thread::hardware_concurrency()));
    return pool;
  }

 private:
  std::vector<std::thread> workers_;
  std::queue<std::function<void()>> tasks_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool stop_;
  std::atomic<uint64_t> submitted_{0};
  std::atomic<uint64_t> done_{0};
};

}  // namespace

namespace {

// Shared per-image normalize: uint8 HWC plane gather -> fp32 CHW planes.
// Used by the one-shot preprocess API and the prefetching loader.
inline void NormalizeImage(const uint8_t* src, float* dst, int64_t h,
                           int64_t w, int64_t c, const float* mean,
                           const float* inv_std) {
  for (int64_t k = 0; k < c; ++k) {
    float mk = mean[k], ik = inv_std[k];
    float* plane = dst + k * h * w;
    for (int64_t p = 0; p < h * w; ++p) {
      plane[p] = (static_cast<float>(src[p * c + k]) - mk) * ik;
    }
  }
}

// channels-last variant: normalize in place order (no transpose) — a
// straight sequential walk, feeding channels-last models without the
// NHWC->NCHW->NHWC round trip.
inline void NormalizeImageNHWC(const uint8_t* src, float* dst, int64_t h,
                               int64_t w, int64_t c, const float* mean,
                               const float* inv_std) {
  for (int64_t p = 0; p < h * w; ++p) {
    const uint8_t* sp = src + p * c;
    float* dp = dst + p * c;
    for (int64_t k = 0; k < c; ++k) {
      dp[k] = (static_cast<float>(sp[k]) - mean[k]) * inv_std[k];
    }
  }
}

}  // namespace

extern "C" {

// Concatenate n same-dtype host tensors into one contiguous buffer
// (apex_C.flatten). srcs[i] points at sizes[i] elements of elem_size bytes.
void apex_flatten(const void** srcs, const int64_t* sizes, int n,
                  int64_t elem_size, void* dst) {
  // compute offsets, then copy chunks in parallel
  std::vector<int64_t> offsets(n + 1, 0);
  for (int i = 0; i < n; ++i) offsets[i + 1] = offsets[i] + sizes[i];
  auto& pool = ThreadPool::Get();
  char* out = static_cast<char*>(dst);
  for (int i = 0; i < n; ++i) {
    const char* src = static_cast<const char*>(srcs[i]);
    char* d = out + offsets[i] * elem_size;
    int64_t bytes = sizes[i] * elem_size;
    pool.Submit([src, d, bytes] { std::memcpy(d, src, bytes); });
  }
  pool.Wait();
}

// Inverse: scatter a flat buffer back into n host tensors
// (apex_C.unflatten).
void apex_unflatten(const void* src, const int64_t* sizes, int n,
                    int64_t elem_size, void** dsts) {
  std::vector<int64_t> offsets(n + 1, 0);
  for (int i = 0; i < n; ++i) offsets[i + 1] = offsets[i] + sizes[i];
  auto& pool = ThreadPool::Get();
  const char* in = static_cast<const char*>(src);
  for (int i = 0; i < n; ++i) {
    const char* s = in + offsets[i] * elem_size;
    char* dst = static_cast<char*>(dsts[i]);
    int64_t bytes = sizes[i] * elem_size;
    pool.Submit([s, dst, bytes] { std::memcpy(dst, s, bytes); });
  }
  pool.Wait();
}

// Greedy in-order bucket assignment: tensors are packed into buckets of at
// least message_size elements in arrival order — the planning half of the
// reference DDP's bucketing (distributed.py:338-361), done once on host
// instead of per-backward on device.  Returns the number of buckets.
int apex_plan_buckets(const int64_t* sizes, int n, int64_t message_size,
                      int32_t* bucket_ids) {
  int bucket = 0;
  int64_t filled = 0;
  for (int i = 0; i < n; ++i) {
    bucket_ids[i] = bucket;
    filled += sizes[i];
    if (filled >= message_size) {
      bucket++;
      filled = 0;
    }
  }
  return (filled > 0 || n == 0) ? bucket + 1 : bucket;
}

// Input-pipeline preprocessing: NHWC uint8 images -> NCHW float32,
// normalized with per-channel mean/std — the host half of the reference
// example's data_prefetcher (examples/imagenet/main_amp.py:264-300), which
// on GPU ran on a side CUDA stream; on TPU it runs on host threads
// overlapped with device compute.
static void PreprocessBatch(const uint8_t* in, float* out, int64_t n,
                            int64_t h, int64_t w, int64_t c,
                            const float* mean, const float* std,
                            bool channels_last) {
  auto& pool = ThreadPool::Get();
  std::vector<float> inv_std(c);
  for (int64_t k = 0; k < c; ++k) inv_std[k] = 1.0f / std[k];
  const float* inv = inv_std.data();
  for (int64_t img = 0; img < n; ++img) {
    const uint8_t* src = in + img * h * w * c;
    float* dst = out + img * h * w * c;   // same element count per image
    pool.Submit([src, dst, h, w, c, mean, inv, channels_last] {
      if (channels_last) {
        NormalizeImageNHWC(src, dst, h, w, c, mean, inv);
      } else {
        NormalizeImage(src, dst, h, w, c, mean, inv);
      }
    });
  }
  pool.Wait();
}

void apex_preprocess_nhwc_u8_to_nchw_f32(const uint8_t* in, float* out,
                                         int64_t n, int64_t h, int64_t w,
                                         int64_t c, const float* mean,
                                         const float* std) {
  PreprocessBatch(in, out, n, h, w, c, mean, std, /*channels_last=*/false);
}

// channels-last variant: same threaded normalize, no transpose
void apex_preprocess_nhwc_u8_to_nhwc_f32(const uint8_t* in, float* out,
                                         int64_t n, int64_t h, int64_t w,
                                         int64_t c, const float* mean,
                                         const float* std) {
  PreprocessBatch(in, out, n, h, w, c, mean, std, /*channels_last=*/true);
}

int apex_native_version() { return 3; }

}  // extern "C"

// ---------------------------------------------------------------------------
// Prefetching data loader: the native input pipeline.
//
// The reference's data_prefetcher (examples/imagenet/main_amp.py:264-300)
// overlaps H2D copies + normalization with compute on a side CUDA stream.
// The TPU-native equivalent is host-side: worker threads assemble
// normalized NCHW fp32 batches into a ring of slots *ahead* of the
// training loop, so the Python step only wraps a ready pointer and hands
// it to device_put while the next batches are already being built.
//
// Ordered delivery: batch numbers are assigned under the slot mutex, so
// the outstanding batches always occupy the available slots and the
// consumer (who demands batch k before k+1) can never deadlock.
// Shuffling is a per-epoch affine bijection i -> (a*i + c) % n (stateless,
// workers never coordinate about epoch boundaries).
// ---------------------------------------------------------------------------

namespace {

struct Slot {
  std::vector<float> images;
  std::vector<int32_t> labels;
  int64_t batch = -1;
  enum State { kFree, kFilling, kReady, kInUse } state = kFree;
};

struct Loader {
  const uint8_t* images;  // (n, h, w, c) borrowed; caller keeps it alive
  const int32_t* labels;  // (n,)
  int64_t n, h, w, c, batch;
  std::vector<float> mean, inv_std;
  bool channels_last = false;   // deliver (B, H, W, C) instead of NCHW
  bool shuffle;
  uint64_t seed;
  int64_t batches_per_epoch;

  std::vector<Slot> slots;
  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv_free, cv_ready;
  int64_t next_fill = 0;
  int64_t next_deliver = 0;
  bool stop = false;
  // consumers currently inside apex_loader_next: destroy() must not free
  // the Loader while one is re-acquiring mu after the stop wakeup
  int in_next = 0;
  std::condition_variable cv_quiesce;

  // Per-epoch true permutations (Fisher–Yates over a splitmix64 stream),
  // matching the Python fallback's np.random.permutation semantics: every
  // sample appears exactly once per epoch.  The previous affine-bijection
  // "shuffle" was a correlated-stride walk, not a uniform shuffle
  // (round-1 advisor finding).  Four exact-keyed cache slots cover the
  // epochs that can be in flight at once (bounded by prefetch depth);
  // Fill() copies its batch's indices under one lock, so no reference
  // escapes and workers don't serialize per sample.
  static constexpr int kPermSlots = 4;
  std::mutex perm_mu;
  std::array<int64_t, kPermSlots> perm_epoch{-1, -1, -1, -1};
  std::array<std::vector<int64_t>, kPermSlots> perms;

  static uint64_t SplitMix64(uint64_t& s) {
    uint64_t z = (s += 0x9e3779b97f4a7c15ull);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
    return z ^ (z >> 31);
  }

  // Copies the batch's sample indices out *by value under one lock*: a
  // reference escaping the lock could be regenerated in place by a worker
  // several epochs ahead reusing the cache slot (tiny datasets put 3+
  // epochs in flight with the default prefetch depth).
  void BatchIndices(int64_t global_batch, std::vector<int64_t>& out) {
    int64_t epoch = global_batch / batches_per_epoch;
    int64_t start = (global_batch % batches_per_epoch) * batch;
    out.resize(batch);
    if (!shuffle) {
      for (int64_t j = 0; j < batch; ++j) out[j] = start + j;
      return;
    }
    std::lock_guard<std::mutex> lock(perm_mu);
    int slot = static_cast<int>(epoch % kPermSlots);
    if (perm_epoch[slot] != epoch) {
      auto& p = perms[slot];
      p.resize(n);
      for (int64_t k = 0; k < n; ++k) p[k] = k;
      uint64_t s = seed + 0x9e3779b97f4a7c15ull * (epoch + 1);
      for (int64_t k = n - 1; k > 0; --k) {
        int64_t j = static_cast<int64_t>(SplitMix64(s) % (k + 1));
        std::swap(p[k], p[j]);
      }
      perm_epoch[slot] = epoch;
    }
    const auto& p = perms[slot];
    for (int64_t j = 0; j < batch; ++j) out[j] = p[start + j];
  }

  void Fill(Slot& s, int64_t b) {
    float* dst_base = s.images.data();
    std::vector<int64_t> idx;
    BatchIndices(b, idx);
    for (int64_t j = 0; j < batch; ++j) {
      int64_t src_idx = idx[j];
      const uint8_t* src = images + src_idx * h * w * c;
      float* dst = dst_base + j * c * h * w;
      if (channels_last) {
        NormalizeImageNHWC(src, dst, h, w, c, mean.data(), inv_std.data());
      } else {
        NormalizeImage(src, dst, h, w, c, mean.data(), inv_std.data());
      }
      s.labels[j] = labels[src_idx];
    }
  }

  void WorkerLoop() {
    for (;;) {
      Slot* s = nullptr;
      int64_t b;
      {
        std::unique_lock<std::mutex> lock(mu);
        cv_free.wait(lock, [this] {
          if (stop) return true;
          for (auto& sl : slots)
            if (sl.state == Slot::kFree) return true;
          return false;
        });
        if (stop) return;
        for (auto& sl : slots) {
          if (sl.state == Slot::kFree) { s = &sl; break; }
        }
        b = next_fill++;  // assigned under the lock: see header comment
        s->state = Slot::kFilling;
        s->batch = b;
      }
      Fill(*s, b);
      {
        std::lock_guard<std::mutex> lock(mu);
        s->state = Slot::kReady;
      }
      cv_ready.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* apex_loader_create(const uint8_t* images, const int32_t* labels,
                         int64_t n, int64_t h, int64_t w, int64_t c,
                         int64_t batch, int depth, int num_workers,
                         uint64_t seed, const float* mean,
                         const float* stddev, int shuffle,
                         int channels_last) {
  if (n < batch || batch <= 0 || depth <= 0 || num_workers <= 0)
    return nullptr;
  auto* L = new Loader();
  L->images = images;
  L->labels = labels;
  L->n = n; L->h = h; L->w = w; L->c = c; L->batch = batch;
  L->channels_last = channels_last != 0;
  L->shuffle = shuffle != 0;
  L->seed = seed;
  L->batches_per_epoch = n / batch;  // drop-last
  L->mean.assign(mean, mean + c);
  L->inv_std.resize(c);
  for (int64_t k = 0; k < c; ++k) L->inv_std[k] = 1.0f / stddev[k];
  L->slots.resize(depth);
  for (auto& s : L->slots) {
    s.images.resize(batch * c * h * w);
    s.labels.resize(batch);
  }
  for (int i = 0; i < num_workers; ++i)
    L->workers.emplace_back([L] { L->WorkerLoop(); });
  return L;
}

// Blocks until the next in-order batch is ready; returns its index and
// pointers into the slot (valid until apex_loader_release of that pointer).
int64_t apex_loader_next(void* loader, const float** out_images,
                         const int32_t** out_labels) {
  auto* L = static_cast<Loader*>(loader);
  std::unique_lock<std::mutex> lock(L->mu);
  L->in_next++;
  Slot* hit = nullptr;
  // stop also releases consumers: destroy() must not hang a thread
  // blocked here (round-1 advisor finding)
  L->cv_ready.wait(lock, [&] {
    if (L->stop) return true;
    for (auto& s : L->slots) {
      if (s.state == Slot::kReady && s.batch == L->next_deliver) {
        hit = &s;
        return true;
      }
    }
    return false;
  });
  if (L->stop && hit == nullptr) {
    // signal destroy() we are out before it frees the Loader
    L->in_next--;
    L->cv_quiesce.notify_all();
    return -1;
  }
  L->in_next--;
  L->cv_quiesce.notify_all();   // destroy() may be draining concurrently
  hit->state = Slot::kInUse;
  L->next_deliver++;
  *out_images = hit->images.data();
  *out_labels = hit->labels.data();
  return hit->batch;
}

// Return a delivered slot (identified by its images pointer) to the pool.
void apex_loader_release(void* loader, const float* images_ptr) {
  auto* L = static_cast<Loader*>(loader);
  {
    std::lock_guard<std::mutex> lock(L->mu);
    for (auto& s : L->slots) {
      if (s.images.data() == images_ptr && s.state == Slot::kInUse) {
        s.state = Slot::kFree;
        break;
      }
    }
  }
  L->cv_free.notify_one();
}

void apex_loader_destroy(void* loader) {
  auto* L = static_cast<Loader*>(loader);
  {
    std::lock_guard<std::mutex> lock(L->mu);
    L->stop = true;
  }
  L->cv_free.notify_all();
  L->cv_ready.notify_all();   // wake any consumer blocked in next()
  {
    // wait until no consumer is inside next() — deleting while one is
    // re-acquiring mu after the stop wakeup would be a use-after-free
    std::unique_lock<std::mutex> lock(L->mu);
    L->cv_quiesce.wait(lock, [L] { return L->in_next == 0; });
  }
  for (auto& wkr : L->workers) wkr.join();
  delete L;
}

}  // extern "C"
