// apex_tpu_C: native host runtime for apex_tpu.
//
// TPU-native counterpart of the reference's apex_C extension
// (csrc/flatten_unflatten.cpp:5-13) plus the host-side pieces that matter
// on TPU: on TPU the *device* flatten is free (XLA fuses concatenates),
// but host-side staging — assembling fused fp32 buffers from numpy arrays,
// planning DDP buckets, and preprocessing input batches — sits on the
// critical path of the input pipeline and is implemented here in C++ with
// a small thread pool.
//
// Exposed via a plain C ABI and loaded with ctypes (the environment has no
// pybind11); every entry point has a pure-Python fallback in
// apex_tpu/_native/__init__.py, mirroring the reference's graceful
// degradation (README.md:90-95).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Minimal thread pool (shared by flatten and preprocessing).
// ---------------------------------------------------------------------------
class ThreadPool {
 public:
  explicit ThreadPool(int n) : stop_(false) {
    for (int i = 0; i < n; ++i) {
      workers_.emplace_back([this] {
        for (;;) {
          std::function<void()> task;
          {
            std::unique_lock<std::mutex> lock(mu_);
            cv_.wait(lock, [this] { return stop_ || !tasks_.empty(); });
            if (stop_ && tasks_.empty()) return;
            task = std::move(tasks_.front());
            tasks_.pop();
          }
          task();
          done_.fetch_add(1, std::memory_order_release);
        }
      });
    }
  }

  ~ThreadPool() {
    {
      std::lock_guard<std::mutex> lock(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& w : workers_) w.join();
  }

  void Submit(std::function<void()> f) {
    {
      std::lock_guard<std::mutex> lock(mu_);
      tasks_.push(std::move(f));
    }
    submitted_.fetch_add(1, std::memory_order_acq_rel);
    cv_.notify_one();
  }

  // Monotonic counters, never reset: Wait() snapshots the submit count at
  // entry and blocks until that many tasks have completed.  Concurrent
  // callers sharing the singleton pool may over-wait (for each other's
  // tasks) but can never under-wait or deadlock — no data race.
  void Wait() {
    uint64_t target = submitted_.load(std::memory_order_acquire);
    while (done_.load(std::memory_order_acquire) < target) {
      std::this_thread::yield();
    }
  }

  static ThreadPool& Get() {
    static ThreadPool pool(
        std::max(1u, std::thread::hardware_concurrency()));
    return pool;
  }

 private:
  std::vector<std::thread> workers_;
  std::queue<std::function<void()>> tasks_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool stop_;
  std::atomic<uint64_t> submitted_{0};
  std::atomic<uint64_t> done_{0};
};

}  // namespace

extern "C" {

// Concatenate n same-dtype host tensors into one contiguous buffer
// (apex_C.flatten). srcs[i] points at sizes[i] elements of elem_size bytes.
void apex_flatten(const void** srcs, const int64_t* sizes, int n,
                  int64_t elem_size, void* dst) {
  // compute offsets, then copy chunks in parallel
  std::vector<int64_t> offsets(n + 1, 0);
  for (int i = 0; i < n; ++i) offsets[i + 1] = offsets[i] + sizes[i];
  auto& pool = ThreadPool::Get();
  char* out = static_cast<char*>(dst);
  for (int i = 0; i < n; ++i) {
    const char* src = static_cast<const char*>(srcs[i]);
    char* d = out + offsets[i] * elem_size;
    int64_t bytes = sizes[i] * elem_size;
    pool.Submit([src, d, bytes] { std::memcpy(d, src, bytes); });
  }
  pool.Wait();
}

// Inverse: scatter a flat buffer back into n host tensors
// (apex_C.unflatten).
void apex_unflatten(const void* src, const int64_t* sizes, int n,
                    int64_t elem_size, void** dsts) {
  std::vector<int64_t> offsets(n + 1, 0);
  for (int i = 0; i < n; ++i) offsets[i + 1] = offsets[i] + sizes[i];
  auto& pool = ThreadPool::Get();
  const char* in = static_cast<const char*>(src);
  for (int i = 0; i < n; ++i) {
    const char* s = in + offsets[i] * elem_size;
    char* dst = static_cast<char*>(dsts[i]);
    int64_t bytes = sizes[i] * elem_size;
    pool.Submit([s, dst, bytes] { std::memcpy(dst, s, bytes); });
  }
  pool.Wait();
}

// Greedy in-order bucket assignment: tensors are packed into buckets of at
// least message_size elements in arrival order — the planning half of the
// reference DDP's bucketing (distributed.py:338-361), done once on host
// instead of per-backward on device.  Returns the number of buckets.
int apex_plan_buckets(const int64_t* sizes, int n, int64_t message_size,
                      int32_t* bucket_ids) {
  int bucket = 0;
  int64_t filled = 0;
  for (int i = 0; i < n; ++i) {
    bucket_ids[i] = bucket;
    filled += sizes[i];
    if (filled >= message_size) {
      bucket++;
      filled = 0;
    }
  }
  return (filled > 0 || n == 0) ? bucket + 1 : bucket;
}

// Input-pipeline preprocessing: NHWC uint8 images -> NCHW float32,
// normalized with per-channel mean/std — the host half of the reference
// example's data_prefetcher (examples/imagenet/main_amp.py:264-300), which
// on GPU ran on a side CUDA stream; on TPU it runs on host threads
// overlapped with device compute.
void apex_preprocess_nhwc_u8_to_nchw_f32(const uint8_t* in, float* out,
                                         int64_t n, int64_t h, int64_t w,
                                         int64_t c, const float* mean,
                                         const float* std) {
  auto& pool = ThreadPool::Get();
  std::vector<float> inv_std(c);
  for (int64_t k = 0; k < c; ++k) inv_std[k] = 1.0f / std[k];
  const float* inv = inv_std.data();
  for (int64_t img = 0; img < n; ++img) {
    const uint8_t* src = in + img * h * w * c;
    float* dst = out + img * c * h * w;
    pool.Submit([src, dst, h, w, c, mean, inv] {
      for (int64_t k = 0; k < c; ++k) {
        float mk = mean[k], ik = inv[k];
        float* plane = dst + k * h * w;
        for (int64_t p = 0; p < h * w; ++p) {
          plane[p] = (static_cast<float>(src[p * c + k]) - mk) * ik;
        }
      }
    });
  }
  pool.Wait();
}

int apex_native_version() { return 1; }

}  // extern "C"
