#!/usr/bin/env bash
# Build the apex_tpu native host runtime (plain g++, no external deps).
set -euo pipefail
cd "$(dirname "$0")"
# no -march=native: the .so may outlive the build machine; -O3 + memcpy
# dominate anyway
g++ -O3 -fPIC -shared -pthread -std=c++17 \
    apex_tpu_C.cpp -o libapex_tpu_C.so
echo "built $(pwd)/libapex_tpu_C.so"
