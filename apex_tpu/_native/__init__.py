"""apex_tpu._native — ctypes bindings for the C++ host runtime.

Loads libapex_tpu_C.so (built by build.sh / `python setup.py build_native`),
auto-building it on first import when a compiler is available.  Every entry
point has a numpy fallback, so a Python-only environment keeps working —
the reference's graceful-degradation invariant (README.md:90-95) applied
to the host runtime.

API:
  available() -> bool
  flatten(list[np.ndarray]) -> np.ndarray           (apex_C.flatten)
  unflatten(flat, like) -> list[np.ndarray]         (apex_C.unflatten)
  plan_buckets(sizes, message_size) -> np.ndarray   (DDP bucket planner)
  preprocess_images(u8_nhwc, mean, std, data_format="NCHW"|"NHWC")
      -> normalized f32, transposed to NCHW or delivered NHWC in place
      order (input pipeline)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Sequence

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libapex_tpu_C.so")

_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _needs_build() -> bool:
    if not os.path.exists(_SO):
        return True
    src = os.path.join(_HERE, "apex_tpu_C.cpp")
    try:  # rebuild when the source is newer than the binary
        return os.path.getmtime(src) > os.path.getmtime(_SO)
    except OSError:
        return False


def _try_load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed:  # don't shell out to the compiler on every call
        return None
    if _needs_build():
        try:
            subprocess.run(["bash", os.path.join(_HERE, "build.sh")],
                           check=True, capture_output=True, timeout=120)
        except Exception:
            _load_failed = True
            return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        _load_failed = True
        return None
    lib.apex_flatten.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int, ctypes.c_int64, ctypes.c_void_p]
    lib.apex_unflatten.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_void_p)]
    lib.apex_plan_buckets.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32)]
    lib.apex_plan_buckets.restype = ctypes.c_int
    lib.apex_preprocess_nhwc_u8_to_nchw_f32.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float)]
    try:
        lib.apex_preprocess_nhwc_u8_to_nhwc_f32.argtypes = \
            lib.apex_preprocess_nhwc_u8_to_nchw_f32.argtypes
    except AttributeError:
        pass    # stale v2 .so; version() gates the NHWC paths below
    lib.apex_native_version.restype = ctypes.c_int
    # ABI v2's create takes 13 args; v3 appended a data_format int.
    # Declare exactly what the loaded .so expects — passing a surplus
    # trailing int to a v2 library happens to work on x86-64/aarch64
    # calling conventions but is not something to rely on.
    _loader_args = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
        ctypes.c_int, ctypes.c_uint64, ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float), ctypes.c_int]
    if int(lib.apex_native_version()) >= 3:
        _loader_args.append(ctypes.c_int)
    lib.apex_loader_create.argtypes = _loader_args
    lib.apex_loader_create.restype = ctypes.c_void_p
    lib.apex_loader_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_void_p)]
    lib.apex_loader_next.restype = ctypes.c_int64
    lib.apex_loader_release.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.apex_loader_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def available() -> bool:
    return _try_load() is not None


def flatten(tensors: Sequence[np.ndarray]) -> np.ndarray:
    """Concatenate same-dtype host arrays into one contiguous 1-D buffer."""
    tensors = [np.ascontiguousarray(t) for t in tensors]
    if not tensors:
        return np.zeros((0,), np.float32)
    dt = tensors[0].dtype
    if any(t.dtype != dt for t in tensors):
        raise TypeError("flatten() requires a same-dtype list")
    total = sum(t.size for t in tensors)
    lib = _try_load()
    if lib is None:
        return np.concatenate([t.reshape(-1) for t in tensors])
    out = np.empty((total,), dt)
    n = len(tensors)
    srcs = (ctypes.c_void_p * n)(
        *[t.ctypes.data_as(ctypes.c_void_p) for t in tensors])
    sizes = (ctypes.c_int64 * n)(*[t.size for t in tensors])
    lib.apex_flatten(srcs, sizes, n, dt.itemsize,
                     out.ctypes.data_as(ctypes.c_void_p))
    return out


def unflatten(flat: np.ndarray, like: Sequence[np.ndarray]
              ) -> List[np.ndarray]:
    flat = np.ascontiguousarray(flat)
    lib = _try_load()
    outs = [np.empty(t.shape, flat.dtype) for t in like]
    if lib is None:
        off = 0
        for o in outs:
            o[...] = flat[off:off + o.size].reshape(o.shape)
            off += o.size
        return outs
    n = len(outs)
    dsts = (ctypes.c_void_p * n)(
        *[o.ctypes.data_as(ctypes.c_void_p) for o in outs])
    sizes = (ctypes.c_int64 * n)(*[o.size for o in outs])
    lib.apex_unflatten(flat.ctypes.data_as(ctypes.c_void_p), sizes, n,
                       flat.dtype.itemsize, dsts)
    return outs


def plan_buckets(sizes: Sequence[int], message_size: int) -> np.ndarray:
    """Greedy in-order bucket ids (DDP bucketing, distributed.py:338-361)."""
    sizes = np.asarray(list(sizes), np.int64)
    lib = _try_load()
    if lib is None:
        ids = np.zeros(len(sizes), np.int32)
        bucket = filled = 0
        for i, s in enumerate(sizes):
            ids[i] = bucket
            filled += int(s)
            if filled >= message_size:
                bucket += 1
                filled = 0
        return ids
    ids = np.zeros(len(sizes), np.int32)
    lib.apex_plan_buckets(
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(sizes),
        message_size, ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return ids


def version() -> int:
    """ABI version of the loaded native lib (0 when unavailable)."""
    lib = _try_load()
    return int(lib.apex_native_version()) if lib is not None else 0


def preprocess_images(images_u8: np.ndarray, mean: Sequence[float],
                      std: Sequence[float],
                      data_format: str = "NCHW") -> np.ndarray:
    """NHWC uint8 -> normalized float32 on host threads, delivered NCHW
    (default) or NHWC (no transpose)."""
    images_u8 = np.ascontiguousarray(images_u8)
    n, h, w, c = images_u8.shape
    nhwc_out = data_format == "NHWC"
    lib = _try_load()
    # the NHWC entry point needs ABI v3 — a stale v2 .so falls back
    if lib is None or (nhwc_out and version() < 3):
        f = images_u8.astype(np.float32)
        f = (f - np.asarray(mean, np.float32)) / np.asarray(std, np.float32)
        return np.ascontiguousarray(f if nhwc_out
                                    else f.transpose(0, 3, 1, 2))
    out = np.empty((n, h, w, c) if nhwc_out else (n, c, h, w), np.float32)
    mean_c = (ctypes.c_float * c)(*[float(m) for m in mean])
    std_c = (ctypes.c_float * c)(*[float(s) for s in std])
    fn = (lib.apex_preprocess_nhwc_u8_to_nhwc_f32 if nhwc_out
          else lib.apex_preprocess_nhwc_u8_to_nchw_f32)
    fn(images_u8.ctypes.data_as(ctypes.c_void_p),
       out.ctypes.data_as(ctypes.c_void_p), n, h, w, c, mean_c, std_c)
    return out
