"""Continuous-batching serving engine (vLLM-lite, fixed slots).

The reference toolkit predates LLM serving; generate_cached covers the
static-batch case, and this engine covers the real serving shape:
requests ARRIVE and FINISH at different times, and the decode step
always runs the full slot batch so the MXU stays busy while individual
sequences come and go.

Design (deliberately simple — correctness over paging):

- ``slots`` fixed sequences of length ``buf_len``; per-slot KV cache
  rows inside the usual (B, Hkv, S, D) buffers;
- ``add_request`` claims a free slot, seeds ITS cache row with a
  chunked prefill of the prompt (one scatter per layer), no impact on
  other slots;
- ``step()`` is ONE jitted dispatch: a DECODE WINDOW of ``window``
  in-graph decode ticks (``lax.scan`` over ``decode_chunk(L=1)`` at
  per-slot positions + greedy head, models/llama.py decode_chunk
  contract), emitting a ``[slots, window]`` token buffer plus validity
  masks that the host unpacks ONCE per window — the per-token
  host-sync tax becomes a per-window tax.  Inactive slots decode
  garbage that the masks drop; a slot that hits its EOS or token
  limit mid-window FREEZES in-graph (ids/cur_len/cache/RNG stream
  stop advancing) so exactness survives any window size; arrivals
  are admitted at window boundaries;
- every jitted cache mutator donates its KV buffers
  (``donate_argnums``): the multi-GB cache is updated in place
  instead of XLA keeping a second copy alive across every tick;
- a request finishes on ``eos_token_id`` or its ``max_new_tokens``;
  the slot frees immediately and can be reclaimed next ``add_request``;
- optional PREFIX SHARING (``prefix_pool``): registered prompt
  prefixes are prefilled once into pool rows; matching requests admit
  by a static KV row-copy + suffix-only chunked prefill (see
  ``Engine.__init__``) — the static-shape answer to vLLM's prefix
  cache.

Exactness (greedy and speculative-greedy paths): a request's output is
token-for-token what ``generate_cached`` would produce for it alone —
regardless of what other requests share the batch (pinned in
tests/test_serving.py with staggered arrivals).  Sampled mode
(``temperature > 0``) draws each request from its own key stream,
advanced once per its own decode step — co-tenants and arrival timing
never perturb it.  With an explicit ``submit(..., seed=N)`` the stream
is request-intrinsic (fully batch-independent, pinned in tests); the
default stream keys off the request id, i.e. it is deterministic given
the engine's SUBMISSION ORDER.  The two namespaces are
domain-separated, so an explicit seed never collides with an auto id.

Works with any model exposing ``prefill_cache`` / ``decode_chunk`` /
``init_cache`` and a greedy head (GPT, Llama and its Mistral / Qwen2 /
Gemma / NeoX configs).  MoE models must be served DROPLESS
(``capacity_factor >= n_experts``, e.g. a ``mixtral_from_hf`` config):
capacity-bounded routing would make one request's tokens depend on
which other requests share the batch, and the constructor rejects it.

Encoder-decoder models (T5) get their own :class:`Seq2SeqEngine`: the
per-slot residents are the request's precomputed cross-attention K/V
and a decoder self-attention cache instead of one decoder KV cache.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .models.speculative import _head_logits
from .observability import MetricsRegistry
# every engine jit routes through the compilation ledger: the entry
# label + abstract-signature record is what the zero-retrace
# steady-state contract (tests/test_serving.py) and the fleet's
# survivors-recompile-nothing pin measure deltas over.  The wrapper's
# bookkeeping is host-side python — the traced graphs are unchanged,
# so the donation/host-transfer audits hold as before.
from .observability.compilation import instrumented_jit
# ambient-gated spans: these record ONLY when a distributed-trace
# context is active on the calling thread (a fleet dispatching a traced
# request), so a standalone engine pays one contextvar read per call
# and its process recorder never grows — and nothing here touches the
# jitted graphs, so the zero-host-transfer audit is unaffected.
from .observability.tracing import maybe_event, maybe_span

__all__ = ["Engine", "PagedEngine", "Seq2SeqEngine",
           "DONATION_BLOCKLIST", "STEP_K_ARG_NAMES",
           "PREFILL_SLOT_ARG_NAMES", "SEQ2SEQ_STEP_K_ARG_NAMES",
           "PAGED_STEP_K_ARG_NAMES", "PAGED_ADMIT_ARG_NAMES"]

# Argument names the engine jits must NEVER donate: per-slot length
# vectors.  Donating `_sstep`'s cur_len made executables RELOADED from
# the persistent XLA:CPU compile cache decode garbage (fresh compiles
# fine — single runs pass, the next warm run hangs; jax 0.4.37 AOT
# quirk, PR 2).  apex_tpu.analysis's donation rule enforces this
# blocklist over every registered serving entry point, so the gotcha
# stays pinned even if the inline comments rot.  kv_len (positions
# prefilled so far) and n_blk (blocks held) are the paged engine's
# members of the same per-slot-length-vector class.
DONATION_BLOCKLIST = ("cur_len", "n_new", "kv_len", "n_blk")

# Positional parameter names of the jitted hot mutators, in signature
# order — the analysis donation rule maps `Lowered.args_info` donation
# flags back through these to name what is (and is not) aliased.
STEP_K_ARG_NAMES = ("ids", "cur_len", "cache", "keys", "temps",
                    "limit", "eos")
PREFILL_SLOT_ARG_NAMES = ("ids", "cache", "d_cache", "slot", "row")
SEQ2SEQ_STEP_K_ARG_NAMES = ("state", "out", "n_new", "limit", "eos")
PAGED_STEP_K_ARG_NAMES = ("ids", "cur_len", "kv_len", "pool", "keys",
                          "temps", "limit", "eos", "tables", "n_blk",
                          "free_stack", "free_top", "pending")
PAGED_ADMIT_ARG_NAMES = ("ids", "cur_len", "kv_len", "limit", "eos",
                         "keys", "temps", "tables", "n_blk",
                         "free_stack", "free_top", "slot", "row",
                         "plen", "lim", "eos_id", "key", "temp",
                         "n_need")

# generated tokens/sec per request spans toy CPU engines (~1/s) to
# hardware batch decode (~10k/s)
_TPS_BUCKETS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
                1000.0, 2000.0, 5000.0, 10000.0, 20000.0)


def _tree_nbytes(tree) -> int:
    """Device bytes across a pytree's array leaves — the one leaf-
    accounting rule `kv_cache_bytes` and both engines' fragmentation
    ledgers share (so they can never drift)."""
    return int(sum(leaf.nbytes
                   for leaf in jax.tree_util.tree_leaves(tree)
                   if hasattr(leaf, "nbytes")))


class _Request:
    def __init__(self, rid, slot, prompt_len, max_new, eos):
        self.rid = rid
        self.slot = slot
        self.prompt_len = prompt_len
        self.max_new = max_new
        self.eos = eos
        self.generated: List[int] = []
        self.done = False
        # telemetry timestamps (engine clock): queue entry, slot
        # admission, first emitted token, finish
        self.t_submit: Optional[float] = None
        self.t_admit: Optional[float] = None
        self.t_first: Optional[float] = None
        self.t_finish: Optional[float] = None


class _SlotScheduler:
    """Shared request-lifecycle machinery for both engines: slot
    bookkeeping, the FIFO submit queue, and result harvesting.
    Subclasses provide ``_admit(rid, prompt, max_new, eos)`` (claim
    ``self._free.pop()`` and seed device state) and
    ``_check_prompt(prompt)`` (shape validation), plus their own
    ``step()``."""

    def _init_scheduler(self, slots: int,
                        metrics: Optional[MetricsRegistry] = None):
        self._free = list(range(slots))
        self._waiting: List[Any] = []
        self._by_slot: Dict[int, _Request] = {}
        self._finished: Dict[int, _Request] = {}
        self._next_rid = 0
        # -- telemetry: per-engine registry (pass one in to aggregate
        # several engines or to export alongside other process metrics)
        self._clock = time.perf_counter
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._submit_ts: Dict[int, float] = {}
        # rid -> tenant tag (observability only: stamped on the
        # engine's queue/prefill spans so engine-internal hops inside
        # a fleet trace say whose request they served); dropped with
        # the request (finish/cancel/take_waiting)
        self._tenant_tags: Dict[int, str] = {}
        # engine-LOCAL totals for stats(): registry counters are shared
        # when several engines share a registry, and per-engine fields
        # (notably prefix_hit_rate's denominator) must not conflate
        # another engine's traffic
        self._n_admitted = 0
        self._n_tokens = 0
        self._n_steps = 0
        self._n_syncs = 0
        self._n_preempted = 0
        self._last_util = 0.0
        self.window = int(getattr(self, "window", 1))
        self._m_prefill = self.metrics.histogram(
            "engine_prefill_seconds",
            help="admission latency: prompt prefill + slot seed")
        self._m_decode = self.metrics.histogram(
            "engine_decode_step_seconds",
            help="per-TOKEN decode latency: one window's wall time "
                 "(incl. the host fetch) / tokens it emitted")
        self._m_queue_wait = self.metrics.histogram(
            "engine_queue_wait_seconds",
            help="submit-to-admission wait in the FIFO queue")
        self._m_ttft = self.metrics.histogram(
            "engine_ttft_seconds",
            help="submit to first emitted token, per request")
        self._m_tps = self.metrics.histogram(
            "engine_request_tokens_per_sec", buckets=_TPS_BUCKETS,
            help="generated tokens/sec per finished request")
        self._m_admitted = self.metrics.counter("engine_admitted_total")
        self._m_finished = self.metrics.counter("engine_finished_total")
        self._m_tokens = self.metrics.counter("engine_tokens_total")
        self._m_steps = self.metrics.counter(
            "engine_decode_steps_total",
            help="device decode dispatches (one per window, NOT per "
                 "token)")
        self._m_syncs = self.metrics.counter(
            "engine_host_syncs_total",
            help="device->host result fetches the decode loop paid "
                 "(one per window; 1/window per token when full)")
        self.metrics.gauge(
            "engine_window_size",
            help="in-graph decode ticks per host round trip").set(
            float(self.window))
        # the fragmentation gauges start honest: everything allocated,
        # nothing used (subclasses call _init_scheduler after their KV
        # buffers exist)
        self._set_kv_gauges()

    def _admit_timed(self, rid, *rest, refresh_kv=True):
        """All admissions (direct and queue-drained) route through here:
        times the prefill/seed, stamps the request's lifecycle
        timestamps, and feeds the admission histograms.
        ``refresh_kv=False`` lets a batch drain defer the fragmentation
        ledger rebuild to ONE refresh at its end instead of one full
        KV-tree scan per admitted request."""
        t0 = self._clock()
        # engine_rid, not rid: these spans land inside FLEET request
        # traces whose rid attrs are fleet ids — the replica-local id
        # is a different namespace and must not join against them
        tenant = self._tenant_tags.get(rid)
        with maybe_span("engine_prefill", engine_rid=rid,
                        **({"tenant": tenant} if tenant is not None
                           else {})):
            self._admit(rid, *rest)
        t1 = self._clock()
        self._m_prefill.observe(t1 - t0)
        self._m_admitted.inc()
        self._n_admitted += 1
        req = next((r for r in self._by_slot.values() if r.rid == rid),
                   None)
        if req is not None:
            req.t_submit = self._submit_ts.pop(rid, t0)
            req.t_admit = t1
            self._m_queue_wait.observe(max(t0 - req.t_submit, 0.0))
        if refresh_kv:
            self._set_kv_gauges()   # admission filled a slot's prefix

    def _record_step(self, t0: float, tokens: int = 1,
                     capacity: int = 0) -> float:
        """Per-dispatch bookkeeping after the device fetch; returns
        `now` so harvest loops stamp first-token times without
        re-reading the clock per request.  ``tokens`` is what the
        window emitted (the decode histogram observes wall time /
        tokens — per-TOKEN latency, not raw window time);
        ``capacity`` is ``live_slots * window``, the window's token
        budget, feeding the utilization gauge (speculative ticks can
        exceed 1.0 — that is the acceptance rate showing)."""
        now = self._clock()
        self._m_decode.observe((now - t0) / max(tokens, 1))
        self._m_steps.inc()
        self._n_steps += 1
        self._m_syncs.inc()
        self._n_syncs += 1
        if capacity > 0:
            self._last_util = tokens / capacity
            self.metrics.gauge(
                "engine_window_utilization",
                help="tokens emitted / (live slots * window size) of "
                     "the last dispatch").set(self._last_util)
        self.metrics.gauge("engine_live").set(len(self._by_slot))
        self.metrics.gauge("engine_queue_depth").set(len(self._waiting))
        self.metrics.gauge("engine_occupancy").set(
            len(self._by_slot) / self.slots)
        return now

    def _harvest(self, emitted, t0):
        """Shared post-dispatch harvest for both engines: per-token
        metrics, first-token stamps, EOS truncation (windowed paths
        already mask in-graph — this also covers the speculative path,
        whose accepted run can cross the EOS), finish + device-freeze
        of done slots, queue drain.  ``emitted`` maps every live slot
        to the tokens its request emitted this dispatch."""
        n_emitted = sum(len(t) for t in emitted.values())
        now = self._record_step(t0, tokens=n_emitted,
                                capacity=len(emitted) * self.window)
        out: Dict[int, Any] = {}
        for slot, req in list(self._by_slot.items()):
            toks = emitted[slot]
            if req.eos is not None and req.eos in toks:
                toks = toks[:toks.index(req.eos) + 1]
            req.generated.extend(toks)
            if toks:
                out[req.rid] = list(toks)
                if req.t_first is None:
                    req.t_first = now
                self._m_tokens.inc(len(toks))
                self._n_tokens += len(toks)
            hit_eos = req.eos is not None and req.eos in toks
            if hit_eos or self._out_of_budget(req):
                self._finish(slot, req)
                # stop the device from advancing the freed slot (also
                # what marks it inactive for the next window's scan)
                self._freeze_slot(slot)
        self._drain_queue()
        # after the window's growth/finishes and the re-admissions:
        # the per-window fragmentation sample the ISSUE's ledger asks
        # for (admissions inside _drain_queue already refreshed, but a
        # window with only finishes/growth would otherwise go stale)
        self._set_kv_gauges()
        return out

    def _check_request(self, prompt, max_new_tokens, seed,
                       temperature):
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens}")
        if seed is not None and not self._supports_seed:
            raise ValueError("per-request seed is only meaningful for "
                             "the sampled decoder-only Engine")
        if temperature is not None:
            if not self._supports_temperature:
                raise ValueError(
                    "per-request temperature needs an engine built "
                    "with temperature > 0 (the sampled tick); greedy "
                    "and speculative engines have no override point")
            if not (temperature >= 0):    # also rejects NaN
                raise ValueError(f"temperature must be >= 0, got "
                                 f"{temperature}")
        self._check_prompt(prompt)

    _supports_seed = False
    _supports_temperature = False
    # duck-typed capability flag: a fleet passes its request's tenant
    # tag through to replicas that advertise it (stub/proxy replicas
    # without the flag keep the pre-tenant dispatch signature)
    accepts_tenant = True
    # how this engine admits requests and holds KV: "fixed_slot" (one
    # contiguous buf_len row per slot, admission when a slot frees) or
    # "paged" (block-pool KV + iteration-boundary admission).  Exported
    # on bench lines (schema v12) so trend tooling never compares a
    # paged line against a fixed-slot baseline unknowingly.
    admission_mode = "fixed_slot"

    def _can_admit_direct(self, prompt, max_new_tokens) -> bool:
        """Admission-control hook for :meth:`submit`: True when the
        engine can admit THIS request right now rather than queue it.
        The fixed-slot engines only need a free slot; the paged engine
        also needs block headroom."""
        return bool(self._free)

    def add_request(self, prompt: Sequence[int],
                    max_new_tokens: int,
                    eos_token_id: Optional[int] = None,
                    seed: Optional[int] = None,
                    temperature: Optional[float] = None,
                    tenant: Optional[str] = None) -> int:
        """Claim a slot, seed it, return the request id.  Raises if no
        slot is free (``submit`` queues instead).  ``seed`` names a
        request-intrinsic sampling stream and ``temperature`` overrides
        the engine default for THIS request (0.0 = greedy row) — both
        Engine-sampled-mode only; validated HERE so a bad request fails
        at submission, not mid-harvest in a later ``step()``.
        ``tenant`` is an opaque observability tag stamped on the
        request's engine-side spans (queue/prefill)."""
        if not self._free:
            raise RuntimeError("no free slot; harvest finished "
                               "requests, use submit(), or add "
                               "capacity")
        self._check_request(prompt, max_new_tokens, seed, temperature)
        rid = self._next_rid
        self._next_rid += 1
        if tenant is not None:
            self._tenant_tags[rid] = str(tenant)
        self._submit_ts.setdefault(rid, self._clock())
        self._admit_timed(rid, prompt, max_new_tokens, eos_token_id, seed,
                          temperature)
        return rid

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               eos_token_id: Optional[int] = None,
               seed: Optional[int] = None,
               temperature: Optional[float] = None,
               tenant: Optional[str] = None) -> int:
        """``add_request`` that QUEUES when the engine is full; queued
        requests are admitted automatically as slots free at the end
        of each ``step()`` (arrival order)."""
        self._check_request(prompt, max_new_tokens, seed, temperature)
        if not self._waiting and self._can_admit_direct(prompt,
                                                        max_new_tokens):
            return self.add_request(prompt, max_new_tokens,
                                    eos_token_id, seed, temperature,
                                    tenant=tenant)
        rid = self._next_rid
        self._next_rid += 1
        if tenant is not None:
            self._tenant_tags[rid] = str(tenant)
        self._submit_ts[rid] = self._clock()
        self._waiting.append((rid, list(prompt), max_new_tokens,
                              eos_token_id, seed, temperature))
        self._set_queue_gauge()
        maybe_event("engine_queue", engine_rid=rid,
                    queue_depth=len(self._waiting),
                    **({"tenant": str(tenant)} if tenant is not None
                       else {}))
        return rid

    def _set_queue_gauge(self):
        # the gauge must track every mutation of the waiting queue, not
        # only the end-of-step snapshot: the fleet layer sheds, drains
        # and re-enqueues between steps, and its tests read the gauge
        # against stats()["queue_depth"] after each such move
        self.metrics.gauge("engine_queue_depth").set(len(self._waiting))

    def _drain_queue(self):
        admitted = False
        while self._free and self._waiting:
            self._admit_timed(*self._waiting.pop(0), refresh_kv=False)
            admitted = True
        self._set_queue_gauge()
        if admitted:
            self._set_kv_gauges()   # one ledger rebuild per drain

    def take_waiting(self) -> List[tuple]:
        """Pop and return the whole waiting queue (FIFO order) as
        ``(rid, prompt, max_new_tokens, eos_token_id, seed,
        temperature)`` tuples — the drain/failover hook: a fleet
        re-enqueues these onto surviving replicas.  The popped rids are
        dead to THIS engine (its queue-depth gauge and stats drop
        them); the caller owns re-submission."""
        taken, self._waiting = self._waiting, []
        for rid, *_ in taken:
            self._submit_ts.pop(rid, None)
            self._tenant_tags.pop(rid, None)
        self._set_queue_gauge()
        return taken

    def free_slots(self) -> int:
        """Slots a new request could claim right now (admission-control
        surface for routers that must not grow ``_waiting``)."""
        return len(self._free)

    def queue_depth(self) -> int:
        """Waiting-queue length, without the histogram-summary cost of
        ``stats()`` — the fleet router reads this every dispatch."""
        return len(self._waiting)

    def is_finished(self, rid: int) -> bool:
        """True once ``result(rid)`` will return (harvest surface for a
        fleet polling many replicas)."""
        return rid in self._finished

    def cancel(self, rid: int) -> bool:
        """Abandon a request: a waiting request is dropped from the
        queue, a live one frees its slot and freezes on device (its
        partial tokens are discarded — it never enters ``result()``).
        Returns False for unknown/finished rids.  The fleet layer uses
        this to clear stale work off a replica being drained or
        recovered after a failover."""
        for i, item in enumerate(self._waiting):
            if item[0] == rid:
                del self._waiting[i]
                self._submit_ts.pop(rid, None)
                self._tenant_tags.pop(rid, None)
                self._set_queue_gauge()
                return True
        for slot, req in list(self._by_slot.items()):
            if req.rid == rid:
                self._tenant_tags.pop(rid, None)
                del self._by_slot[slot]
                self._free.append(slot)
                self._freeze_slot(slot)
                self.metrics.gauge("engine_live").set(len(self._by_slot))
                self.metrics.gauge("engine_occupancy").set(
                    len(self._by_slot) / self.slots)
                self._set_kv_gauges()   # the slot's KV row is waste now
                return True
        return False

    def preempt(self, rid: int) -> bool:
        """Evict a request to make room for a higher-priority one (the
        fleet QoS plane's eviction API).  Mechanically this is
        :meth:`cancel` — the slot frees, a paged engine returns the
        victim's KV blocks through the same eager host-side recycling
        path (``_freeze_slot``), so a warmed engine preempts with
        ZERO new traces — but the intent differs and is accounted
        separately: ``preempted`` in :meth:`stats` and the
        ``engine_preempted_total`` counter name evictions, not
        abandonments.  The caller owns re-queueing the victim from its
        prompt (exactness holds: greedy / explicitly-seeded decodes
        are request-intrinsic).  Returns False for unknown/finished
        rids, like ``cancel``."""
        ok = self.cancel(rid)
        if ok:
            self._n_preempted += 1
            self.metrics.counter(
                "engine_preempted_total",
                help="requests evicted mid-decode by the fleet QoS "
                     "plane (slot freed, KV blocks recycled)").inc()
        return ok

    def _finish(self, slot, req):
        req.done = True
        req.t_finish = self._clock()
        self._tenant_tags.pop(req.rid, None)
        del self._by_slot[slot]
        self._free.append(slot)
        self._finished[req.rid] = req
        self._m_finished.inc()
        if req.t_first is not None and req.t_submit is not None:
            self._m_ttft.observe(req.t_first - req.t_submit)
        if req.generated and req.t_admit is not None:
            dur = req.t_finish - req.t_admit
            if dur > 0:
                self._m_tps.observe(len(req.generated) / dur)

    def result(self, rid: int) -> List[int]:
        """Generated tokens (incl. EOS if hit) for a finished request."""
        return list(self._finished[rid].generated)

    def live(self) -> int:
        return len(self._by_slot)

    def compile_census(self) -> Dict[str, str]:
        """The expected-closure compile census: every compilation-
        ledger entry THIS engine's configuration will trace, mapped to
        the lifecycle stage that first traces it (``admission`` /
        ``decode`` trace during :meth:`warmup`; ``register_prefix`` /
        ``prefix_admission`` trace when the prefix pool is actually
        used).  The zero-retrace contract tests compare the ledger's
        observed entries against this — a closure compiling that the
        census does not name is a compile-plane surprise."""
        return {}

    def warmup(self):
        """Pre-compile the engine's admission + decode closures before
        traffic by running ONE throwaway request (1-token prompt, one
        window) end to end.  Every ``Engine`` instance re-jits its own
        closures, so a cold fleet pays N compiles on its first timed
        window unless each replica is warmed first — the PR 4 bench
        gotcha, fixed at the source here (``Fleet.warmup`` fans this
        out over its replicas).  Requires an idle engine; the warmup
        request is scrubbed from ``result()`` but does consume one
        request id and feeds the admission/decode histograms (a
        sampled engine's default rid-keyed streams shift by one —
        pass explicit seeds where exactness against an unwarmed twin
        matters).  Returns ``self``."""
        if self._by_slot or self._waiting:
            raise RuntimeError(
                "warmup() needs an idle engine (no live or queued "
                "requests); warm before traffic")
        rid = self.add_request([0], max_new_tokens=1)
        while not self.is_finished(rid):
            self.step()
        self._finished.pop(rid, None)
        return self

    def _kv_buffers(self):
        """Pytrees of device-resident KV state this engine owns —
        subclasses override; the base scheduler has none."""
        return []

    def kv_cache_bytes(self) -> int:
        """Device bytes held by this engine's KV cache buffers (slot
        caches, draft caches, prefix-pool rows; seq2seq slot state).
        The paged-KV refactor (ROADMAP item 1) is judged against this
        number — it is recomputed from the live buffers, so a layout
        change cannot silently stop being counted."""
        return sum(_tree_nbytes(buf) for buf in self._kv_buffers())

    # -- KV fragmentation ledger (PR 13) -------------------------------
    # ``kv_cache_bytes`` says what the engine ALLOCATED; the paged-KV
    # refactor is really judged on what it WASTES — capacity positions
    # reserved for a slot beyond what its request's cur_len occupies
    # (plus whole rows held by free slots and unregistered pool rows).
    # Everything here is computed from host-side mirrors (the request
    # records' prompt_len + generated, which track the device cur_len
    # exactly) and leaf .nbytes — zero device syncs, zero new prims in
    # any jitted graph.

    def _kv_usage(self):
        """(slot_entries, pool_entries) — subclass hook; each entry
        carries at least ``used_bytes`` / ``kv_waste_bytes`` ints."""
        return [], []

    def kv_fragmentation(self) -> Dict[str, Any]:
        """The full per-slot ledger: allocated / used / wasted bytes,
        the utilization fraction, and one entry per slot (and prefix
        pool row) naming what occupies it — the number ROADMAP item
        1's paged allocator must drive down, per slot so the dashboard
        can see WHERE the waste sits."""
        total = self.kv_cache_bytes()
        slots, pools = self._kv_usage()
        used = min(int(sum(e["used_bytes"] for e in slots)
                       + sum(e["used_bytes"] for e in pools)), total)
        return {"kv_cache_bytes": total,
                "kv_used_bytes": used,
                "kv_waste_bytes": total - used,
                "kv_utilization": (used / total if total else 0.0),
                "slots": slots, "pools": pools}

    def kv_waste_bytes(self) -> int:
        """Allocated-but-unused KV bytes right now (see
        :meth:`kv_fragmentation`)."""
        return self.kv_fragmentation()["kv_waste_bytes"]

    def kv_utilization(self) -> float:
        """Used / allocated KV bytes in [0, 1] (0.0 on an engine with
        no KV state)."""
        return self.kv_fragmentation()["kv_utilization"]

    def _set_kv_gauges(self) -> Dict[str, Any]:
        """Refresh the fragmentation gauges from one ledger snapshot;
        wired at the same mutation points as ``engine_queue_depth``
        (admission, window harvest, cancel), so gauge == stats()
        through submit/step/cancel/eos — the fleet tests pin queue
        depth that way and the serving tests pin these the same way."""
        frag = self.kv_fragmentation()
        self.metrics.gauge(
            "engine_kv_waste_bytes",
            help="allocated-but-unused KV bytes (slot capacity beyond "
                 "cur_len, free slots, empty pool rows) — ROADMAP "
                 "item 1's fragmentation needle").set(
            frag["kv_waste_bytes"])
        self.metrics.gauge(
            "engine_kv_utilization",
            help="used / allocated KV bytes of this engine's "
                 "buffers").set(frag["kv_utilization"])
        return frag

    def stats(self) -> Dict[str, Any]:
        """Scheduler + telemetry snapshot.  The four original counters
        (live/waiting/free/finished) keep their flat-int shape; the
        telemetry additions are occupancy ratios, monotonic totals, and
        latency-histogram summaries ({count, sum, mean, p50, p99} — the
        percentiles are fixed-bucket estimates).  ``queue_depth``
        mirrors ``waiting`` under the name the metrics registry uses.
        The scalar totals are engine-LOCAL; the histogram summaries come
        from ``self.metrics``, so with an explicitly shared registry
        they aggregate every engine sharing it.

        Memory fields (PR 8): ``kv_cache_bytes`` (this engine's KV
        buffers), ``device_live_bytes`` (process-wide
        ``jax.live_arrays`` census, also folded into the registry's
        ``device_live_bytes`` gauge), and HBM occupancy where the
        backend reports real memory stats (``hbm_bytes_in_use`` /
        ``hbm_bytes_limit`` / ``hbm_occupancy``; None on CPU-style
        backends — the live census is the portable signal there).

        Fragmentation fields (PR 13): ``kv_waste_bytes`` /
        ``kv_utilization`` from the same ledger snapshot the
        ``engine_kv_waste_bytes`` / ``engine_kv_utilization`` gauges
        are set from — gauge == stats() by construction (the
        queue-depth pinning discipline)."""
        from .observability import memory as obs_memory
        frag = self._set_kv_gauges()
        kv = frag["kv_cache_bytes"]
        self.metrics.gauge(
            "engine_kv_cache_bytes",
            help="device bytes held by this engine's KV buffers"
        ).set(kv)
        census = obs_memory.record_live_arrays(self.metrics)
        hw = census.get("memory_stats")
        # memory_stats() keys are backend-dependent — guard each one
        occupancy = (hw["bytes_in_use"] / hw["bytes_limit"]
                     if hw and hw.get("bytes_limit")
                     and hw.get("bytes_in_use") is not None else None)
        return {"live": len(self._by_slot),
                "admission_mode": self.admission_mode,
                "kv_cache_bytes": kv,
                "kv_waste_bytes": frag["kv_waste_bytes"],
                "kv_utilization": frag["kv_utilization"],
                "device_live_bytes": census["bytes"],
                "hbm_bytes_in_use": hw.get("bytes_in_use") if hw else None,
                "hbm_bytes_limit": hw.get("bytes_limit") if hw else None,
                "hbm_occupancy": occupancy,
                "waiting": len(self._waiting),
                "free": len(self._free),
                "finished": len(self._finished),
                "slots": self.slots,
                "occupancy": len(self._by_slot) / self.slots,
                "queue_depth": len(self._waiting),
                "admitted": self._n_admitted,
                "preempted": self._n_preempted,
                "tokens_generated": self._n_tokens,
                "decode_steps": self._n_steps,
                "window": self.window,
                "host_syncs": self._n_syncs,
                "window_utilization": self._last_util,
                "tokens_per_sync": (self._n_tokens / self._n_syncs
                                    if self._n_syncs else 0.0),
                "prefill_latency": self._m_prefill.summary(),
                "decode_step_latency": self._m_decode.summary(),
                "queue_wait": self._m_queue_wait.summary(),
                "ttft": self._m_ttft.summary(),
                "request_tokens_per_sec": self._m_tps.summary()}


class Engine(_SlotScheduler):
    def __init__(self, model, params, slots: int, buf_len: int,
                 cache_dtype=None, draft=None, draft_params=None,
                 gamma: int = 4, temperature: float = 0.0,
                 top_k=None, top_p=None, rng=None,
                 prefix_pool: int = 0, prefix_chunk: int = 32,
                 rolling: bool = False, window: int = 1,
                 metrics: Optional[MetricsRegistry] = None):
        """``draft``/``draft_params`` switch ``step()`` to SPECULATIVE
        decoding: one ``spec_iteration`` (models/speculative.py) per
        tick, so every live request advances 1..gamma+1 tokens per
        step while staying token-for-token equal to its solo greedy
        decode.  ``temperature > 0`` samples instead (plain path only;
        combine with a draft for speculative SAMPLING semantics at the
        generate_speculative level).

        ``prefix_pool > 0`` enables PREFIX SHARING (the TPU-native
        answer to vLLM's prefix cache, minus paging — XLA wants static
        shapes, so reuse is row-granular, not block-granular):
        ``register_prefix(tokens)`` prefills a dedicated pool row once;
        any later request whose prompt starts with a registered prefix
        admits by gathering that pool row's KV, running only the
        SUFFIX through ``decode_chunk`` in ``prefix_chunk``-wide
        chunks against the (1, ...) row cache, and scattering the row
        into its slot — skipping the full-buffer prefill forward
        entirely.  Causality makes the
        spliced KV bit-identical to a fresh prefill (positions < L
        never see the suffix), so the solo-decode exactness contract is
        unchanged (pinned in tests/test_serving.py).  The chunk fn
        compiles once; chunks that would run past ``buf_len`` slide
        back and idempotently recompute the overlap.

        ``rolling=True`` serves a sliding-window model (Mistral-class)
        with O(window) KV memory per slot instead of O(buf_len):
        position p lives in ring slot p % W.  Admission prefills the
        prompt into a temporary full-width single-row cache, then
        relayouts the last W positions into the ring (one gather —
        exact, because a sliding-window model's decode never attends
        past W back).  The decode tick is the same ``decode_chunk``
        (L=1 rolling is wired in the model layer).  Incompatible with
        ``draft`` (speculative verify needs L>1 chunks) and
        ``prefix_pool`` (the splice relayout is not wired).

        ``window=K`` runs K decode ticks IN-GRAPH per ``step()``
        (``lax.scan``): the host fetches a ``[slots, K]`` token buffer
        + validity masks once per window instead of one token per
        round trip, so the per-token host-sync tax drops to 1/K.
        EOS/token-limit masking happens in-graph — a finished slot
        freezes mid-window — so the token-for-token exactness
        contract (vs ``generate_cached`` and vs the K=1 engine) is
        unchanged; arrivals are admitted at window boundaries, which
        bounds added TTFT at one window of ticks.  Incompatible with
        ``draft`` (spec_iteration already amortizes the sync over up
        to gamma+1 tokens; composing the two is not wired)."""
        self.model = model
        self.params = params
        self.slots = slots
        self.buf_len = buf_len
        self.draft = draft
        self.draft_params = draft_params
        self.gamma = gamma
        self.temperature = temperature
        self.window = int(window)
        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if self.window > 1 and draft is not None:
            raise NotImplementedError(
                "windowed decode + speculative is not wired "
                "(spec_iteration already amortizes the host sync over "
                "up to gamma+1 tokens per tick); use window=1 with a "
                "draft")
        if temperature > 0.0 and draft is not None:
            raise NotImplementedError(
                "sampled speculative engine ticks are not wired; use "
                "greedy speculation or the plain sampled path")
        self._key = (rng if rng is not None
                     else jax.random.PRNGKey(0))
        # capacity-bounded MoE routing would make a request's tokens
        # depend on what else shares the batch, breaking the
        # batch-independence contract — require dropless experts
        from .parallel.expert_parallel import ExpertParallelMLP
        for mod in model.modules():
            if (isinstance(mod, ExpertParallelMLP)
                    and mod.capacity_factor < mod.n_experts):
                raise ValueError(
                    f"MoE layer with capacity_factor="
                    f"{mod.capacity_factor} < n_experts="
                    f"{mod.n_experts} can drop tokens depending on "
                    f"batch contents; serve dropless "
                    f"(capacity_factor >= n_experts) to keep requests "
                    f"batch-independent")
        if cache_dtype is None:
            # follow generate_cached's default: the table/param dtype
            cache_dtype = (model._table(params).dtype
                           if hasattr(model, "_table")
                           else params["wte"]["weight"].dtype)
        self.rolling = rolling
        if rolling:
            if draft is not None:
                raise NotImplementedError(
                    "rolling + speculative is not wired (verify needs "
                    "L>1 chunks over the ring)")
            if prefix_pool:
                raise NotImplementedError(
                    "rolling + prefix_pool is not wired")
            self._window = getattr(model.cfg, "sliding_window", None)
            if not self._window:
                raise ValueError("rolling=True requires a model with "
                                 "sliding_window set")
            if cache_dtype == jnp.int8:
                # admission prefills with fp attention reads, but the
                # solo rolling decode (step prefill) reads dequantized
                # int8 for layers >= 1 — the caches differ numerically
                # and the token-for-token contract would quietly break
                raise NotImplementedError(
                    "rolling + int8 cache is not wired (admission "
                    "parity with the solo step-prefill path)")
        self.ids = jnp.zeros((slots, buf_len), jnp.int32)
        self.cur_len = jnp.zeros((slots,), jnp.int32)
        self.limit = jnp.zeros((slots,), jnp.int32)   # per-slot final
        # per-slot EOS id for the in-graph window masking; -1 = none.
        # limit doubles as the liveness source: _finish zeroes it, so
        # cur_len < limit is exactly "this slot is serving a request"
        self._eos = jnp.full((slots,), -1, jnp.int32)
        self.cache = (model.init_cache(slots, dtype=cache_dtype,
                                       rolling=True) if rolling
                      else model.init_cache(slots, dtype=cache_dtype))
        self.d_cache = (draft.init_cache(slots, dtype=cache_dtype)
                        if draft is not None else None)
        self._init_scheduler(slots, metrics)

        def _seed(m, ps, cache, slot, row):
            row_cache = m.prefill_cache(ps, row[None, :],
                                        jax.tree_util.tree_map(
                lambda b: jnp.zeros((1,) + b.shape[1:], b.dtype), cache))
            return jax.tree_util.tree_map(
                lambda b, r: lax.dynamic_update_index_in_dim(
                    b, r[0].astype(b.dtype), slot, axis=0),
                cache, row_cache)

        def _prefill_slot(ids, cache, d_cache, slot, row):
            """Seed one slot: prefill the row alone, scatter its cache
            row into the batch cache(s)."""
            cache = _seed(model, params, cache, slot, row)
            if draft is not None:
                d_cache = _seed(draft, draft_params, d_cache, slot, row)
            ids = lax.dynamic_update_index_in_dim(ids, row, slot, axis=0)
            return ids, cache, d_cache

        # donate_argnums on every cache mutator: the KV buffers are
        # scattered/updated in place instead of XLA holding the old
        # multi-GB cache alive next to the new one per dispatch
        self._prefill_slot = instrumented_jit(
            _prefill_slot, "engine._prefill_slot",
            arg_names=PREFILL_SLOT_ARG_NAMES, donate_argnums=(0, 1, 2))

        if rolling:
            W = self._window

            def _prefill_slot_rolling(ids, cache, slot, row, plen):
                """Full-width single-row prefill, then relayout the
                last W positions into the ring (slot j <- the largest
                position p < plen with p % W == j; unwritten slots stay
                zero and the ring validity mask never selects them)."""
                full = model.prefill_cache(
                    params, row[None, :],
                    model.init_cache(1, dtype=cache_dtype))
                j = jnp.arange(W)
                p_j = plen - 1 - ((plen - 1 - j) % W)
                gather = jnp.maximum(p_j, 0)    # p_j < plen <= width

                def relayout(b, fb):
                    ring = jnp.take(fb[0], gather, axis=1)  # width ax 2
                    ring = jnp.where((p_j >= 0)[None, :, None],
                                     ring, 0)
                    return lax.dynamic_update_index_in_dim(
                        b, ring.astype(b.dtype), slot, axis=0)

                cache = jax.tree_util.tree_map(relayout, cache, full)
                ids = lax.dynamic_update_index_in_dim(ids, row, slot,
                                                      axis=0)
                return ids, cache

            self._prefill_slot_rolling = instrumented_jit(
                _prefill_slot_rolling, "engine._prefill_slot_rolling",
                arg_names=("ids", "cache", "slot", "row", "plen"),
                donate_argnums=(0, 1))

        # -- prefix-sharing pool ------------------------------------------
        if prefix_chunk < 1:
            raise ValueError(f"prefix_chunk must be >= 1, got "
                             f"{prefix_chunk}")
        self.prefix_pool = prefix_pool
        self.prefix_chunk = min(prefix_chunk, buf_len)
        self.prefix_hits = 0
        self._prefixes: List[tuple] = []
        if prefix_pool > 0:
            self._pool_cache = model.init_cache(prefix_pool,
                                                dtype=cache_dtype)
            self._pool_d_cache = (draft.init_cache(prefix_pool,
                                                   dtype=cache_dtype)
                                  if draft is not None else None)

            def _seed_pool(pool_cache, d_pool, idx, row):
                pool_cache = _seed(model, params, pool_cache, idx, row)
                if draft is not None:
                    d_pool = _seed(draft, draft_params, d_pool, idx,
                                   row)
                return pool_cache, d_pool

            self._seed_pool = instrumented_jit(
                _seed_pool, "engine._seed_pool",
                arg_names=("pool_cache", "d_pool", "idx", "row"),
                donate_argnums=(0, 1))

            # splice = one row gather from the pool, K suffix chunks on
            # the (1, ...) ROW cache (not the whole multi-slot tree —
            # no full-cache round trip per chunk), one scatter into the
            # slot.  Shared by target and draft caches.
            def _take_row(cache, idx):
                return jax.tree_util.tree_map(
                    lambda b: lax.dynamic_index_in_dim(
                        b, idx, 0, keepdims=True), cache)

            def _put_row(cache, rc, slot):
                return jax.tree_util.tree_map(
                    lambda b, r: lax.dynamic_update_index_in_dim(
                        b, r[0].astype(b.dtype), slot, axis=0),
                    cache, rc)

            # _take_row must NOT donate: the pool rows are the shared
            # prefix capital, reused by every later matching admission
            self._take_row = instrumented_jit(
                _take_row, "engine._take_row",
                arg_names=("cache", "idx"))
            self._put_row = instrumented_jit(
                _put_row, "engine._put_row",
                arg_names=("cache", "rc", "slot"), donate_argnums=(0,))
            self._chunk_row = {
                "cache": instrumented_jit(
                    lambda rc, t, o: model.decode_chunk(
                        params, t, jnp.full((1,), o, jnp.int32),
                        rc)[1],
                    "engine._chunk_row",
                    arg_names=("rc", "toks", "off"))}
            if draft is not None:
                self._chunk_row["d_cache"] = instrumented_jit(
                    lambda rc, t, o: draft.decode_chunk(
                        draft_params, t, jnp.full((1,), o, jnp.int32),
                        rc)[1],
                    "engine._chunk_row_draft",
                    arg_names=("rc", "toks", "off"))

        if draft is not None:
            from .models.speculative import spec_iteration

            def _sstep(ids, cur_len, limit, t_cache, d_cache):
                ids2, new_len, t_cache, d_cache, _ = spec_iteration(
                    model, params, draft, draft_params, ids, cur_len,
                    limit, ids, t_cache, d_cache, gamma)
                return ids2, new_len, t_cache, d_cache

            # NOT cur_len (argnum 1): donating it corrupts the
            # executable when reloaded from the persistent XLA:CPU
            # compilation cache (jax 0.4.37 AOT quirk — fresh compiles
            # are fine, cache loads decode garbage; pinned by running
            # the serving suite twice against one cache dir).  The
            # multi-GB wins are the two cache trees; ids rides along.
            self._sstep = instrumented_jit(
                _sstep, "engine._sstep",
                arg_names=("ids", "cur_len", "limit", "t_cache",
                           "d_cache"),
                donate_argnums=(0, 3, 4))

        K = self.window

        def _step_k(ids, cur_len, cache, keys, temps, limit, eos):
            """K decode ticks in-graph (``lax.scan``) — ONE host round
            trip per window.  The carry holds a per-slot active mask:
            a slot that emits its EOS or reaches its token limit
            freezes for the rest of the window (ids/cur_len/cache/RNG
            stream stop advancing), so every request's tokens are
            exactly its solo decode regardless of K.  Emits the
            ``[slots, K]`` token buffer + validity mask the host
            unpacks once."""

            def tick(carry, _):
                ids, cur_len, cache, keys, alive = carry
                pos = jnp.maximum(cur_len - 1, 0)
                tok_in = jnp.take_along_axis(
                    ids, jnp.clip(pos, 0, buf_len - 1)[:, None], axis=1)
                # frozen/garbage slots recompute the KV their position
                # already holds (same token, same pos -> same values):
                # the write is idempotent, so the cache needs no mask
                h, cache = model.decode_chunk(params, tok_in, pos,
                                              cache)
                logits = _head_logits(model, params, h)[:, 0]
                if temperature > 0.0:
                    from .models import sampling as smp
                    # PER-SLOT key streams: each request draws from its
                    # own fold_in(base, seed) chain, advanced once per
                    # its OWN decode step (frozen slots hold their
                    # key), so its tokens depend only on its seed and
                    # step count — never on co-tenants, arrival timing,
                    # or the window size (batch-independent sampling)
                    split = jax.vmap(
                        lambda k: jax.random.split(k, 2))(keys)
                    new_keys, subs = split[:, 0], split[:, 1]
                    # per-request temperature: rows pre-scale their
                    # logits (sample_token at T=1 then filters — same
                    # semantics as a static temperature); a per-request
                    # T=0 row falls back to argmax via the where
                    safe_t = jnp.where(temps > 0, temps, 1.0)
                    scaled = (logits.astype(jnp.float32)
                              / safe_t[:, None])
                    sampled = jax.vmap(
                        lambda k, l: smp.sample_token(
                            k, l, 1.0, top_k=top_k,
                            top_p=top_p))(subs, scaled).astype(jnp.int32)
                    greedy = jnp.argmax(logits,
                                        axis=-1).astype(jnp.int32)
                    nxt = jnp.where(temps > 0, sampled, greedy)
                    keys = jnp.where(alive[:, None], new_keys, keys)
                else:
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                can = alive & (cur_len < buf_len)
                ids = jax.vmap(
                    lambda row, p, t, c: row.at[p].set(
                        jnp.where(c, t, row[p])))(
                    ids, jnp.minimum(cur_len, buf_len - 1), nxt, can)
                new_len = jnp.where(can, cur_len + 1, cur_len)
                emitted = alive
                hit_eos = (eos >= 0) & (nxt == eos)
                alive = alive & ~hit_eos & (new_len < limit)
                return ((ids, new_len, cache, keys, alive),
                        (nxt, emitted))

            alive0 = cur_len < limit
            (ids, cur_len, cache, keys, _), (toks, valid) = lax.scan(
                tick, (ids, cur_len, cache, keys, alive0), None,
                length=K)
            return ids, cur_len, cache, keys, toks.T, valid.T

        # donate ids + the KV cache + the key table, NOT cur_len: the
        # per-slot length vector is the argnum class whose donation
        # corrupts executables reloaded from the persistent XLA:CPU
        # compilation cache (see _sstep below), and donating a
        # (slots,)-int32 buys nothing anyway
        self._step_k = instrumented_jit(
            _step_k, "engine._step_k", arg_names=STEP_K_ARG_NAMES,
            donate_argnums=(0, 2, 3))
        self._slot_keys = jax.vmap(
            lambda i: jax.random.fold_in(self._key, i))(
            jnp.arange(slots))
        self._slot_temp = jnp.full((slots,), float(temperature),
                                   jnp.float32)
        # the prefix-pool/draft allocations above postdate
        # _init_scheduler's first ledger snapshot — refresh so the
        # gauges cover the full allocation from birth
        self._set_kv_gauges()

    # -- request lifecycle -------------------------------------------------
    def register_prefix(self, tokens: Sequence[int]) -> int:
        """Prefill ``tokens`` into a prefix-pool row once; later
        prompts starting with them admit via KV splice + suffix-only
        prefill.  Returns the pool index.  Requires ``prefix_pool``
        capacity at construction."""
        if self.prefix_pool == 0:
            raise RuntimeError("Engine built with prefix_pool=0")
        if len(self._prefixes) >= self.prefix_pool:
            raise RuntimeError(f"prefix pool full "
                               f"({self.prefix_pool} rows)")
        self._check_prompt(tokens)
        idx = len(self._prefixes)
        row = np.zeros((self.buf_len,), np.int32)
        row[:len(tokens)] = tokens
        self._pool_cache, self._pool_d_cache = self._seed_pool(
            self._pool_cache, self._pool_d_cache, idx,
            jnp.asarray(row))
        self._prefixes.append(tuple(int(t) for t in tokens))
        self._set_kv_gauges()           # the pool row is occupied now
        return idx

    def _match_prefix(self, prompt):
        """(pool_idx, L) of the longest registered prefix the prompt
        starts with, or (None, 0)."""
        best, best_len = None, 0
        pt = tuple(int(t) for t in prompt)
        for i, pref in enumerate(self._prefixes):
            if len(pref) > best_len and len(pref) <= len(pt) \
                    and pt[:len(pref)] == pref:
                best, best_len = i, len(pref)
        return best, best_len

    @property
    def _supports_seed(self):
        # mirrors _supports_temperature: a seed names a per-request
        # sampling stream, which only exists on the sampled tick — the
        # greedy tick never draws and the speculative engine pins its
        # own draft/verify streams, so a seed there would be silently
        # ignored; reject it at submission instead (ADVICE r5)
        return self.temperature > 0.0 and self.draft is None

    @property
    def _supports_temperature(self):
        # the sampled tick graph only exists when the engine was built
        # sampled; a greedy engine has no per-request override point
        return self.temperature > 0.0 and self.draft is None

    def _admit(self, rid, prompt, max_new_tokens, eos_token_id,
               seed=None, temperature=None):
        slot = self._free.pop()
        self._slot_temp = self._slot_temp.at[slot].set(
            float(self.temperature if temperature is None
                  else temperature))
        # sampling stream: domain-separated so an explicit seed can
        # never collide with an auto rid.  Default (seed=None) keys off
        # the rid — deterministic given the SUBMISSION ORDER; an
        # explicit seed gives a request-intrinsic stream independent of
        # everything else (the batch-independence contract)
        base = jax.random.fold_in(self._key, 0 if seed is None else 1)
        self._slot_keys = self._slot_keys.at[slot].set(
            jax.random.fold_in(base, rid if seed is None else seed))
        row = np.zeros((self.buf_len,), np.int32)
        row[:len(prompt)] = prompt
        pidx, L = (self._match_prefix(prompt) if self._prefixes
                   else (None, 0))
        if self.rolling:
            self.ids, self.cache = self._prefill_slot_rolling(
                self.ids, self.cache, slot, jnp.asarray(row),
                len(prompt))
        elif pidx is not None:
            # splice: gather the pool row, run only the suffix
            # [L, prompt_len) through decode_chunk on that row, scatter
            # it into the slot
            self.prefix_hits += 1
            self.metrics.counter("engine_prefix_hits_total").inc()
            C = self.prefix_chunk
            for attr, chunk_fn in self._chunk_row.items():
                pool = (self._pool_cache if attr == "cache"
                        else self._pool_d_cache)
                rc = self._take_row(pool, pidx)
                off = L
                while off < len(prompt):
                    # slide the last chunk back instead of shrinking
                    # it: one compiled width, overlap recompute is
                    # idempotent
                    start = min(off, self.buf_len - C)
                    toks = jnp.asarray(row[None, start:start + C])
                    rc = chunk_fn(rc, toks, start)
                    off = start + C
                setattr(self, attr,
                        self._put_row(getattr(self, attr), rc, slot))
            self.ids = self.ids.at[slot].set(jnp.asarray(row))
        else:
            self.ids, self.cache, self.d_cache = self._prefill_slot(
                self.ids, self.cache, self.d_cache, slot,
                jnp.asarray(row))
        self.cur_len = self.cur_len.at[slot].set(len(prompt))
        self.limit = self.limit.at[slot].set(
            min(len(prompt) + max_new_tokens, self.buf_len))
        self._eos = self._eos.at[slot].set(
            -1 if eos_token_id is None else int(eos_token_id))
        self._by_slot[slot] = _Request(rid, slot, len(prompt),
                                       max_new_tokens, eos_token_id)

    def _check_prompt(self, prompt):
        if len(prompt) < 1 or len(prompt) >= self.buf_len:
            raise ValueError(f"prompt length {len(prompt)} not in "
                             f"[1, {self.buf_len})")

    def step(self) -> Dict[int, Any]:
        """One batched decode dispatch — a WINDOW of ``window``
        in-graph decode ticks.  Returns {request_id: [tokens]} for
        every live request that emitted this window (1..window tokens
        on the plain path, 1..gamma+1 under speculative decoding);
        finished requests free their slot (their last token, EOS
        included, is still reported and recorded) and queued arrivals
        admit at the window boundary."""
        if not self._by_slot and self._waiting:
            # cancel() can free every slot without draining the queue
            # (unlike _finish, which drains via _harvest); admit here so
            # queued requests never strand on an idle engine
            self._drain_queue()
        if not self._by_slot:
            return {}
        t0 = self._clock()
        live = list(self._by_slot)
        with maybe_span("engine_window_decode", window=self.window,
                        live=len(live)):
            if self.draft is not None:
                old_len = np.asarray(self.cur_len)
                (self.ids, self.cur_len, self.cache,
                 self.d_cache) = self._sstep(self.ids, self.cur_len,
                                             self.limit, self.cache,
                                             self.d_cache)
                new_len = np.asarray(self.cur_len)
                rows = np.asarray(self.ids)
                emitted = {slot: [int(t) for t in
                                  rows[slot,
                                       old_len[slot]:new_len[slot]]]
                           for slot in self._by_slot}
            else:
                (self.ids, self.cur_len, self.cache, self._slot_keys,
                 toks, valid) = self._step_k(self.ids, self.cur_len,
                                             self.cache,
                                             self._slot_keys,
                                             self._slot_temp,
                                             self.limit, self._eos)
                # THE host sync: one fetch per window, not per token
                toks_h, valid_h = jax.device_get((toks, valid))
                emitted = {slot: [int(t) for t, v
                                  in zip(toks_h[slot], valid_h[slot])
                                  if v]
                           for slot in live}
        return self._harvest(emitted, t0)

    def _out_of_budget(self, req):
        return (len(req.generated) >= req.max_new
                or req.prompt_len + len(req.generated) >= self.buf_len)

    def _freeze_slot(self, slot):
        self.limit = self.limit.at[slot].set(0)

    def _kv_buffers(self):
        bufs = [self.cache]
        for attr in ("d_cache", "_pool_cache", "_pool_d_cache"):
            buf = getattr(self, attr, None)
            if buf is not None:
                bufs.append(buf)
        return bufs

    def _kv_usage(self):
        """Per-slot / per-pool-row KV occupancy, from host mirrors
        only: a live request's used positions are ``prompt_len +
        len(generated)`` (the exact host twin of the device
        ``cur_len``), capped at the slot's position capacity —
        ``buf_len``, or the ring width for a rolling engine (the ring
        never holds more than W positions, so a long request *fully*
        uses its O(window) row).  Slot and draft caches share the same
        position axis, so one per-position byte price covers both."""
        cap = self._window if self.rolling else self.buf_len
        slot_bytes = _tree_nbytes(self.cache)
        if getattr(self, "d_cache", None) is not None:
            slot_bytes += _tree_nbytes(self.d_cache)
        per_pos = slot_bytes / (self.slots * cap) if self.slots else 0.0
        row_bytes = int(round(per_pos * cap))
        slots = []
        for slot in range(self.slots):
            req = self._by_slot.get(slot)
            used_pos = (min(req.prompt_len + len(req.generated), cap)
                        if req is not None else 0)
            used_b = int(round(per_pos * used_pos))
            slots.append({"slot": slot,
                          "rid": req.rid if req is not None else None,
                          "used_positions": used_pos,
                          "capacity_positions": cap,
                          "used_bytes": used_b,
                          "kv_waste_bytes": row_bytes - used_b})
        pools = []
        if getattr(self, "prefix_pool", 0):
            pool_bytes = _tree_nbytes(self._pool_cache)
            if self._pool_d_cache is not None:
                pool_bytes += _tree_nbytes(self._pool_d_cache)
            per_pool_pos = pool_bytes / (self.prefix_pool * self.buf_len)
            pool_row = int(round(per_pool_pos * self.buf_len))
            for i in range(self.prefix_pool):
                used_pos = (min(len(self._prefixes[i]), self.buf_len)
                            if i < len(self._prefixes) else 0)
                used_b = int(round(per_pool_pos * used_pos))
                pools.append({"row": i, "used_positions": used_pos,
                              "capacity_positions": self.buf_len,
                              "used_bytes": used_b,
                              "kv_waste_bytes": pool_row - used_b})
        return slots, pools

    def compile_census(self) -> Dict[str, str]:
        census: Dict[str, str] = {}
        census["engine._prefill_slot_rolling" if self.rolling
               else "engine._prefill_slot"] = "admission"
        census["engine._sstep" if self.draft is not None
               else "engine._step_k"] = "decode"
        if self.prefix_pool > 0:
            census["engine._seed_pool"] = "register_prefix"
            census["engine._take_row"] = "prefix_admission"
            census["engine._put_row"] = "prefix_admission"
            census["engine._chunk_row"] = "prefix_admission"
            if self.draft is not None:
                census["engine._chunk_row_draft"] = "prefix_admission"
        return census

    def stats(self) -> Dict[str, Any]:
        """Base snapshot plus prefix-cache effectiveness: splice
        admissions so far and the hit rate over all admissions (0.0 on
        an engine with no admissions yet or no prefix pool)."""
        s = super().stats()
        s["prefix_hits"] = self.prefix_hits
        s["prefix_hit_rate"] = (self.prefix_hits / s["admitted"]
                                if s["admitted"] else 0.0)
        return s


class PagedEngine(_SlotScheduler):
    """Paged-KV continuous-batching engine (ROADMAP item 1): the
    fixed-slot ``Engine``'s admission/KV architecture replaced by a
    BLOCK-POOL cache plus iteration-level scheduling, in the
    PagedAttention (arXiv:2309.06180) / ORCA shape adapted to XLA's
    static-shape world.

    - KV lives in ONE pool of ``num_blocks`` fixed-size blocks per
      cache leaf (``(num_blocks, Hkv, block_size, D)``); each slot owns
      a per-request BLOCK TABLE — a static-shape ``(max_blocks,)``
      int32 row of physical block ids (padded; ``n_blk`` says how many
      are real).  A request reserves ``ceil(min(prompt+max_new,
      buf_len) / block_size)`` blocks at admission (so an admitted
      request can never deadlock mid-decode) and the device RECYCLES
      them in-graph the tick it hits eos/max-tokens — not at the
      window boundary, not at the next host sync.
    - Prefill is CHUNKED and interleaved with decode inside the same
      ``lax.scan`` window: an admitted slot advances ``kv_len`` by
      ``prefill_chunk`` positions per tick (under a ``lax.cond`` so a
      decode-only steady state never pays the chunk-width forward)
      until it is decode-ready, while other slots keep decoding.
    - Admission happens at the ITERATION boundary: ``step()`` stages
      the waiting queue's head-of-line requests into a static-shape
      ``pending`` pack, and each scan tick admits at most one of them
      into a free slot the moment the block budget allows — a request
      freed at tick t can hand its blocks to the next request at tick
      t+1 of the SAME window.

    Everything stays in-graph with static shapes: the gather
    (``pool[tables]`` -> a dense per-slot view fed to the models'
    unmodified ``decode_chunk``), the column scatter back into the
    pool, the free-stack push/pop, and the admission writes — so the
    zero-retrace steady-state contract holds exactly as for the fixed
    engine (one trace per entry at warmup, delta == 0 forever after).
    Causality makes the dense view exact: positions a slot has not
    written (or stale junk from a previous tenant of a recycled block)
    sit at indices > its current position and the models' causal mask
    zeroes them out of every softmax, so when ``block_size`` divides
    ``buf_len`` the attention computation is bit-identical to the
    fixed-slot engine's and the token-for-token exactness contract
    (vs ``generate_cached`` and vs ``Engine``) carries over — greedy
    AND explicit-seed sampled (same per-request fold_in streams,
    advanced once per own decode tick).

    Donation: ``ids``, the block pool and the RNG key table are
    donated; ``cur_len``/``kv_len``/``n_blk`` are per-slot length
    vectors on ``DONATION_BLOCKLIST`` (the PR 2 compile-cache
    corruption class) and the scheduler vectors (tables, free stack,
    limits) are cheap enough that donating them buys nothing.

    Not wired (use ``Engine``): speculative drafts, rolling windows,
    prefix pools — the splice/ring relayouts are row-granular and the
    paged pool is block-granular."""

    admission_mode = "paged"

    def __init__(self, model, params, slots: int, buf_len: int,
                 block_size: int = 16, num_blocks: Optional[int] = None,
                 prefill_chunk: int = 16, cache_dtype=None,
                 temperature: float = 0.0, top_k=None, top_p=None,
                 rng=None, window: int = 1,
                 metrics: Optional[MetricsRegistry] = None):
        """``block_size`` is the KV positions per block (pick it so it
        divides ``buf_len``: the dense gather width is then exactly
        ``buf_len`` and the attention math is bit-identical to the
        fixed-slot engine; any size stays exact via the causal mask,
        but a non-divisor pads the gather).  ``num_blocks`` is the pool
        capacity (default ``slots * ceil(buf_len / block_size)`` — the
        fixed-slot worst case; the paged win comes from setting it
        LOWER than that and admitting more slots, since real mixed
        traffic rarely reserves full buffers).  ``prefill_chunk`` is
        the positions one prefill tick advances."""
        self.model = model
        self.params = params
        self.slots = slots
        self.buf_len = buf_len
        self.temperature = temperature
        self.window = int(window)
        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got "
                             f"{block_size}")
        if prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got "
                             f"{prefill_chunk}")
        self.block_size = int(block_size)
        self.prefill_chunk = int(min(prefill_chunk, buf_len))
        # static max-blocks padding: every block table is this wide
        self.max_blocks = -(-buf_len // self.block_size)
        self.num_blocks = (int(num_blocks) if num_blocks is not None
                           else slots * self.max_blocks)
        if self.num_blocks < self.max_blocks:
            raise ValueError(
                f"num_blocks={self.num_blocks} cannot hold even one "
                f"full-length request ({self.max_blocks} blocks of "
                f"{self.block_size})")
        self._key = (rng if rng is not None
                     else jax.random.PRNGKey(0))
        # same dropless-MoE batch-independence requirement as Engine
        from .parallel.expert_parallel import ExpertParallelMLP
        for mod in model.modules():
            if (isinstance(mod, ExpertParallelMLP)
                    and mod.capacity_factor < mod.n_experts):
                raise ValueError(
                    f"MoE layer with capacity_factor="
                    f"{mod.capacity_factor} < n_experts="
                    f"{mod.n_experts} can drop tokens depending on "
                    f"batch contents; serve dropless "
                    f"(capacity_factor >= n_experts) to keep requests "
                    f"batch-independent")
        if cache_dtype is None:
            cache_dtype = (model._table(params).dtype
                           if hasattr(model, "_table")
                           else params["wte"]["weight"].dtype)
        # the pool: re-leaf the model's own (1, H, S, D) cache template
        # as (num_blocks, H, block_size, D) — one tree_map, so int8
        # scale sidecars and any future leaf page identically (every
        # leaf's position axis is axis 2 by the models/_cache contract)
        template = model.init_cache(1, dtype=cache_dtype)
        NB, bs = self.num_blocks, self.block_size

        def _pool_leaf(leaf):
            if leaf.ndim != 4:
                raise NotImplementedError(
                    "paged KV needs (B, H, S, D)-shaped cache leaves")
            return jnp.zeros((NB, leaf.shape[1], bs) + leaf.shape[3:],
                             leaf.dtype)

        self.pool = jax.tree_util.tree_map(_pool_leaf, template)
        MB = self.max_blocks
        self.ids = jnp.zeros((slots, buf_len), jnp.int32)
        self.cur_len = jnp.zeros((slots,), jnp.int32)
        # prompt positions whose KV is already written; a slot is
        # decode-ready when kv_len == cur_len - 1 (the decode tick
        # itself computes position cur_len - 1)
        self.kv_len = jnp.zeros((slots,), jnp.int32)
        self.limit = jnp.zeros((slots,), jnp.int32)
        self._eos = jnp.full((slots,), -1, jnp.int32)
        self.tables = jnp.zeros((slots, MB), jnp.int32)
        self.n_blk = jnp.zeros((slots,), jnp.int32)
        # LIFO free stack: free_stack[:free_top] are the free block ids
        self.free_stack = jnp.arange(NB, dtype=jnp.int32)
        self.free_top = jnp.int32(NB)
        # host mirrors (refreshed from the one per-window fetch /
        # mutated by the host-side admission paths): block headroom for
        # admission control and per-slot holdings for the ledger
        self._free_top_h = NB
        self._slot_nblk_h: Dict[int, int] = {}
        self._stream_keys_memo: Dict[int, Any] = {}
        self._n_midwindow = 0
        self._slot_keys = jax.vmap(
            lambda i: jax.random.fold_in(self._key, i))(
            jnp.arange(slots))
        self._slot_temp = jnp.full((slots,), float(temperature),
                                   jnp.float32)
        self._init_scheduler(slots, metrics)
        self.metrics.gauge(
            "engine_kv_blocks_total",
            help="KV pool capacity in blocks").set(float(NB))

        S_d = MB * bs            # dense gather width per slot
        C = self.prefill_chunk
        K = self.window
        n_slots = slots

        def _gather_dense(pool, tables):
            """pool leaves -> per-slot dense (slots, H, MB*bs, D)
            views through the block tables (stale/padded table entries
            gather junk that the causal mask drops)."""
            def g(leaf):
                d = leaf[tables]                # (slots, MB, H, bs, D)
                d = d.transpose(0, 2, 1, 3, 4)
                return d.reshape(n_slots, leaf.shape[1], S_d,
                                 leaf.shape[3])
            return jax.tree_util.tree_map(g, pool)

        def _scatter_cols(pool, dense, tables, q, gate):
            """Write the freshly computed columns ``q`` (slots, L) of
            the dense views back into their physical blocks.  Gated:
            lanes with ``gate`` False scatter to index num_blocks and
            ``mode='drop'`` discards them — a freed block that was
            already re-handed to another request must never see a
            stale write."""
            blk = jnp.clip(q // bs, 0, MB - 1)
            phys = jnp.take_along_axis(tables, blk, axis=1)
            phys = jnp.where(gate, phys, NB).reshape(-1)
            off = (q % bs).reshape(-1)
            qc = jnp.clip(q, 0, S_d - 1)

            def s(pl, dl):
                H, Dp = pl.shape[1], pl.shape[3]
                idx = jnp.broadcast_to(
                    qc[:, None, :, None],
                    (n_slots, H, qc.shape[1], Dp))
                cols = jnp.take_along_axis(dl, idx, axis=2)
                vals = cols.transpose(0, 2, 1, 3).reshape(-1, H, Dp)
                return pl.at[phys, :, off, :].set(vals, mode="drop")

            return jax.tree_util.tree_map(s, pool, dense)

        def _pop_blocks(free_stack, free_top, n_need):
            """Top n_need entries of the free stack as a padded
            (max_blocks,) table row (static shape; unpopped lanes 0)."""
            j = jnp.arange(MB)
            src = jnp.clip(free_top - 1 - j, 0, NB - 1)
            return jnp.where(j < n_need, free_stack[src], 0)

        def _paged_step_k(ids, cur_len, kv_len, pool, keys, temps,
                          limit, eos, tables, n_blk, free_stack,
                          free_top, pending):
            """K continuous-batching ticks in-graph: each tick runs
            admission (at most one staged request into a freed slot,
            block budget permitting), one chunked-prefill advance for
            every not-yet-ready slot (under a cond — decode-only
            steady state skips it), one decode tick for every ready
            slot, and the in-graph block recycling of slots that died
            this tick.  Emits the (slots, K) token/validity buffers
            plus a (K,) admitted-slot vector the host replays."""
            p_count = pending["count"]

            def tick(carry, _):
                (ids, cur_len, kv_len, pool, keys, temps, limit, eos,
                 tables, n_blk, free_stack, free_top, p_next) = carry
                # -- admission at the iteration boundary --------------
                i = jnp.clip(p_next, 0, n_slots - 1)
                n_need = pending["n_need"][i]
                free_slot = limit == 0
                can = ((p_next < p_count) & jnp.any(free_slot)
                       & (free_top >= n_need))
                slot = jnp.argmax(free_slot).astype(jnp.int32)
                onehot = (jnp.arange(n_slots) == slot) & can
                trow = _pop_blocks(free_stack, free_top, n_need)
                tables = jnp.where(onehot[:, None], trow[None, :],
                                   tables)
                free_top = free_top - jnp.where(can, n_need, 0)
                ids = jnp.where(onehot[:, None],
                                pending["ids"][i][None, :], ids)
                cur_len = jnp.where(onehot, pending["len"][i], cur_len)
                kv_len = jnp.where(onehot, 0, kv_len)
                limit = jnp.where(onehot, pending["limit"][i], limit)
                eos = jnp.where(onehot, pending["eos"][i], eos)
                temps = jnp.where(onehot, pending["temps"][i], temps)
                keys = jnp.where(onehot[:, None],
                                 pending["keys"][i][None, :], keys)
                n_blk = jnp.where(onehot, n_need, n_blk)
                p_next = p_next + can.astype(jnp.int32)
                adm = jnp.where(can, slot, -1)

                # -- chunked prefill, interleaved with decode ---------
                alive = cur_len < limit
                needs_pf = alive & (kv_len < cur_len - 1)

                def do_prefill(pool, kv_len):
                    pos0 = jnp.clip(kv_len, 0, buf_len - 1)
                    qs = pos0[:, None] + jnp.arange(C)[None, :]
                    toks = jnp.take_along_axis(
                        ids, jnp.clip(qs, 0, buf_len - 1), axis=1)
                    dense = _gather_dense(pool, tables)
                    _, dense = model.decode_chunk(params, toks, pos0,
                                                  dense)
                    gate = (needs_pf[:, None]
                            & (qs < (cur_len - 1)[:, None]))
                    pool2 = _scatter_cols(pool, dense, tables, qs,
                                          gate)
                    kv2 = jnp.where(
                        needs_pf,
                        jnp.minimum(kv_len + C, cur_len - 1), kv_len)
                    return pool2, kv2

                pool, kv_len = lax.cond(
                    jnp.any(needs_pf), do_prefill,
                    lambda pool, kv_len: (pool, kv_len), pool, kv_len)

                # -- decode tick for every decode-ready slot ----------
                # re-check against the POST-prefill kv_len: a slot
                # whose last prefill chunk landed this tick decodes in
                # the same tick (the gather below re-reads the freshly
                # scattered pool), so prefill->decode costs no bubble
                dec_ok = alive & (kv_len >= cur_len - 1)
                pos = jnp.maximum(cur_len - 1, 0)
                tok_in = jnp.take_along_axis(
                    ids, jnp.clip(pos, 0, buf_len - 1)[:, None],
                    axis=1)
                dense = _gather_dense(pool, tables)
                h, dense = model.decode_chunk(params, tok_in, pos,
                                              dense)
                pool = _scatter_cols(pool, dense, tables, pos[:, None],
                                     dec_ok[:, None])
                logits = _head_logits(model, params, h)[:, 0]
                if temperature > 0.0:
                    from .models import sampling as smp
                    # identical stream discipline to Engine._step_k:
                    # per-request keys advance once per OWN decode tick
                    # (not while prefilling, not after death), so the
                    # sampled output is batch-independent and equal to
                    # the fixed-slot engine's token for token
                    split = jax.vmap(
                        lambda k: jax.random.split(k, 2))(keys)
                    new_keys, subs = split[:, 0], split[:, 1]
                    safe_t = jnp.where(temps > 0, temps, 1.0)
                    scaled = (logits.astype(jnp.float32)
                              / safe_t[:, None])
                    sampled = jax.vmap(
                        lambda k, l: smp.sample_token(
                            k, l, 1.0, top_k=top_k,
                            top_p=top_p))(subs,
                                          scaled).astype(jnp.int32)
                    greedy = jnp.argmax(logits,
                                        axis=-1).astype(jnp.int32)
                    nxt = jnp.where(temps > 0, sampled, greedy)
                    keys = jnp.where(dec_ok[:, None], new_keys, keys)
                else:
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                # alive => cur_len < limit <= buf_len, so the write is
                # never out of row (unlike Engine there is no separate
                # can mask — limit already caps at buf_len)
                ids = jax.vmap(
                    lambda row, p, t, c: row.at[p].set(
                        jnp.where(c, t, row[p])))(
                    ids, jnp.minimum(cur_len, buf_len - 1), nxt,
                    dec_ok)
                new_len = jnp.where(dec_ok, cur_len + 1, cur_len)
                # the decode scatter just wrote KV at cur_len-1, so
                # the coverage counter advances with it — without this
                # the next tick would re-"prefill" an already-written
                # position and decode only every other tick
                kv_len = jnp.where(dec_ok, cur_len, kv_len)

                # -- in-graph block recycling on eos/limit ------------
                hit_eos = dec_ok & (eos >= 0) & (nxt == eos)
                died = dec_ok & (hit_eos | (new_len >= limit))
                freed = jnp.where(died, n_blk, 0)
                offs = jnp.cumsum(freed) - freed     # exclusive scan
                jj = jnp.arange(MB)[None, :]
                push = died[:, None] & (jj < n_blk[:, None])
                dest = jnp.where(push,
                                 free_top + offs[:, None] + jj, NB)
                free_stack = free_stack.at[dest.reshape(-1)].set(
                    tables.reshape(-1), mode="drop")
                free_top = free_top + jnp.sum(freed)
                limit = jnp.where(died, 0, limit)
                n_blk = jnp.where(died, 0, n_blk)

                return ((ids, new_len, kv_len, pool, keys, temps,
                         limit, eos, tables, n_blk, free_stack,
                         free_top, p_next),
                        (nxt, dec_ok, adm))

            carry = (ids, cur_len, kv_len, pool, keys, temps, limit,
                     eos, tables, n_blk, free_stack, free_top,
                     jnp.int32(0))
            carry, (toks, valid, adm) = lax.scan(tick, carry, None,
                                                 length=K)
            (ids, cur_len, kv_len, pool, keys, temps, limit, eos,
             tables, n_blk, free_stack, free_top, _) = carry
            return (ids, cur_len, kv_len, pool, keys, temps, limit,
                    eos, tables, n_blk, free_stack, free_top,
                    toks.T, valid.T, adm)

        # donate ids + the pool + the key table; cur_len/kv_len/n_blk
        # are DONATION_BLOCKLIST length vectors (PR 2 compile-cache
        # corruption class) and the rest is read-mostly scheduler state
        self._paged_step_k = instrumented_jit(
            _paged_step_k, "engine._paged_step_k",
            arg_names=PAGED_STEP_K_ARG_NAMES, donate_argnums=(0, 3, 4))

        def _paged_admit(ids, cur_len, kv_len, limit, eos, keys, temps,
                         tables, n_blk, free_stack, free_top, slot,
                         row, plen, lim, eos_id, key, temp, n_need):
            """Window-boundary admission: reserve blocks off the free
            stack and seed the slot's scheduler row.  No prefill here
            — the prompt's KV is written lazily by the chunked-prefill
            ticks inside the next window (that is what lets admission
            cost O(scheduler row) instead of O(full forward))."""
            trow = _pop_blocks(free_stack, free_top, n_need)
            tables = lax.dynamic_update_index_in_dim(tables, trow,
                                                     slot, axis=0)
            free_top = free_top - n_need
            ids = lax.dynamic_update_index_in_dim(ids, row, slot,
                                                  axis=0)
            cur_len = cur_len.at[slot].set(plen)
            kv_len = kv_len.at[slot].set(0)
            limit = limit.at[slot].set(lim)
            eos = eos.at[slot].set(eos_id)
            keys = keys.at[slot].set(key)
            temps = temps.at[slot].set(temp)
            n_blk = n_blk.at[slot].set(n_need)
            return (ids, cur_len, kv_len, limit, eos, keys, temps,
                    tables, n_blk, free_top)

        self._paged_admit = instrumented_jit(
            _paged_admit, "engine._paged_admit",
            arg_names=PAGED_ADMIT_ARG_NAMES, donate_argnums=(0, 5))
        self._set_kv_gauges()

    # -- admission ---------------------------------------------------------
    def _blocks_for(self, prompt, max_new_tokens) -> int:
        """Blocks a request reserves at admission: its FULL budget
        up front (positions through min(prompt+max_new, buf_len)), so
        an admitted request can always run to completion — admission
        control is the only backpressure point, and the engine can
        never deadlock with every slot mid-request and no block to
        grow into."""
        need = min(len(prompt) + max_new_tokens, self.buf_len)
        return -(-need // self.block_size)

    def _stream_key(self, rid, seed):
        """The per-request sampling key — same domain-separated
        fold_in chain as Engine (exactness contract).  Memoized per
        rid so staging the same waiting request across several windows
        hands the device bit-identical key bytes."""
        k = self._stream_keys_memo.get(rid)
        if k is None:
            base = jax.random.fold_in(self._key,
                                      0 if seed is None else 1)
            k = jax.random.fold_in(base, rid if seed is None else seed)
            self._stream_keys_memo[rid] = k
        return k

    @property
    def _supports_seed(self):
        return self.temperature > 0.0

    @property
    def _supports_temperature(self):
        return self.temperature > 0.0

    def _check_prompt(self, prompt):
        if len(prompt) < 1 or len(prompt) >= self.buf_len:
            raise ValueError(f"prompt length {len(prompt)} not in "
                             f"[1, {self.buf_len})")

    def _can_admit_direct(self, prompt, max_new_tokens) -> bool:
        return (bool(self._free) and self._free_top_h
                >= self._blocks_for(prompt, max_new_tokens))

    def add_request(self, prompt, max_new_tokens, eos_token_id=None,
                    seed=None, temperature=None, tenant=None):
        if self._free and self._free_top_h < self._blocks_for(
                prompt, max_new_tokens):
            raise RuntimeError(
                f"no free KV blocks for this request (needs "
                f"{self._blocks_for(prompt, max_new_tokens)}, "
                f"{self._free_top_h} free); use submit() to queue "
                f"until blocks recycle, or grow num_blocks")
        return super().add_request(prompt, max_new_tokens,
                                   eos_token_id, seed, temperature,
                                   tenant=tenant)

    def _admit(self, rid, prompt, max_new_tokens, eos_token_id,
               seed=None, temperature=None):
        n_need = self._blocks_for(prompt, max_new_tokens)
        if self._free_top_h < n_need:
            raise RuntimeError(
                f"no free KV blocks (need {n_need}, have "
                f"{self._free_top_h}); use submit() to queue until "
                f"blocks recycle")
        slot = self._free.pop()
        row = np.zeros((self.buf_len,), np.int32)
        row[:len(prompt)] = prompt
        lim = min(len(prompt) + max_new_tokens, self.buf_len)
        key = self._stream_key(rid, seed)
        self._stream_keys_memo.pop(rid, None)
        (self.ids, self.cur_len, self.kv_len, self.limit, self._eos,
         self._slot_keys, self._slot_temp, self.tables, self.n_blk,
         self.free_top) = self._paged_admit(
            self.ids, self.cur_len, self.kv_len, self.limit,
            self._eos, self._slot_keys, self._slot_temp, self.tables,
            self.n_blk, self.free_stack, self.free_top,
            jnp.int32(slot), jnp.asarray(row), jnp.int32(len(prompt)),
            jnp.int32(lim),
            jnp.int32(-1 if eos_token_id is None else eos_token_id),
            key,
            jnp.float32(self.temperature if temperature is None
                        else temperature),
            jnp.int32(n_need))
        self._free_top_h -= n_need
        self._slot_nblk_h[slot] = n_need
        self._by_slot[slot] = _Request(rid, slot, len(prompt),
                                       max_new_tokens, eos_token_id)

    def _drain_queue(self):
        # FIFO head-of-line semantics (no reordering — a small request
        # must not starve a big one forever): stop at the first queued
        # request that does not fit the current slot/block headroom
        admitted = False
        while (self._free and self._waiting
               and self._free_top_h >= self._blocks_for(
                   self._waiting[0][1], self._waiting[0][2])):
            self._admit_timed(*self._waiting.pop(0), refresh_kv=False)
            admitted = True
        self._set_queue_gauge()
        if admitted:
            self._set_kv_gauges()

    # -- the window --------------------------------------------------------
    def _stage_pending(self):
        """Static-shape pack of the waiting queue's first ``slots``
        requests for in-window admission.  Items STAY in ``_waiting``
        until the device confirms their admission (the ``adm`` replay)
        — so ``take_waiting`` / failover / cancel keep their exact
        semantics for requests the device has not started."""
        wait_rids = {item[0] for item in self._waiting}
        self._stream_keys_memo = {
            r: k for r, k in self._stream_keys_memo.items()
            if r in wait_rids}
        P = self.slots
        n = min(len(self._waiting), P)
        ids = np.zeros((P, self.buf_len), np.int32)
        lens = np.zeros((P,), np.int32)
        lims = np.zeros((P,), np.int32)
        eoss = np.full((P,), -1, np.int32)
        temps = np.zeros((P,), np.float32)
        needs = np.zeros((P,), np.int32)
        keys = jnp.zeros((P, 2), jnp.uint32)
        for i in range(n):
            (rid, prompt, max_new, eos_id, seed,
             temp) = self._waiting[i]
            ids[i, :len(prompt)] = prompt
            lens[i] = len(prompt)
            lims[i] = min(len(prompt) + max_new, self.buf_len)
            eoss[i] = -1 if eos_id is None else int(eos_id)
            temps[i] = float(self.temperature if temp is None
                             else temp)
            needs[i] = self._blocks_for(prompt, max_new)
            keys = keys.at[i].set(self._stream_key(rid, seed))
        return {"count": jnp.int32(n), "ids": jnp.asarray(ids),
                "len": jnp.asarray(lens), "limit": jnp.asarray(lims),
                "eos": jnp.asarray(eoss), "temps": jnp.asarray(temps),
                "keys": keys, "n_need": jnp.asarray(needs)}

    def step(self) -> Dict[int, Any]:
        """One decode window: stage the queue head, run the K
        continuous-batching ticks, fetch tokens + validity + the
        admission trace in ONE host sync, then replay the device's
        tick-by-tick decisions into the host bookkeeping."""
        if not self._by_slot and not self._waiting:
            return {}
        t0 = self._clock()
        live0 = len(self._by_slot)
        pending = self._stage_pending()
        with maybe_span("engine_window_decode", window=self.window,
                        live=live0):
            (self.ids, self.cur_len, self.kv_len, self.pool,
             self._slot_keys, self._slot_temp, self.limit, self._eos,
             self.tables, self.n_blk, self.free_stack, self.free_top,
             toks, valid, adm) = self._paged_step_k(
                self.ids, self.cur_len, self.kv_len, self.pool,
                self._slot_keys, self._slot_temp, self.limit,
                self._eos, self.tables, self.n_blk, self.free_stack,
                self.free_top, pending)
            # THE host sync: tokens, validity, in-window admissions
            # and the block headroom, fetched once per window
            toks_h, valid_h, adm_h, ft_h = jax.device_get(
                (toks, valid, adm, self.free_top))
        self._free_top_h = int(ft_h)
        return self._harvest_paged(toks_h, valid_h, adm_h, t0, live0)

    def _harvest_paged(self, toks_h, valid_h, adm_h, t0, live0):
        """Replay the window's device decisions in tick order: an
        admission at tick t binds the queue head to its slot BEFORE
        that slot's later tokens are harvested, and a death at tick t
        frees the slot before a tick-t' > t admission reuses it — the
        same order the scan applied on device."""
        n_tok = int(valid_h.sum())
        now = self._record_step(t0, tokens=n_tok,
                                capacity=max(live0, 1) * self.window)
        out: Dict[int, Any] = {}
        for t in range(self.window):
            s = int(adm_h[t])
            if s >= 0:
                (rid, prompt, max_new, eos_id, seed,
                 temp) = self._waiting.pop(0)
                req = _Request(rid, s, len(prompt), max_new, eos_id)
                req.t_submit = self._submit_ts.pop(rid, None)
                req.t_admit = now
                if req.t_submit is not None:
                    self._m_queue_wait.observe(
                        max(now - req.t_submit, 0.0))
                self._by_slot[s] = req
                if s in self._free:
                    self._free.remove(s)
                self._slot_nblk_h[s] = self._blocks_for(prompt,
                                                        max_new)
                self._stream_keys_memo.pop(rid, None)
                self._m_admitted.inc()
                self._n_admitted += 1
                self._n_midwindow += 1
                self.metrics.counter(
                    "engine_midwindow_admissions_total",
                    help="requests admitted INSIDE a decode window at "
                         "an iteration boundary (blocks freed by a "
                         "death earlier in the same window, reused "
                         "before it ends)").inc()
                self._set_queue_gauge()
            for s2 in range(self.slots):
                if not valid_h[s2][t]:
                    continue
                req = self._by_slot.get(s2)
                if req is None:
                    continue
                tok = int(toks_h[s2][t])
                req.generated.append(tok)
                out.setdefault(req.rid, []).append(tok)
                if req.t_first is None:
                    req.t_first = now
                self._m_tokens.inc()
                self._n_tokens += 1
                hit = req.eos is not None and tok == req.eos
                if hit or self._out_of_budget(req):
                    # the device already recycled this request's
                    # blocks IN-GRAPH the tick it died; the host only
                    # mirrors the bookkeeping (no _freeze_slot — limit
                    # is zeroed on device too)
                    self._slot_nblk_h.pop(s2, None)
                    self._finish(s2, req)
        self._drain_queue()
        self._set_kv_gauges()
        return out

    def _out_of_budget(self, req):
        return (len(req.generated) >= req.max_new
                or req.prompt_len + len(req.generated) >= self.buf_len)

    def _freeze_slot(self, slot):
        """cancel() of a LIVE request: the device never saw it die, so
        the host releases its blocks eagerly (plain device ops, not a
        jitted entry — cancel is a rare between-windows host API and
        eager ops never touch the compilation ledger)."""
        n = self._slot_nblk_h.pop(slot, 0)
        if n:
            j = jnp.arange(self.max_blocks)
            dest = jnp.where(j < n, self.free_top + j,
                             self.num_blocks)
            self.free_stack = self.free_stack.at[dest].set(
                self.tables[slot], mode="drop")
            self.free_top = self.free_top + jnp.int32(n)
            self._free_top_h += n
        self.limit = self.limit.at[slot].set(0)
        self.n_blk = self.n_blk.at[slot].set(0)

    # -- observability -----------------------------------------------------
    def _kv_buffers(self):
        return [self.pool]

    def _kv_usage(self):
        """PER-BLOCK accounting: a live request's waste is only the
        unfilled tail of its LAST reserved block-set (held blocks *
        block_size minus the positions its cur_len twin occupies);
        unreserved pool blocks surface as one free-pool entry.  This
        is the ledger line the ISSUE gates on: versus the fixed-slot
        engine's whole-row reservations, `kv_waste_bytes` collapses to
        sub-block granularity on mixed-length traffic."""
        pool_bytes = _tree_nbytes(self.pool)
        per_block = (pool_bytes / self.num_blocks
                     if self.num_blocks else 0.0)
        per_pos = per_block / self.block_size
        slots = []
        for slot in range(self.slots):
            req = self._by_slot.get(slot)
            held = (self._slot_nblk_h.get(slot, 0)
                    if req is not None else 0)
            used_pos = (min(req.prompt_len + len(req.generated),
                            held * self.block_size)
                        if req is not None else 0)
            used_b = int(round(per_pos * used_pos))
            held_b = int(round(per_block * held))
            slots.append({"slot": slot,
                          "rid": req.rid if req is not None else None,
                          "blocks_held": held,
                          "used_positions": used_pos,
                          "capacity_positions": held * self.block_size,
                          "used_bytes": used_b,
                          "kv_waste_bytes": held_b - used_b})
        free_blocks = max(self.num_blocks
                          - sum(self._slot_nblk_h.values()), 0)
        pools = [{"row": "free_blocks", "blocks": free_blocks,
                  "used_positions": 0,
                  "capacity_positions": free_blocks * self.block_size,
                  "used_bytes": 0,
                  "kv_waste_bytes": int(round(per_block
                                              * free_blocks))}]
        return slots, pools

    def _set_kv_gauges(self):
        frag = super()._set_kv_gauges()
        self.metrics.gauge(
            "engine_kv_blocks_free",
            help="KV pool blocks not reserved by any live request "
                 "(admission headroom)").set(float(self._free_top_h))
        return frag

    def compile_census(self) -> Dict[str, str]:
        # ONE decode-window graph covers chunked prefill, decode, the
        # in-window admission and the block recycling (they are cond
        # branches / masked lanes of the same scan, all traced at the
        # first call), plus the window-boundary admission entry
        return {"engine._paged_admit": "admission",
                "engine._paged_step_k": "decode"}

    def warmup(self):
        """Pre-compile the full paged census before traffic: one
        request whose prompt spans a chunk boundary (so the
        chunked-prefill + decode + recycling paths of the scan trace)
        plus a second 1-token request (exercising admission again —
        same graphs, and on a 1-slot engine it rides the in-window
        admission path).  Both are scrubbed from ``result()``; see
        ``Engine.warmup`` for the rid/stream caveats."""
        if self._by_slot or self._waiting:
            raise RuntimeError(
                "warmup() needs an idle engine (no live or queued "
                "requests); warm before traffic")
        plen = max(1, min(self.prefill_chunk + 1, self.buf_len - 1))
        r1 = self.add_request([0] * plen, max_new_tokens=1)
        r2 = self.submit([0], max_new_tokens=1)
        while not (self.is_finished(r1) and self.is_finished(r2)):
            self.step()
        self._finished.pop(r1, None)
        self._finished.pop(r2, None)
        return self

    def stats(self) -> Dict[str, Any]:
        """Base snapshot plus the block-pool fields the v12 bench
        schema exports: pool geometry, live headroom, and how many
        admissions happened INSIDE a window (the continuous-batching
        win made visible)."""
        s = super().stats()
        s["block_size"] = self.block_size
        s["blocks_total"] = self.num_blocks
        s["blocks_free"] = self._free_top_h
        s["max_blocks_per_request"] = self.max_blocks
        s["midwindow_admissions"] = self._n_midwindow
        return s


class Seq2SeqEngine(_SlotScheduler):
    """Continuous batching for ENCODER-DECODER models (T5 family).

    Decoder-only serving reuses one KV cache per slot; seq2seq serving
    needs two per-slot residents instead: the cross-attention K/V
    precomputed from that request's encoder pass, and a decoder
    self-attention cache.  ``add_request`` runs the encoder for the new
    request alone and scatters both into its slot
    (``T5.init_seq2seq_state`` / ``seed_slot_seq2seq``); ``step()`` is
    one jitted ``decode_step_rows`` tick over all slots at per-slot
    decoder positions — greedy, matching ``T5.generate``'s semantics
    token-for-token for each request regardless of what shares the
    batch (pinned in tests/test_serving.py).

    ``src_len`` fixes the padded source width (requests validate
    against it; shorter sources are masked, exactly like
    ``generate(attention_mask=...)``); ``max_new_cap`` fixes the
    decoder cache width, and per-request ``max_new_tokens`` may be
    anything up to it.  ``submit`` queues FIFO like the decoder-only
    Engine.  ``window=K`` scans K decoder ticks in-graph per
    ``step()`` with the same mid-window EOS/limit freeze and
    once-per-window host fetch as the decoder-only engine.
    """

    def __init__(self, model, params, slots: int, src_len: int,
                 max_new_cap: int, cache_dtype=None, window: int = 1,
                 metrics: Optional[MetricsRegistry] = None):
        self.model = model
        self.params = params
        self.slots = slots
        self.src_len = src_len
        self.max_new_cap = max_new_cap
        self.window = int(window)
        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if cache_dtype is None:
            cache_dtype = params["shared"]["weight"].dtype
        self.state = model.init_seq2seq_state(slots, src_len,
                                              max_new_cap, cache_dtype)
        self.out = jnp.zeros((slots, max_new_cap), jnp.int32)
        self.n_new = jnp.zeros((slots,), jnp.int32)
        # per-slot token budget (n_new < s_limit == slot is live; zeroed
        # on finish) and EOS id (-1 = none) for the in-graph masking
        self.s_limit = jnp.zeros((slots,), jnp.int32)
        self._eos = jnp.full((slots,), -1, jnp.int32)
        self._init_scheduler(slots, metrics)

        # donate the slot state: the encoder scatter updates the cross
        # K/V + decoder cache in place instead of duplicating them
        self._seed = instrumented_jit(
            lambda st, slot, row, n: model.seed_slot_seq2seq(
                params, st, slot, row, n),
            "seq2seq._seed", arg_names=("state", "slot", "row", "n"),
            donate_argnums=(0,))

        def _step_k(state, out, n_new, limit, eos):
            """K decoder ticks in-graph; same freeze/validity contract
            as the decoder-only ``_step_k``."""

            def tick(carry, _):
                state, out, n_new, alive = carry
                start = jnp.full((slots,),
                                 model.cfg.decoder_start_token_id,
                                 jnp.int32)
                prev = jnp.take_along_axis(
                    out, jnp.maximum(n_new - 1, 0)[:, None],
                    axis=1)[:, 0]
                tok = jnp.where(n_new == 0, start, prev)
                logits, state = model.decode_step_rows(params, tok,
                                                       n_new, state)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                can = alive & (n_new < max_new_cap)
                out = jax.vmap(
                    lambda row, p, t, c: row.at[p].set(
                        jnp.where(c, t, row[p])))(
                    out, jnp.minimum(n_new, max_new_cap - 1), nxt, can)
                new_n = jnp.where(can, n_new + 1, n_new)
                emitted = alive
                hit_eos = (eos >= 0) & (nxt == eos)
                alive = alive & ~hit_eos & (new_n < limit)
                return (state, out, new_n, alive), (nxt, emitted)

            alive0 = n_new < limit
            (state, out, n_new, _), (toks, valid) = lax.scan(
                tick, (state, out, n_new, alive0), None,
                length=self.window)
            return state, out, n_new, toks.T, valid.T

        # state + out donated; n_new deliberately not (the per-slot
        # length vector — see the donation note on Engine._step_k)
        self._step_k = instrumented_jit(
            _step_k, "seq2seq._step_k",
            arg_names=SEQ2SEQ_STEP_K_ARG_NAMES, donate_argnums=(0, 1))

    def compile_census(self) -> Dict[str, str]:
        return {"seq2seq._seed": "admission",
                "seq2seq._step_k": "decode"}

    def _kv_buffers(self):
        # per-slot seq2seq state: cross-attention K/V + decoder cache
        return [self.state]

    def _kv_usage(self):
        """Per-slot occupancy over the two seq2seq residents: the
        ``cross`` subtree is cross-attention K/V (used up to the
        request's source length), the ``dec`` subtree is the decoder
        self-attention cache (used up to its generated count);
        remaining per-slot state (e.g. the source mask) counts as used
        while the slot is live.  Classified by the state's own subtree
        keys (``init_seq2seq_state``'s contract) — an axis-value
        heuristic would misclassify whenever ``src_len ==
        max_new_cap`` — with a shape-based fallback for state pytrees
        that don't follow the key convention."""
        if isinstance(self.state, dict) and "cross" in self.state \
                and "dec" in self.state:
            cross = _tree_nbytes(self.state["cross"])
            dec = _tree_nbytes(self.state["dec"])
            other = _tree_nbytes(self.state) - cross - dec
        else:
            cross = dec = other = 0
            for leaf in jax.tree_util.tree_leaves(self.state):
                shape = getattr(leaf, "shape", ())
                nb = getattr(leaf, "nbytes", 0)
                if len(shape) >= 2 and self.src_len in shape[1:]:
                    cross += nb
                elif len(shape) >= 2 and self.max_new_cap in shape[1:]:
                    dec += nb
                else:
                    other += nb
        slots = []
        for slot in range(self.slots):
            req = self._by_slot.get(slot)
            if req is not None:
                src_pos = min(req.prompt_len, self.src_len)
                dec_pos = min(len(req.generated), self.max_new_cap)
                live = 1.0
            else:
                src_pos = dec_pos = 0
                live = 0.0
            used_b = int(round(
                cross * src_pos / (self.slots * self.src_len)
                + dec * dec_pos / (self.slots * self.max_new_cap)
                + other * live / self.slots))
            cap_b = int(round((cross + dec + other) / self.slots))
            slots.append({"slot": slot,
                          "rid": req.rid if req is not None else None,
                          "used_positions": src_pos + dec_pos,
                          "capacity_positions": (self.src_len
                                                 + self.max_new_cap),
                          "used_bytes": used_b,
                          "kv_waste_bytes": cap_b - used_b})
        return slots, []

    def _check_prompt(self, src):
        if len(src) < 1 or len(src) > self.src_len:
            raise ValueError(f"source length {len(src)} not in "
                             f"[1, {self.src_len}]")

    def _admit(self, rid, src, max_new_tokens, eos_token_id,
               seed=None, temperature=None):
        slot = self._free.pop()
        row = np.zeros((self.src_len,), np.int32)
        row[:len(src)] = src
        self.state = self._seed(self.state, slot, jnp.asarray(row),
                                len(src))
        self.n_new = self.n_new.at[slot].set(0)
        max_new = min(max_new_tokens, self.max_new_cap)
        self.s_limit = self.s_limit.at[slot].set(max_new)
        self._eos = self._eos.at[slot].set(
            -1 if eos_token_id is None else int(eos_token_id))
        self._by_slot[slot] = _Request(rid, slot, len(src), max_new,
                                       eos_token_id)

    def step(self) -> Dict[int, Any]:
        """One batched decoder dispatch — a window of ``window``
        in-graph ticks; {rid: [tokens]} for live requests.  Finishes
        on per-request EOS or token budget (frozen mid-window
        in-graph); the slot frees at the window boundary."""
        if not self._by_slot and self._waiting:
            # see Engine.step: cancel() may leave waiting work on an
            # otherwise idle engine
            self._drain_queue()
        if not self._by_slot:
            return {}
        t0 = self._clock()
        live = list(self._by_slot)
        with maybe_span("engine_window_decode", window=self.window,
                        live=len(live)):
            (self.state, self.out, self.n_new, toks,
             valid) = self._step_k(self.state, self.out, self.n_new,
                                   self.s_limit, self._eos)
            # THE host sync: one fetch per window, not per token
            toks_h, valid_h = jax.device_get((toks, valid))
            emitted = {slot: [int(t) for t, v
                              in zip(toks_h[slot], valid_h[slot]) if v]
                       for slot in live}
        return self._harvest(emitted, t0)

    def _out_of_budget(self, req):
        # req.max_new is already min(max_new_tokens, max_new_cap)
        return len(req.generated) >= req.max_new

    def _freeze_slot(self, slot):
        self.s_limit = self.s_limit.at[slot].set(0)
