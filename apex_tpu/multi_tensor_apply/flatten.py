"""Flatten / unflatten: fused flat buffers per dtype group.

TPU-native replacement for the reference's apex_C extension
(csrc/flatten_unflatten.cpp:5-13) and its `split_half_float_double` dtype
bucketing (apex/parallel/distributed.py:51-58).  DDP's bucketed allreduce
and the fused optimizers both operate on these buffers: one contiguous
array per dtype means one psum / one Pallas kernel launch per group instead
of per-parameter work — the multi_tensor_apply insight, expressed the XLA
way (SURVEY.md §7).
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["flatten", "unflatten", "split_by_dtype", "TreeFlattener",
           "pack_flat", "unpack_flat"]


def pack_flat(tree: Any, dtype=None) -> Tuple[jax.Array, list, Any]:
    """Concatenate tree leaves into one flat buffer (optionally casting).
    Returns (flat, leaves, treedef); empty trees give a 0-length buffer.
    The single flatten helper shared by the fused optimizers and the
    Pallas kernel family."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return jnp.zeros((0,), dtype or jnp.float32), leaves, treedef
    parts = [l.reshape(-1) if dtype is None else
             l.reshape(-1).astype(dtype) for l in leaves]
    flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return flat, leaves, treedef


def unpack_flat(flat: jax.Array, like_leaves: Sequence[jax.Array], treedef,
                cast_like: bool = True) -> Any:
    """Inverse of pack_flat against reference leaves + treedef."""
    out, off = [], 0
    for l in like_leaves:
        n = int(l.size)
        piece = flat[off:off + n].reshape(l.shape)
        if cast_like:
            piece = piece.astype(l.dtype)
        out.append(piece)
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def flatten(tensors: Sequence[jax.Array]) -> jax.Array:
    """Concatenate raveled same-dtype tensors into one 1-D buffer."""
    tensors = list(tensors)
    if not tensors:
        return jnp.zeros((0,), jnp.float32)
    dt = tensors[0].dtype
    if any(t.dtype != dt for t in tensors):
        raise TypeError("flatten() requires a same-dtype tensor list; "
                        "use split_by_dtype first")
    return jnp.concatenate([t.reshape(-1) for t in tensors])


def unflatten(flat: jax.Array, like: Sequence[jax.Array]) -> List[jax.Array]:
    """Inverse of flatten: view ``flat`` back as tensors shaped like ``like``."""
    out, off = [], 0
    for t in like:
        n = t.size
        out.append(jax.lax.dynamic_slice_in_dim(flat, off, n).reshape(t.shape))
        off += n
    return out


def split_by_dtype(tensors: Sequence[jax.Array]
                   ) -> Dict[Any, List[Tuple[int, jax.Array]]]:
    """Group (index, tensor) pairs by dtype, preserving order within a group
    (the analogue of split_half_float_double, distributed.py:51-58)."""
    groups: Dict[Any, List[Tuple[int, jax.Array]]] = {}
    for i, t in enumerate(tensors):
        groups.setdefault(jnp.dtype(t.dtype), []).append((i, t))
    return groups


class TreeFlattener:
    """Pack a pytree into one flat fp32-or-native buffer per dtype group and
    back.  Structure (treedef, shapes, dtype->indices) is computed once at
    construction, so pack/unpack are pure reshape/concat ops that XLA fuses.
    """

    def __init__(self, tree: Any):
        leaves, self.treedef = jax.tree_util.tree_flatten(tree)
        self.shapes = [l.shape for l in leaves]
        self.sizes = [int(l.size) for l in leaves]
        self.dtypes = [jnp.dtype(l.dtype) for l in leaves]
        self.groups: Dict[Any, List[int]] = {}
        for i, dt in enumerate(self.dtypes):
            self.groups.setdefault(dt, []).append(i)

    def pack(self, tree: Any) -> Dict[Any, jax.Array]:
        leaves = jax.tree_util.tree_leaves(tree)
        out = {}
        for dt, idxs in self.groups.items():
            out[dt] = jnp.concatenate([leaves[i].reshape(-1) for i in idxs])
        return out

    def unpack(self, buffers: Dict[Any, jax.Array]) -> Any:
        leaves: List[Any] = [None] * len(self.shapes)
        for dt, idxs in self.groups.items():
            off = 0
            buf = buffers[dt]
            for i in idxs:
                n = self.sizes[i]
                leaves[i] = buf[off:off + n].reshape(self.shapes[i])
                off += n
        return jax.tree_util.tree_unflatten(self.treedef, leaves)
