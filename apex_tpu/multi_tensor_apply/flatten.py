"""Flatten / unflatten: fused flat buffers per dtype group.

TPU-native replacement for the reference's apex_C extension
(csrc/flatten_unflatten.cpp:5-13) and its `split_half_float_double` dtype
bucketing (apex/parallel/distributed.py:51-58).  DDP's bucketed allreduce
and the fused optimizers both operate on these buffers: one contiguous
array per dtype means one psum / one Pallas kernel launch per group instead
of per-parameter work — the multi_tensor_apply insight, expressed the XLA
way (SURVEY.md §7).
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["flatten", "unflatten", "split_by_dtype", "TreeFlattener",
           "pack_flat", "unpack_flat", "ChunkedFlatLayout", "ChunkedFlat"]


def pack_flat(tree: Any, dtype=None) -> Tuple[jax.Array, list, Any]:
    """Concatenate tree leaves into one flat buffer (optionally casting).
    Returns (flat, leaves, treedef); empty trees give a 0-length buffer.
    The single flatten helper shared by the fused optimizers and the
    Pallas kernel family."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return jnp.zeros((0,), dtype or jnp.float32), leaves, treedef
    parts = [l.reshape(-1) if dtype is None else
             l.reshape(-1).astype(dtype) for l in leaves]
    flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return flat, leaves, treedef


def unpack_flat(flat: jax.Array, like_leaves: Sequence[jax.Array], treedef,
                cast_like: bool = True) -> Any:
    """Inverse of pack_flat against reference leaves + treedef."""
    out, off = [], 0
    for l in like_leaves:
        n = int(l.size)
        piece = flat[off:off + n].reshape(l.shape)
        if cast_like:
            piece = piece.astype(l.dtype)
        out.append(piece)
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def flatten(tensors: Sequence[jax.Array]) -> jax.Array:
    """Concatenate raveled same-dtype tensors into one 1-D buffer."""
    tensors = list(tensors)
    if not tensors:
        return jnp.zeros((0,), jnp.float32)
    dt = tensors[0].dtype
    if any(t.dtype != dt for t in tensors):
        raise TypeError("flatten() requires a same-dtype tensor list; "
                        "use split_by_dtype first")
    return jnp.concatenate([t.reshape(-1) for t in tensors])


def unflatten(flat: jax.Array, like: Sequence[jax.Array]) -> List[jax.Array]:
    """Inverse of flatten: view ``flat`` back as tensors shaped like ``like``."""
    out, off = [], 0
    for t in like:
        n = t.size
        out.append(jax.lax.dynamic_slice_in_dim(flat, off, n).reshape(t.shape))
        off += n
    return out


def split_by_dtype(tensors: Sequence[jax.Array]
                   ) -> Dict[Any, List[Tuple[int, jax.Array]]]:
    """Group (index, tensor) pairs by dtype, preserving order within a group
    (the analogue of split_half_float_double, distributed.py:51-58)."""
    groups: Dict[Any, List[Tuple[int, jax.Array]]] = {}
    for i, t in enumerate(tensors):
        groups.setdefault(jnp.dtype(t.dtype), []).append((i, t))
    return groups


class TreeFlattener:
    """Pack a pytree into one flat fp32-or-native buffer per dtype group and
    back.  Structure (treedef, shapes, dtype->indices) is computed once at
    construction, so pack/unpack are pure reshape/concat ops that XLA fuses.
    """

    def __init__(self, tree: Any):
        leaves, self.treedef = jax.tree_util.tree_flatten(tree)
        self.shapes = [l.shape for l in leaves]
        self.sizes = [int(l.size) for l in leaves]
        self.dtypes = [jnp.dtype(l.dtype) for l in leaves]
        self.groups: Dict[Any, List[int]] = {}
        for i, dt in enumerate(self.dtypes):
            self.groups.setdefault(dt, []).append(i)

    def pack(self, tree: Any) -> Dict[Any, jax.Array]:
        leaves = jax.tree_util.tree_leaves(tree)
        out = {}
        for dt, idxs in self.groups.items():
            out[dt] = jnp.concatenate([leaves[i].reshape(-1) for i in idxs])
        return out

    def unpack(self, buffers: Dict[Any, jax.Array]) -> Any:
        leaves: List[Any] = [None] * len(self.shapes)
        for dt, idxs in self.groups.items():
            off = 0
            buf = buffers[dt]
            for i in idxs:
                n = self.sizes[i]
                leaves[i] = buf[off:off + n].reshape(self.shapes[i])
                off += n
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


class ChunkedFlatLayout:
    """Static layout for a *chunk-padded* fused buffer with a segment map.

    Every float leaf is padded to a multiple of ``chunk`` elements, so each
    chunk belongs to exactly one tensor.  Per-tensor reductions then cost
    one dense pass (chunk partial sums, an XLA row reduction) plus a
    segment-sum over the tiny (num_chunks,) vector — the TPU-shaped
    equivalent of the reference's single multi_tensor_l2norm kernel with a
    per-tensor output buffer (csrc/multi_tensor_l2norm_kernel.cu:117-180),
    replacing round-1's per-leaf Python loop (~2 reductions per leaf on a
    400-leaf tree).  Distinct from amp's dense ``_FlatLayout`` (no padding,
    fused half-copy rebuild): here padding buys alignment for segment math.

    The layout is static (computed once, hashable) so it can ride pytree
    aux_data; padded slots hold zeros and are invariant under elementwise
    optimizer updates with zero gradients.
    """

    def __init__(self, tree: Any, chunk: int = 1024):
        import numpy as np
        leaves, self.treedef = jax.tree_util.tree_flatten(tree)
        self.chunk = int(chunk)
        self.shapes = tuple(tuple(l.shape) for l in leaves)
        self.dtypes = tuple(str(jnp.result_type(l)) for l in leaves)
        self.is_float = tuple(
            jnp.issubdtype(jnp.result_type(l), jnp.floating) for l in leaves)
        sizes, padded, offsets, off = [], [], [], 0
        for shape, f in zip(self.shapes, self.is_float):
            n = int(np.prod(shape, dtype=np.int64)) if f else 0
            p = -(-n // self.chunk) * self.chunk
            sizes.append(n)
            padded.append(p)
            offsets.append(off)
            off += p
        self.sizes = tuple(sizes)
        self.padded = tuple(padded)
        self.offsets = tuple(offsets)
        self.total = off
        self.num_tensors = sum(1 for f in self.is_float if f)
        seg = np.zeros(off // self.chunk, np.int32)
        tensor_idx = 0
        for i, f in enumerate(self.is_float):
            if not f:
                continue
            lo = self.offsets[i] // self.chunk
            hi = (self.offsets[i] + self.padded[i]) // self.chunk
            seg[lo:hi] = tensor_idx
            tensor_idx += 1
        self._seg_ids = seg            # numpy; jnp-ified lazily per trace

    def _key(self):
        return (self.treedef, self.shapes, self.dtypes, self.chunk)

    def __eq__(self, other):
        return (isinstance(other, ChunkedFlatLayout)
                and self._key() == other._key())

    def __hash__(self):
        return hash(self._key())

    def pack(self, tree: Any, dtype=jnp.float32) -> jax.Array:
        leaves = jax.tree_util.tree_leaves(tree)
        parts = []
        for l, f, n, p in zip(leaves, self.is_float, self.sizes,
                              self.padded):
            if not f:
                continue
            flat = l.reshape(-1).astype(dtype)
            if p != n:
                flat = jnp.pad(flat, (0, p - n))
            parts.append(flat)
        if not parts:
            return jnp.zeros((0,), dtype)
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

    def unpack(self, flat: jax.Array, like_leaves=None,
               cast_like: bool = True) -> Any:
        out = []
        fi = 0
        for i, (shape, f) in enumerate(zip(self.shapes, self.is_float)):
            if not f:
                out.append(like_leaves[i] if like_leaves is not None
                           else None)
                continue
            piece = jax.lax.dynamic_slice_in_dim(
                flat, self.offsets[i], self.sizes[i]).reshape(shape)
            if cast_like:
                piece = piece.astype(jnp.dtype(self.dtypes[i]))
            out.append(piece)
            fi += 1
        return jax.tree_util.tree_unflatten(self.treedef, out)

    # -- segment math ------------------------------------------------------
    def per_tensor_sqsum(self, flat: jax.Array) -> jax.Array:
        """(num_tensors,) sum of squares per tensor: one dense row
        reduction + a tiny segment-sum."""
        K = self.total // self.chunk
        cs = jnp.sum(jnp.square(flat.astype(jnp.float32)).reshape(
            K, self.chunk), axis=1)
        return jax.ops.segment_sum(cs, jnp.asarray(self._seg_ids),
                                   num_segments=self.num_tensors)

    def expand_per_tensor(self, vals: jax.Array) -> jax.Array:
        """(num_tensors,) -> (total,) per-element broadcast via the chunk
        segment map (cheap gather of K values, then a dense broadcast)."""
        K = self.total // self.chunk
        per_chunk = vals[jnp.asarray(self._seg_ids)]
        return jnp.broadcast_to(per_chunk[:, None],
                                (K, self.chunk)).reshape(-1)


@jax.tree_util.register_pytree_node_class
class ChunkedFlat:
    """A flat buffer + its static ChunkedFlatLayout as one pytree node
    (single array leaf; layout rides aux_data, same pattern as
    amp.FlatMasters)."""

    def __init__(self, buf: jax.Array, layout: ChunkedFlatLayout):
        self.buf = buf
        self.layout = layout

    def tree_flatten(self):
        return (self.buf,), self.layout

    @classmethod
    def tree_unflatten(cls, layout, children):
        return cls(children[0], layout)
