"""Multi-tensor ops: scale / axpby / l2norm (+ fused unscale with overflow
detection) over lists of arrays or whole pytrees.

These are the TPU-native equivalents of the reference's amp_C CUDA kernels
(csrc/multi_tensor_scale_kernel.cu, multi_tensor_axpby_kernel.cu,
multi_tensor_l2norm_kernel.cu, dispatched through the chunked
multi_tensor_apply harness in csrc/multi_tensor_apply.cuh:40-126).  The CUDA
harness exists to pack tensor addresses into 4KB kernel-arg structs; XLA has
no such constraint, so the idiomatic form is a tree_map that XLA fuses into
a handful of loops — or, on TPU, a single Pallas kernel over a fused flat
buffer (apex_tpu.ops.pallas_multi_tensor), selected automatically.

Semantics preserved from the reference:

- ``multi_tensor_scale``: out = in * scale, and the returned ``found_inf``
  flag is 1.0 iff any *input* element is non-finite — the fused
  unscale+overflow-check (multi_tensor_scale_kernel.cu:64-73).
- ``multi_tensor_axpby``: out = a*x + b*y with the finite check applied to
  x, y, or both per ``arg_to_check`` (multi_tensor_axpby_kernel.cu:67-84);
  used for gradient accumulation across backward passes
  (apex/amp/scaler.py:167-172).
- ``multi_tensor_l2norm``: global L2 norm and optional per-tensor norms,
  accumulated in fp32 (multi_tensor_l2norm_kernel.cu:47-180).

Unlike the reference there is no mutated ``noop_flag`` buffer: the flag is a
device scalar returned functionally, so under jit no host sync is forced
(the reference pays one D2H per step at apex/amp/scaler.py:192-193).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def _leaves(tree: Any) -> List[jax.Array]:
    return [x for x in jax.tree_util.tree_leaves(tree)]


def _nonfinite_any(leaves: Sequence[jax.Array]) -> jax.Array:
    """1.0 if any element of any leaf is inf/nan, else 0.0 (fp32 scalar)."""
    if not leaves:
        return jnp.zeros((), jnp.float32)
    flags = [jnp.any(~jnp.isfinite(x.astype(jnp.float32))) for x in leaves]
    out = flags[0]
    for f in flags[1:]:
        out = out | f
    return out.astype(jnp.float32)


def multi_tensor_scale(tree: Any, scale, check_finite: bool = True
                       ) -> Tuple[Any, jax.Array]:
    """out = tree * scale; found_inf flags non-finite *inputs*.

    Output leaves keep their input dtypes (the reference kernel writes
    through templated out-types; cross-dtype copy-scaling is done by
    passing ``out_dtype``-cast trees at the call site).
    """
    from ..ops import dispatch
    if dispatch.use_pallas_for(tree):
        from ..ops import pallas_multi_tensor as pk
        return pk.multi_tensor_scale(tree, scale, check_finite)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    found_inf = _nonfinite_any(leaves) if check_finite else jnp.zeros(
        (), jnp.float32)
    scale = jnp.asarray(scale, jnp.float32)
    out = [(x.astype(jnp.float32) * scale).astype(x.dtype) for x in leaves]
    return jax.tree_util.tree_unflatten(treedef, out), found_inf


def multi_tensor_axpby(a, b, x_tree: Any, y_tree: Any,
                       arg_to_check: int = -1) -> Tuple[Any, jax.Array]:
    """out = a*x + b*y leafwise; finite check on x (0), y (1) or both (-1)."""
    from ..ops import dispatch
    if dispatch.use_pallas_for(x_tree):
        from ..ops import pallas_multi_tensor as pk
        return pk.multi_tensor_axpby(a, b, x_tree, y_tree, arg_to_check)
    xs, treedef = jax.tree_util.tree_flatten(x_tree)
    ys = jax.tree_util.tree_leaves(y_tree)
    if arg_to_check == 0:
        found_inf = _nonfinite_any(xs)
    elif arg_to_check == 1:
        found_inf = _nonfinite_any(ys)
    else:
        found_inf = jnp.maximum(_nonfinite_any(xs), _nonfinite_any(ys))
    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)
    out = [(a * xv.astype(jnp.float32) + b * yv.astype(jnp.float32)
            ).astype(xv.dtype) for xv, yv in zip(xs, ys)]
    return jax.tree_util.tree_unflatten(treedef, out), found_inf


def multi_tensor_l2norm(tree: Any, per_tensor: bool = False
                        ) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Global (and optionally per-leaf) L2 norm in fp32."""
    from ..ops import dispatch
    if dispatch.use_pallas_for(tree):
        from ..ops import pallas_multi_tensor as pk
        return pk.multi_tensor_l2norm(tree, per_tensor)
    leaves = _leaves(tree)
    if not leaves:
        z = jnp.zeros((), jnp.float32)
        return z, (jnp.zeros((0,), jnp.float32) if per_tensor else None)
    if per_tensor:
        if all(jnp.issubdtype(jnp.result_type(x), jnp.floating)
               for x in leaves):
            # segment-map form: one dense pass + a (num_chunks,)
            # segment-sum instead of 2 reductions per leaf
            # (see ChunkedFlatLayout)
            from .flatten import ChunkedFlatLayout
            lay = ChunkedFlatLayout(tree)
            sq = lay.per_tensor_sqsum(lay.pack(tree))
        else:
            # non-float leaves: keep one entry per leaf so the output
            # stays positionally aligned with tree_leaves
            sq = jnp.stack([jnp.sum(jnp.square(x.astype(jnp.float32)))
                            for x in leaves])
        return jnp.sqrt(jnp.sum(sq)), jnp.sqrt(sq)
    sq = [jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves]
    return jnp.sqrt(sum(sq)), None


def global_grad_norm(tree: Any) -> jax.Array:
    """fp32 global L2 norm; returns -1.0 when non-finite, matching the
    overflow convention of apex/optimizers/fp16_optimizer.py:103-128."""
    norm, _ = multi_tensor_l2norm(tree)
    return jnp.where(jnp.isfinite(norm), norm, -jnp.ones((), jnp.float32))
