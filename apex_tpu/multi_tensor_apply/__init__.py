"""apex_tpu.multi_tensor_apply — multi-tensor kernel dispatch.

API-parity shim for apex.multi_tensor_apply (multi_tensor_apply.py:3-30):
``multi_tensor_applier(op, tensor_lists, *args)`` calls ``op`` over the
tensor lists and returns ``(outputs, found_inf)``; the mutated noop-flag
buffer of the reference becomes a functional return value.
"""

from .multi_tensor import (multi_tensor_scale, multi_tensor_axpby,
                           multi_tensor_l2norm, global_grad_norm)
from .flatten import flatten, unflatten, split_by_dtype, TreeFlattener


class MultiTensorApply:
    """Callable shim mirroring apex's MultiTensorApply. ``chunk_size`` is
    accepted for signature parity; XLA/Pallas pick their own tiling."""

    available = True
    warned = False

    def __init__(self, chunk_size: int = 2048 * 32):
        self.chunk_size = chunk_size

    def __call__(self, op, tensor_lists, *args, **kwargs):
        return op(*tensor_lists, *args, **kwargs)


multi_tensor_applier = MultiTensorApply(2048 * 32)
