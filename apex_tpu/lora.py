"""LoRA — low-rank adaptation as pure param-tree arithmetic.

The reference toolkit predates parameter-efficient fine-tuning; this
is the TPU-functional take: instead of wrapping layers (the torch
idiom), adapters are a FLAT dict keyed by the target weight's tree
path, and ``merge`` produces an ordinary param tree
``W + scale * B @ A`` that drops into any model/optimizer/serving path
unchanged — the model code never learns LoRA exists, and XLA fuses the
rank-r update into the surrounding graph.

Standard init (Hu et al. 2021): A ~ N(0, 1/rank), B = 0, so merged ==
base at step 0 (pinned bitwise in tests/test_lora.py).  Fine-tuning
optimizes ONLY the adapter dict; ``scale`` (= alpha/rank) is a static
python float so the adapter pytree holds nothing an optimizer could
mistakenly update::

    adapters = lora.init(params, targets=("q_proj", "v_proj"), rank=8,
                         key=key)
    s = lora.scale(alpha=16.0, rank=8)
    def loss_fn(ad):
        return model.loss(lora.merge(params, ad, s), ids)
    grads = jax.grad(loss_fn)(adapters)       # base params untouched

Serving: ``merge`` once, then quantize/generate as usual; the adapter
dict is its own (tiny) checkpoint — save it with utils.checkpoint like
any pytree.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["init", "merge", "scale", "num_params"]


def _path_str(path) -> str:
    return "/".join(
        str(getattr(k, "key", getattr(k, "idx", k))) for k in path)


def scale(alpha: float = 16.0, rank: int = 8) -> float:
    """The merge scale alpha/rank (kept static on purpose)."""
    return float(alpha) / float(rank)


def init(params: Any, targets: Sequence[str], rank: int = 8,
         key: Optional[jax.Array] = None) -> Dict[str, Any]:
    """Adapter dict ``{path: {"a": (r, in), "b": (out, r)}}`` for every
    2-D leaf whose tree path contains one of ``targets`` (e.g.
    ``("q_proj", "v_proj")`` for Llama attention, ``("qkv",)`` for
    GPT).  Weights follow the framework's (out, in) Linear convention.
    B starts at zero, so ``merge(params, init(...))`` == ``params``."""
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    if key is None:
        key = jax.random.PRNGKey(0)
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    adapters: Dict[str, Any] = {}
    for path, leaf in leaves:
        pstr = _path_str(path)
        if getattr(leaf, "ndim", 0) != 2:
            continue
        if not any(t in pstr for t in targets):
            continue
        out_f, in_f = leaf.shape
        key, sub = jax.random.split(key)
        adapters[pstr] = {
            "a": (jax.random.normal(sub, (rank, in_f), jnp.float32)
                  / float(rank) ** 0.5),
            "b": jnp.zeros((out_f, rank), jnp.float32),
        }
    if not adapters:
        raise ValueError(f"no 2-D weights matched targets {targets!r}")
    return adapters


def merge(params: Any, adapters: Dict[str, Any],
          merge_scale: float = 2.0) -> Any:
    """New param tree with ``W + merge_scale * B @ A`` at every adapted
    path (copy-on-write: unadapted subtrees are shared, not copied).
    Default ``merge_scale`` is ``scale()`` for the default alpha=16,
    rank=8."""
    remaining = set(adapters)

    def walk(node, prefix):
        if isinstance(node, dict):
            out = dict(node)
            for name, sub in node.items():
                p = f"{prefix}/{name}" if prefix else str(name)
                if p in adapters:
                    ad = adapters[p]
                    remaining.discard(p)
                    delta = (merge_scale
                             * (ad["b"] @ ad["a"])).astype(sub.dtype)
                    out[name] = sub + delta
                else:
                    out[name] = walk(sub, p)
            return out
        return node

    merged = walk(params, "")
    if remaining:
        raise KeyError(f"adapter paths not found in params: "
                       f"{sorted(remaining)[:4]}")
    return merged


def num_params(adapters: Dict[str, Any]) -> Tuple[int, int]:
    """(adapter trainable params, full-matrix params at the adapted
    sites) — the fine-tuning footprint vs full fine-tuning."""
    small = sum(int(ad["a"].size + ad["b"].size)
                for ad in adapters.values())
    full = sum(int(ad["b"].shape[0] * ad["a"].shape[1])
               for ad in adapters.values())
    return small, full
