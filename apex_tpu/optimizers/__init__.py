"""apex_tpu.optimizers — fused optimizers on flat parameter buffers.

Reference exports FusedAdam and FP16_Optimizer
(apex/optimizers/__init__.py:1-2); FusedLAMB is added here on top of the
reference's LAMB stage1/stage2 kernel semantics (SURVEY.md §2.2 gap).
"""

from .base import Optimizer, SGD, SGDState, resolve_lr, global_grad_norm
from .fused_adam import FusedAdam, AdamState
from .fused_lamb import FusedLAMB, LambState
from .fused_lion import FusedLion, LionState
from .fp16_optimizer import FP16_Optimizer, FP16OptState
