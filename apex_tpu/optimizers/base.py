"""Optimizer protocol for apex_tpu.

A functional analogue of torch.optim: an Optimizer object holds
hyperparameters and exposes pure ``init(params) -> state`` and
``update(grads, state, params) -> (new_params, new_state)``.  The amp
machinery wraps these the way the reference performs surgery on torch
optimizers (apex/amp/_process_optimizer.py) — but as composition, not
monkey-patching.

``lr`` may be a float or a schedule ``f(step) -> float``; ``state.step``
counts applied (non-skipped) updates so LR schedules and Adam bias
correction see the same step numbering as the reference's skip semantics.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp

__all__ = ["Optimizer", "SGD", "SGDState", "resolve_lr",
           "global_grad_norm"]

Schedule = Union[float, Callable[[jax.Array], jax.Array]]


def resolve_lr(lr: Schedule, step: jax.Array) -> jax.Array:
    if callable(lr):
        return jnp.asarray(lr(step), jnp.float32)
    return jnp.asarray(lr, jnp.float32)


def global_grad_norm(grads: Any) -> jax.Array:
    """Global L2 norm over a gradient pytree (or flat buffer) as an fp32
    device scalar — the observability gauge the amp step reports in its
    info dict.  Pure jnp, so it composes with jit/shard_map; under
    data-parallel the grads are already allreduced, so every replica
    computes the same value with no extra collective."""
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    sq = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    return jnp.sqrt(sq)


class Optimizer:
    def init(self, params: Any) -> Any:
        raise NotImplementedError

    def update(self, grads: Any, state: Any, params: Any) -> Tuple[Any, Any]:
        raise NotImplementedError


class SGDState(NamedTuple):
    step: jax.Array
    momentum: Any  # pytree like params, or None


class SGD(Optimizer):
    # purely elementwise given scalar hyperparams: safe to run on a fused
    # flat buffer (amp._process_optimizer.FlatMasters fast path)
    elementwise = True

    def __init__(self, lr: Schedule = 0.01, momentum: float = 0.0,
                 weight_decay: float = 0.0, nesterov: bool = False,
                 dampening: float = 0.0):
        self.lr = lr
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.dampening = dampening

    def init(self, params: Any) -> SGDState:
        mom = None
        if self.momentum:
            mom = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return SGDState(step=jnp.zeros((), jnp.int32), momentum=mom)

    def update(self, grads: Any, state: SGDState, params: Any):
        lr = resolve_lr(self.lr, state.step)
        wd = self.weight_decay

        def upd(p, g, m):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if wd:
                g = g + wd * p32
            if m is not None:
                m_new = self.momentum * m + (1.0 - self.dampening) * g
                if self.nesterov:
                    g = g + self.momentum * m_new
                else:
                    g = m_new
            else:
                m_new = None
            return (p32 - lr * g).astype(p.dtype), m_new

        if state.momentum is None:
            new_params = jax.tree_util.tree_map(
                lambda p, g: upd(p, g, None)[0], params, grads)
            new_mom = None
        else:
            pairs = jax.tree_util.tree_map(upd, params, grads, state.momentum)
            new_params = jax.tree_util.tree_map(
                lambda pr: pr[0], pairs, is_leaf=lambda x: isinstance(x, tuple))
            new_mom = jax.tree_util.tree_map(
                lambda pr: pr[1], pairs, is_leaf=lambda x: isinstance(x, tuple))
        return new_params, SGDState(step=state.step + 1, momentum=new_mom)
