"""FP16_Optimizer (fused flavor): master-weight wrapper for FusedAdam.

Equivalent of apex/optimizers/fp16_optimizer.py (274 lines): keeps fp32
master weights alongside half model weights, computes the global grad norm
of the incoming (scaled) half grads — overflow is signalled by a non-finite
norm, reported as -1 like the reference (:103-128) — skips the step and
adjusts the loss scale on overflow, and otherwise hands the flat grads to
FusedAdam with the combined scale (:130-161).  Dynamic-scale bookkeeping
(:174-190) reuses the amp LossScaler state machine, which implements the
same halve-on-overflow / double-per-window policy.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .fused_adam import FusedAdam, AdamState
from ..amp.scaler import LossScaler, ScalerState
from ..multi_tensor_apply import global_grad_norm

__all__ = ["FP16_Optimizer", "FP16OptState"]


class FP16OptState(NamedTuple):
    masters: Any          # fp32 master pytree
    adam: AdamState
    scaler: ScalerState


class FP16_Optimizer:
    def __init__(self, init_optimizer: FusedAdam,
                 static_loss_scale: float = 1.0,
                 dynamic_loss_scale: bool = False,
                 dynamic_loss_args: Optional[dict] = None,
                 verbose: bool = True):
        if not isinstance(init_optimizer, FusedAdam):
            raise TypeError(
                "apex_tpu.optimizers.FP16_Optimizer is designed only for "
                "FusedAdam (like the reference, fp16_optimizer.py:28); use "
                "apex_tpu.fp16_utils.FP16_Optimizer for other optimizers.")
        self.optimizer = init_optimizer
        if dynamic_loss_scale:
            args = dynamic_loss_args or {}
            self.loss_scaler = LossScaler("dynamic", **args)
        else:
            self.loss_scaler = LossScaler(static_loss_scale)
        self.verbose = verbose

    # -- functional API ----------------------------------------------------
    def init(self, params: Any) -> FP16OptState:
        masters = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.float32), params)
        return FP16OptState(masters=masters,
                            adam=self.optimizer.init(masters),
                            scaler=self.loss_scaler.init_state())

    def loss_scale(self, state: FP16OptState) -> jax.Array:
        return state.scaler.loss_scale

    def scale_loss(self, loss: jax.Array, state: FP16OptState) -> jax.Array:
        return self.loss_scaler.scale_loss(loss, state.scaler)

    def backward(self, loss_fn, params: Any, state: FP16OptState, *args):
        """value_and_grad of the scaled loss (reference backward,
        fp16_optimizer.py:163-172). Returns (loss, scaled_grads)."""
        scale = state.scaler.loss_scale

        def scaled(p):
            return loss_fn(p, *args).astype(jnp.float32) * scale

        scaled_loss, grads = jax.value_and_grad(scaled)(params)
        return scaled_loss / scale, grads

    def step(self, params: Any, state: FP16OptState, scaled_grads: Any
             ) -> Tuple[Any, FP16OptState, dict]:
        """Grad-norm overflow check, skip-or-apply, master->model copy."""
        norm = global_grad_norm(scaled_grads)  # -1 on inf/nan (:103-128)
        found_inf = (norm < 0).astype(jnp.float32)
        new_sstate = self.loss_scaler.update(state.scaler, found_inf)
        scale = state.scaler.loss_scale

        def do_update(operand):
            p, masters, adam = operand
            new_masters, new_adam = self.optimizer.step(
                masters, adam, scaled_grads, scale=scale,
                grad_norm=jnp.maximum(norm, 0.0))
            new_p = jax.tree_util.tree_map(
                lambda m_, p_: m_.astype(p_.dtype), new_masters, p)
            return new_p, new_masters, new_adam

        new_params, new_masters, new_adam = jax.lax.cond(
            found_inf > 0, lambda op: op, do_update,
            (params, state.masters, state.adam))

        info = {"found_inf": found_inf, "grad_norm": norm,
                "loss_scale": new_sstate.loss_scale}
        return new_params, FP16OptState(masters=new_masters, adam=new_adam,
                                        scaler=new_sstate), info

    # -- checkpointing ("option 2": masters saved separately from model
    #    weights, reference fp16_optimizer.py:211-274) --------------------
    def state_dict(self, state: FP16OptState) -> dict:
        return {"masters": state.masters, "adam": state.adam._asdict(),
                "scaler": state.scaler._asdict()}

    def load_state_dict(self, sd: dict) -> FP16OptState:
        return FP16OptState(
            masters=sd["masters"],
            adam=AdamState(**sd["adam"]),
            scaler=ScalerState(**{k: jnp.asarray(v)
                                  for k, v in sd["scaler"].items()}))
