"""FusedLAMB: layer-wise adaptive large-batch optimizer.

The reference ships the LAMB CUDA kernels (csrc/multi_tensor_lamb_stage_1.cu,
multi_tensor_lamb_stage_2.cu, exposed at csrc/amp_C_frontend.cpp:50-53) but
no Python optimizer class (apex/optimizers/__init__.py:1-2 exports only
FusedAdam) — SURVEY.md §2.2 flags this gap and BASELINE config #5 requires
the optimizer.  This class implements the two-stage algorithm the kernels
encode:

stage 1 (multi_tensor_lamb_stage_1.cu:86-108): grads pre-scaled by the
clipped global norm, Adam-style m/v update with bias correction, producing
a per-parameter ``update = m^/(sqrt(v^)+eps) + weight_decay*p``.

stage 2 (multi_tensor_lamb_stage_2.cu:38-48,66-70): per-tensor trust ratio
``r = ||p|| / ||update||`` (1.0 when either norm is zero), then
``p -= lr * r * update``.

Per-tensor norms come from multi_tensor_l2norm(per_tensor=True)
(csrc/multi_tensor_l2norm_kernel.cu:117-180).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .base import Optimizer, resolve_lr
from ..multi_tensor_apply.flatten import ChunkedFlat, ChunkedFlatLayout

__all__ = ["FusedLAMB", "LambState"]


class LambState(NamedTuple):
    step: jax.Array
    m: Any   # ChunkedFlat fp32 moments over the padded fused buffer
    v: Any


class FusedLAMB(Optimizer):
    def __init__(self, lr=1e-3, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-6,
                 weight_decay: float = 0.01, amsgrad: bool = False,
                 adam_w_mode: bool = True, grad_averaging: bool = True,
                 max_grad_norm: float = 1.0, use_nvlamb: bool = False):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad "
                               "variant.")
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb

    def init(self, params: Any) -> LambState:
        layout = ChunkedFlatLayout(params)
        zeros = jnp.zeros((layout.total,), jnp.float32)
        return LambState(step=jnp.zeros((), jnp.int32),
                         m=ChunkedFlat(zeros, layout),
                         v=ChunkedFlat(zeros, layout))

    def update(self, grads: Any, state: LambState, params: Any):
        return self.step(params, state, grads)

    def step(self, params: Any, state: LambState, grads: Any,
             grad_norm: Optional[jax.Array] = None):
        """One LAMB step over the chunk-padded fused buffer.

        m/v live flat across steps (round-2 VERDICT item 7: no per-step
        tree re-pack of state), and the per-tensor ||p||/||update|| norms
        come from the layout's segment map — one dense pass + a tiny
        segment-sum, not a Python loop over leaves.  Padded slots carry
        zero grads, so m/v/update stay zero there and stage 2 leaves the
        (nonexistent) padded params untouched."""
        beta1, beta2 = self.betas
        t = state.step + 1
        tf = t.astype(jnp.float32)
        lr = resolve_lr(self.lr, state.step)
        beta3 = 1.0 - beta1 if self.grad_averaging else 1.0

        lay = state.m.layout
        g_flat = lay.pack(grads)
        p_flat = lay.pack(params)

        # global grad-norm clipping (stage_1.cu: grads scaled by
        # global_norm/max_norm when above threshold)
        if grad_norm is None:
            grad_norm = jnp.sqrt(jnp.sum(lay.per_tensor_sqsum(g_flat)))
        if self.max_grad_norm and self.max_grad_norm > 0:
            clip_factor = jnp.where(grad_norm > self.max_grad_norm,
                                    grad_norm / self.max_grad_norm, 1.0)
        else:
            clip_factor = jnp.ones((), jnp.float32)

        if self.bias_correction:
            bc1 = 1.0 - jnp.power(beta1, tf)
            bc2 = 1.0 - jnp.power(beta2, tf)
        else:
            bc1 = bc2 = jnp.ones((), jnp.float32)

        wd = self.weight_decay

        from ..ops import dispatch
        use_pallas = dispatch.use_pallas_for(params)
        if use_pallas:
            from ..ops import pallas_lamb
            upd, new_m, new_v = pallas_lamb.lamb_stage1(
                g_flat, p_flat, state.m.buf, state.v.buf, 1.0 / clip_factor,
                1.0 / bc1, 1.0 / bc2, beta1, beta2, beta3, self.eps, wd,
                self.adam_w_mode)
        else:
            g32 = g_flat / clip_factor
            if not self.adam_w_mode and wd:
                g32 = g32 + wd * p_flat  # classic L2 ("adam mode")
            new_m = beta1 * state.m.buf + beta3 * g32
            new_v = beta2 * state.v.buf + (1.0 - beta2) * g32 * g32
            upd = (new_m / bc1) / (jnp.sqrt(new_v / bc2) + self.eps)
            if self.adam_w_mode and wd:
                upd = upd + wd * p_flat  # decoupled decay enters the update

        # stage 2: per-tensor trust ratio (stage_2.cu:38-48)
        p_sq = lay.per_tensor_sqsum(p_flat)
        u_sq = lay.per_tensor_sqsum(upd)
        ratios = jnp.where((p_sq > 0) & (u_sq > 0),
                           jnp.sqrt(p_sq) / jnp.sqrt(u_sq),
                           jnp.ones_like(p_sq))
        ratio_flat = lay.expand_per_tensor(ratios)

        if use_pallas:
            new_p = pallas_lamb.lamb_stage2(p_flat, upd, ratio_flat, lr)
        else:
            new_p = p_flat - lr * ratio_flat * upd

        new_params = lay.unpack(
            new_p, like_leaves=jax.tree_util.tree_leaves(params))
        return new_params, LambState(step=t, m=ChunkedFlat(new_m, lay),
                                     v=ChunkedFlat(new_v, lay))
