"""FusedLAMB: layer-wise adaptive large-batch optimizer.

The reference ships the LAMB CUDA kernels (csrc/multi_tensor_lamb_stage_1.cu,
multi_tensor_lamb_stage_2.cu, exposed at csrc/amp_C_frontend.cpp:50-53) but
no Python optimizer class (apex/optimizers/__init__.py:1-2 exports only
FusedAdam) — SURVEY.md §2.2 flags this gap and BASELINE config #5 requires
the optimizer.  This class implements the two-stage algorithm the kernels
encode:

stage 1 (multi_tensor_lamb_stage_1.cu:86-108): grads pre-scaled by the
clipped global norm, Adam-style m/v update with bias correction, producing
a per-parameter ``update = m^/(sqrt(v^)+eps) + weight_decay*p``.

stage 2 (multi_tensor_lamb_stage_2.cu:38-48,66-70): per-tensor trust ratio
``r = ||p|| / ||update||`` (1.0 when either norm is zero), then
``p -= lr * r * update``.

Per-tensor norms come from multi_tensor_l2norm(per_tensor=True)
(csrc/multi_tensor_l2norm_kernel.cu:117-180).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .base import Optimizer, resolve_lr
from ..multi_tensor_apply import multi_tensor_l2norm

__all__ = ["FusedLAMB", "LambState"]


class LambState(NamedTuple):
    step: jax.Array
    m: Any   # pytree like params (per-tensor trust ratios need leaf identity)
    v: Any


class FusedLAMB(Optimizer):
    def __init__(self, lr=1e-3, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-6,
                 weight_decay: float = 0.01, amsgrad: bool = False,
                 adam_w_mode: bool = True, grad_averaging: bool = True,
                 max_grad_norm: float = 1.0, use_nvlamb: bool = False):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad "
                               "variant.")
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb

    def init(self, params: Any) -> LambState:
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        return LambState(step=jnp.zeros((), jnp.int32),
                         m=jax.tree_util.tree_map(zeros, params),
                         v=jax.tree_util.tree_map(zeros, params))

    def update(self, grads: Any, state: LambState, params: Any):
        return self.step(params, state, grads)

    def step(self, params: Any, state: LambState, grads: Any,
             grad_norm: Optional[jax.Array] = None):
        beta1, beta2 = self.betas
        t = state.step + 1
        tf = t.astype(jnp.float32)
        lr = resolve_lr(self.lr, state.step)
        beta3 = 1.0 - beta1 if self.grad_averaging else 1.0

        # global grad-norm clipping (stage_1.cu: grads scaled by
        # global_norm/max_norm when above threshold)
        if grad_norm is None:
            grad_norm, _ = multi_tensor_l2norm(grads)
        if self.max_grad_norm and self.max_grad_norm > 0:
            clip_factor = jnp.where(grad_norm > self.max_grad_norm,
                                    grad_norm / self.max_grad_norm, 1.0)
        else:
            clip_factor = jnp.ones((), jnp.float32)

        if self.bias_correction:
            bc1 = 1.0 - jnp.power(beta1, tf)
            bc2 = 1.0 - jnp.power(beta2, tf)
        else:
            bc1 = bc2 = jnp.ones((), jnp.float32)

        wd = self.weight_decay

        from ..ops import dispatch
        if dispatch.use_pallas_for(params):
            return self._step_pallas(params, state, grads, t, lr, beta1,
                                     beta2, beta3, bc1, bc2, clip_factor, wd)

        def stage1(p, g, m, v):
            g32 = g.astype(jnp.float32) / clip_factor
            p32 = p.astype(jnp.float32)
            if not self.adam_w_mode and wd:
                g32 = g32 + wd * p32  # classic L2 ("adam mode")
            new_m = beta1 * m + beta3 * g32
            new_v = beta2 * v + (1.0 - beta2) * g32 * g32
            m_hat = new_m / bc1
            v_hat = new_v / bc2
            upd = m_hat / (jnp.sqrt(v_hat) + self.eps)
            if self.adam_w_mode and wd:
                upd = upd + wd * p32  # decoupled decay enters the update
            return upd, new_m, new_v

        triples = jax.tree_util.tree_map(stage1, params, grads, state.m,
                                         state.v)
        is3 = lambda x: isinstance(x, tuple) and len(x) == 3
        updates = jax.tree_util.tree_map(lambda tr: tr[0], triples, is_leaf=is3)
        new_m = jax.tree_util.tree_map(lambda tr: tr[1], triples, is_leaf=is3)
        new_v = jax.tree_util.tree_map(lambda tr: tr[2], triples, is_leaf=is3)

        # stage 2: per-tensor trust ratio (stage_2.cu:38-48)
        def stage2(p, upd):
            p_norm = jnp.sqrt(jnp.sum(jnp.square(p.astype(jnp.float32))))
            u_norm = jnp.sqrt(jnp.sum(jnp.square(upd)))
            ratio = jnp.where((p_norm > 0) & (u_norm > 0), p_norm / u_norm,
                              jnp.ones((), jnp.float32))
            return (p.astype(jnp.float32) - lr * ratio * upd).astype(p.dtype)

        new_params = jax.tree_util.tree_map(stage2, params, updates)
        return new_params, LambState(step=t, m=new_m, v=new_v)

    def _step_pallas(self, params, state, grads, t, lr, beta1, beta2, beta3,
                     bc1, bc2, clip_factor, wd):
        """Flat-buffer kernel path: one stage-1 launch over the fused
        supervector, per-tensor trust ratios, one stage-2 launch."""
        from ..multi_tensor_apply.flatten import pack_flat, unpack_flat
        from ..ops import pallas_lamb

        g_flat, leaves, treedef = pack_flat(grads, jnp.float32)
        p_flat, p_leaves, _ = pack_flat(params, jnp.float32)
        m_flat, _, _ = pack_flat(state.m, jnp.float32)
        v_flat, _, _ = pack_flat(state.v, jnp.float32)

        upd_flat, new_m_flat, new_v_flat = pallas_lamb.lamb_stage1(
            g_flat, p_flat, m_flat, v_flat, 1.0 / clip_factor, 1.0 / bc1,
            1.0 / bc2, beta1, beta2, beta3, self.eps, wd, self.adam_w_mode)

        # per-tensor trust ratios (stage_2.cu:38-48) from
        # multi_tensor_l2norm's per-tensor output, expanded to per-element
        # for the apply kernel
        updates = unpack_flat(upd_flat, leaves, treedef, cast_like=False)
        _, p_norm = multi_tensor_l2norm(params, per_tensor=True)
        _, u_norm = multi_tensor_l2norm(updates, per_tensor=True)
        ratios = jnp.where((p_norm > 0) & (u_norm > 0), p_norm / u_norm,
                           jnp.ones_like(p_norm))
        sizes = [int(l.size) for l in p_leaves]
        ratio_flat = jnp.repeat(ratios, jnp.asarray(sizes),
                                total_repeat_length=p_flat.shape[0])

        new_p_flat = pallas_lamb.lamb_stage2(p_flat, upd_flat, ratio_flat, lr)

        new_params = unpack_flat(new_p_flat, p_leaves, treedef)
        new_m = unpack_flat(new_m_flat, leaves, treedef, cast_like=False)
        new_v = unpack_flat(new_v_flat, leaves, treedef, cast_like=False)
        return new_params, LambState(step=t, m=new_m, v=new_v)
