"""FusedLion: Lion (Chen et al. 2023, "Symbolic Discovery of
Optimization Algorithms") over one fused flat parameter buffer.

Beyond the reference's optimizer set (it ships Adam-era optimizers
only), but built with exactly its fused-buffer discipline
(apex/optimizers/fused_adam.py:50-147): one elementwise pass over the
flat fp32 buffer, grad unscale folded in, optional half-precision
parameter write-out in the same pass.  Lion is pure elementwise, so
the jnp expression IS the fused kernel after XLA fusion — a dedicated
Pallas kernel would add nothing (the op is bandwidth-bound with one
read/write per buffer).

    g~ = g / combined_scale
    u  = sign(b1*m + (1-b1)*g~)
    p -= lr * (u + weight_decay*p)          (decoupled decay)
    m  = b2*m + (1-b2)*g~

Memory: ONE moment buffer (half of Adam's optimizer state) — the
reason Lion matters at scale.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .base import Optimizer, resolve_lr
from ..multi_tensor_apply import multi_tensor_l2norm
from ..multi_tensor_apply.flatten import pack_flat, unpack_flat

__all__ = ["FusedLion", "LionState"]


class LionState(NamedTuple):
    step: jax.Array   # int32; number of applied updates
    m: jax.Array      # fp32 flat momentum


class FusedLion(Optimizer):
    elementwise = True
    supports_output_params_dtype = True

    def __init__(self, lr: float = 1e-4,
                 betas: Tuple[float, float] = (0.9, 0.99),
                 weight_decay: float = 0.0,
                 max_grad_norm: float = 0.0):
        self.lr = lr
        self.betas = betas
        self.weight_decay = weight_decay
        self.max_grad_norm = max_grad_norm

    def init(self, params: Any) -> LionState:
        n = sum(int(l.size) for l in jax.tree_util.tree_leaves(params))
        return LionState(step=jnp.zeros((), jnp.int32),
                         m=jnp.zeros((n,), jnp.float32))

    def update(self, grads: Any, state: LionState, params: Any):
        return self.step(params, state, grads)[:2]

    def step(self, params: Any, state: LionState, grads: Any,
             scale: float = 1.0, grad_norm: Optional[jax.Array] = None,
             output_params_dtype=None):
        """One fused Lion step; signature matches FusedAdam.step
        (scale/grad_norm/output_params_dtype contract)."""
        flat_g, _, _ = pack_flat(grads, jnp.float32)
        flat_p, p_leaves, p_treedef = pack_flat(params, jnp.float32)

        combined_scale = jnp.asarray(scale, jnp.float32)
        if self.max_grad_norm > 0:
            if grad_norm is None:
                grad_norm, _ = multi_tensor_l2norm(flat_g)
            clip = ((grad_norm / combined_scale) + 1e-6) \
                / self.max_grad_norm
            combined_scale = jnp.where(clip > 1.0,
                                       clip * combined_scale,
                                       combined_scale)

        beta1, beta2 = self.betas
        lr = resolve_lr(self.lr, state.step)
        gs = flat_g / combined_scale
        update = jnp.sign(beta1 * state.m + (1.0 - beta1) * gs)
        new_p = flat_p - lr * (update + self.weight_decay * flat_p)
        new_m = beta2 * state.m + (1.0 - beta2) * gs
        half = (new_p.astype(output_params_dtype)
                if output_params_dtype is not None else None)

        new_params = unpack_flat(new_p, p_leaves, p_treedef)
        new_state = LionState(step=state.step + 1, m=new_m)
        if output_params_dtype is not None:
            return new_params, new_state, half
        return new_params, new_state
