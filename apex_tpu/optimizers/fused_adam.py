"""FusedAdam: Adam over one fused flat parameter buffer.

TPU-native equivalent of apex.optimizers.FusedAdam (fused_adam.py:50-147)
backed by csrc/fused_adam_cuda_kernel.cu.  The CUDA kernel's fusion — one
grid-stride pass updating p/m/v with the grad unscale folded in, plus an
optional fp16 parameter write-out in the same kernel (:94-115) — maps here
to a single Pallas elementwise kernel over a flat fp32 buffer (or a jnp
expression XLA fuses identically off-TPU).

Math matches the reference exactly (fused_adam_cuda_kernel.cu:15-18,43-55,
83-91):

    g~ = g / combined_scale
    m  = b1*m + (1-b1)*g~
    v  = b2*v + (1-b2)*g~^2
    denom = sqrt(v + eps)        (eps_inside_sqrt / ADAM_MODE_0)
          | sqrt(v) + eps        (default / ADAM_MODE_1)
    step_size = lr * sqrt(1-b2^t) / (1-b1^t)   (bias correction, host-side)
    p -= step_size * (m/denom + weight_decay*p)

``combined_scale`` folds grad clipping via a precomputed global grad norm
(reference fused_adam.py:98-104).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .base import Optimizer, resolve_lr
from ..multi_tensor_apply import multi_tensor_l2norm
from ..multi_tensor_apply.flatten import pack_flat, unpack_flat

__all__ = ["FusedAdam", "AdamState"]


class AdamState(NamedTuple):
    step: jax.Array   # int32; number of applied updates
    m: jax.Array      # fp32 flat first moment
    v: jax.Array      # fp32 flat second moment


def _adam_kernel(p, m, v, g, step_size, combined_scale, beta1, beta2, eps,
                 eps_inside_sqrt, weight_decay, half_dtype=None):
    """The fused elementwise update on flat fp32 buffers; returns
    (new_p, new_m, new_v, optional half copy of new_p)."""
    from ..ops import dispatch
    if dispatch.use_pallas_for(p):
        from ..ops import pallas_adam
        return pallas_adam.fused_adam(
            p, m, v, g, step_size, combined_scale, beta1, beta2, eps,
            eps_inside_sqrt, weight_decay, half_dtype)
    gs = g / combined_scale
    new_m = beta1 * m + (1.0 - beta1) * gs
    new_v = beta2 * v + (1.0 - beta2) * gs * gs
    if eps_inside_sqrt:
        denom = jnp.sqrt(new_v + eps)
    else:
        denom = jnp.sqrt(new_v) + eps
    update = new_m / denom + weight_decay * p
    new_p = p - step_size * update
    half = new_p.astype(half_dtype) if half_dtype is not None else None
    return new_p, new_m, new_v, half


class FusedAdam(Optimizer):
    """Signature parity with the reference (fused_adam.py:17-49)."""

    # purely elementwise given scalars: safe on a fused flat buffer, and
    # the kernel can emit the half model copy in the same pass
    elementwise = True
    supports_output_params_dtype = True

    def __init__(self, lr=1e-3, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 eps_inside_sqrt: bool = False, weight_decay: float = 0.0,
                 max_grad_norm: float = 0.0, amsgrad: bool = False):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad "
                               "variant.")  # fused_adam.py:38
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.eps_inside_sqrt = eps_inside_sqrt
        self.weight_decay = weight_decay
        self.max_grad_norm = max_grad_norm

    # -- Optimizer protocol ------------------------------------------------
    def init(self, params: Any) -> AdamState:
        n = sum(int(l.size) for l in jax.tree_util.tree_leaves(params))
        return AdamState(step=jnp.zeros((), jnp.int32),
                         m=jnp.zeros((n,), jnp.float32),
                         v=jnp.zeros((n,), jnp.float32))

    def update(self, grads: Any, state: AdamState, params: Any):
        return self.step(params, state, grads)[:2]

    # -- reference-shaped step --------------------------------------------
    def step(self, params: Any, state: AdamState, grads: Any,
             scale: float = 1.0, grad_norm: Optional[jax.Array] = None,
             output_params_dtype=None):
        """One fused Adam step.

        ``scale``: grads are divided by this (loss scale; fused_adam.py:86).
        ``grad_norm``: precomputed global norm of the *scaled* grads for
        clipping (fused_adam.py:98-104); computed on the fly if
        ``max_grad_norm`` is set and none is given.
        ``output_params_dtype``: emit a half-precision copy of the updated
        params in the same pass (the kernel's p_copy, :94-115).
        Returns (new_params, new_state[, half_params]).
        """
        flat_g, _, _ = pack_flat(grads, jnp.float32)
        flat_p, p_leaves, p_treedef = pack_flat(params, jnp.float32)

        combined_scale = jnp.asarray(scale, jnp.float32)
        if self.max_grad_norm > 0:
            if grad_norm is None:
                grad_norm, _ = multi_tensor_l2norm(flat_g)
            clip = ((grad_norm / combined_scale) + 1e-6) / self.max_grad_norm
            combined_scale = jnp.where(clip > 1.0, clip * combined_scale,
                                       combined_scale)

        t = state.step + 1
        beta1, beta2 = self.betas
        lr = resolve_lr(self.lr, state.step)
        if self.bias_correction:
            tf = t.astype(jnp.float32)
            bc1 = 1.0 - jnp.power(beta1, tf)
            bc2 = 1.0 - jnp.power(beta2, tf)
            step_size = lr * jnp.sqrt(bc2) / bc1
        else:
            step_size = lr

        new_p, new_m, new_v, half = _adam_kernel(
            flat_p, state.m, state.v, flat_g, step_size, combined_scale,
            beta1, beta2, self.eps, self.eps_inside_sqrt, self.weight_decay,
            output_params_dtype)

        new_params = unpack_flat(new_p, p_leaves, p_treedef)
        new_state = AdamState(step=t, m=new_m, v=new_v)
        if output_params_dtype is not None:
            return new_params, new_state, half
        return new_params, new_state
