"""apex_tpu — TPU-native mixed-precision + data-parallel training toolkit.

A brand-new framework with the capabilities of NVIDIA Apex (reference:
/root/reference, apex/__init__.py:4-16), built idiomatically on JAX/XLA:

- ``apex_tpu.amp`` — automatic mixed precision: opt-levels O0-O3, dynamic
  loss scaling, op-level half/fp32 cast policies (reference: apex/amp).
- ``apex_tpu.parallel`` — DistributedDataParallel-style gradient psum over a
  device mesh, SyncBatchNorm with cross-chip Welford statistics, LARC,
  Reducer (reference: apex/parallel).
- ``apex_tpu.optimizers`` — FusedAdam / FusedLAMB / FP16_Optimizer backed by
  Pallas kernels over fused flat parameter buffers (reference:
  apex/optimizers + csrc/fused_adam_cuda*, csrc/multi_tensor_lamb*).
- ``apex_tpu.normalization`` — FusedLayerNorm (reference:
  apex/normalization/fused_layer_norm.py + csrc/layer_norm_cuda*).
- ``apex_tpu.fp16_utils`` — manual master-weight toolkit and the legacy
  FP16_Optimizer wrapper (reference: apex/fp16_utils).
- ``apex_tpu.nn`` — the minimal policy-aware layer library the amp machinery
  plugs into (the reference monkey-patches torch; we consult a dtype policy
  at op dispatch instead).

Unlike the reference, every fused kernel has a pure-jnp fallback selected
automatically off-TPU, mirroring Apex's graceful-degradation invariant
(reference README.md:90-95).
"""

from . import nn
from . import amp
from . import multi_tensor_apply
from . import optimizers
from . import normalization
from . import parallel
from . import fp16_utils
from . import RNN
from . import reparameterization
from . import transformer
from . import models
from . import utils
from . import data
from . import lora
from . import serving

__version__ = "0.1.0"
