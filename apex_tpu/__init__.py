"""apex_tpu — TPU-native mixed-precision + data-parallel training toolkit.

A brand-new framework with the capabilities of NVIDIA Apex (reference:
/root/reference, apex/__init__.py:4-16), built idiomatically on JAX/XLA:

- ``apex_tpu.amp`` — automatic mixed precision: opt-levels O0-O3, dynamic
  loss scaling, op-level half/fp32 cast policies (reference: apex/amp).
- ``apex_tpu.parallel`` — DistributedDataParallel-style gradient psum over a
  device mesh, SyncBatchNorm with cross-chip Welford statistics, LARC,
  Reducer (reference: apex/parallel).
- ``apex_tpu.optimizers`` — FusedAdam / FusedLAMB / FP16_Optimizer backed by
  Pallas kernels over fused flat parameter buffers (reference:
  apex/optimizers + csrc/fused_adam_cuda*, csrc/multi_tensor_lamb*).
- ``apex_tpu.normalization`` — FusedLayerNorm (reference:
  apex/normalization/fused_layer_norm.py + csrc/layer_norm_cuda*).
- ``apex_tpu.fp16_utils`` — manual master-weight toolkit and the legacy
  FP16_Optimizer wrapper (reference: apex/fp16_utils).
- ``apex_tpu.nn`` — the minimal policy-aware layer library the amp machinery
  plugs into (the reference monkey-patches torch; we consult a dtype policy
  at op dispatch instead).
- ``apex_tpu.observability`` — unified telemetry: metrics registry with
  device-resident training-step counters, span tracing over the profiler
  ranges, and JSONL / Chrome-trace / Prometheus exporters (the reference
  ships only nvtx ranges and an AverageMeter).

Unlike the reference, every fused kernel has a pure-jnp fallback selected
automatically off-TPU, mirroring Apex's graceful-degradation invariant
(reference README.md:90-95).
"""

import jax as _jax

if not hasattr(_jax, "shard_map"):
    # jax<0.5 compat: the codebase (and its tests) target the stable
    # ``jax.shard_map`` entry point with its ``check_vma`` kwarg; on
    # older jax fall back to the experimental version, mapping
    # check_vma to its pre-rename name check_rep.
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map_compat(f, mesh=None, in_specs=None, out_specs=None,
                          check_vma=None, **kw):
        if check_vma is not None:
            kw.setdefault("check_rep", check_vma)
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)

    _jax.shard_map = _shard_map_compat

if not hasattr(_jax.lax, "axis_size"):
    # same vintage gap: lax.axis_size (static size of a mapped axis)
    # predates this jax; jax.core.axis_frame returns exactly that int
    # (and raises NameError for an unbound axis, matching semantics)
    _jax.lax.axis_size = _jax.core.axis_frame

from . import nn
from . import amp
from . import multi_tensor_apply
from . import optimizers
from . import normalization
from . import parallel
from . import fp16_utils
from . import RNN
from . import reparameterization
from . import transformer
from . import models
from . import utils
from . import observability
from . import data
from . import lora
from . import serving
from . import fleet
from . import analysis

__version__ = "0.1.0"
