"""apex_tpu.reparameterization — weight reparameterization (weight norm).

Reference: apex/reparameterization/{__init__.py,reparameterization.py,
weight_norm.py} — a forward-pre-hook framework computing w = g * v/||v||.
NOTE: the reference snapshot is *broken* (weight_norm.py:3 imports a
``Fused_Weight_Norm`` that fp16_utils no longer exports; SURVEY.md §2.1);
this implementation supplies the working equivalent: the norm is computed
functionally at apply time, fused by XLA into the consumer matmul's
prologue.

``apply_weight_norm(module, name='weight', dim=0)`` wraps a module so its
params tree stores (name_g, name_v) instead of ``name``;
``remove_weight_norm`` bakes the current effective weight back in.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..nn.module import Module

__all__ = ["WeightNorm", "apply_weight_norm", "remove_weight_norm"]


def _norm_except_dim(v: jax.Array, dim: int) -> jax.Array:
    axes = tuple(a for a in range(v.ndim) if a != dim)
    return jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32)), axis=axes,
                            keepdims=True))


def compute_weight(g: jax.Array, v: jax.Array, dim: int,
                   eps: float = 0.0) -> jax.Array:
    n = _norm_except_dim(v, dim)
    return (g.astype(jnp.float32) * v.astype(jnp.float32) / (n + eps)
            ).astype(v.dtype)


class WeightNorm(Module):
    """Wrapper module: v-direction + g-magnitude parameterization of one
    of the inner module's params (reference weight_norm.py:39-78)."""

    def __init__(self, inner: Module, name: str = "weight", dim: int = 0):
        super().__init__()
        self.inner = inner
        self.param_name = name
        self.dim = dim

    def init(self, key):
        params, state = self.inner.init(key)
        inner_p = params.pop("inner", None)
        if inner_p is None:
            inner_p = params
        w = inner_p.pop(self.param_name)
        inner_p[self.param_name + "_v"] = w
        inner_p[self.param_name + "_g"] = _norm_except_dim(w, self.dim)
        return {"inner": inner_p}, state

    def forward(self, params, *args, **kwargs):
        p = dict(params["inner"])
        g = p.pop(self.param_name + "_g")
        v = p.pop(self.param_name + "_v")
        p[self.param_name] = compute_weight(g, v, self.dim)
        return self.inner(p, *args, **kwargs)


def apply_weight_norm(module: Module, name: str = "weight", dim: int = 0
                      ) -> WeightNorm:
    """Wrap ``module`` with weight normalization on param ``name``
    (reference reparameterization.py:56-102)."""
    return WeightNorm(module, name, dim)


def remove_weight_norm(wrapped: WeightNorm, params: dict) -> (Module, dict):
    """Bake the effective weight back into a plain params tree
    (reference reparameterization.py:127-137)."""
    inner_p = dict(params["inner"])
    g = inner_p.pop(wrapped.param_name + "_g")
    v = inner_p.pop(wrapped.param_name + "_v")
    inner_p[wrapped.param_name] = compute_weight(g, v, wrapped.dim)
    return wrapped.inner, inner_p
