"""Weight-only int8 quantization for inference/decoding.

Autoregressive decoding is HBM-bandwidth-bound: every generated token
re-reads every weight, and the MXU sits mostly idle.  Storing weights
as int8 with per-output-channel fp scales halves-to-quarters the bytes
per token; XLA fuses the dequantize (convert + broadcast-multiply) into
the consuming dot's operand read, so the stored tensor — what HBM
actually serves — stays int8.

This is the standard weight-only recipe (symmetric, per-channel,
round-to-nearest); nothing here touches training — the reference
toolkit's scope (SURVEY §2) ends at mixed-precision training, and this
module is the inference-side counterpart the switch-over user expects.

    qparams = quantization.quantize_for_decode(params)
    ids, n = model.generate(qparams, prompt, prompt_len, 64)

``QTensor`` is a pytree node, so quantized trees jit/donate/shard like
ordinary params; ``nn.functional.linear/matmul/embedding`` accept it
directly.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

__all__ = ["QTensor", "quantize", "quantize_for_decode"]


@jax.tree_util.register_pytree_node_class
class QTensor:
    """int8 data + fp scale, dequantizing to ``dtype`` on use.

    ``scale`` keeps ``data``'s rank (size 1 except on ``axis``) so
    ``dequant`` is a plain broadcast multiply.
    """

    def __init__(self, data, scale, axis: int, dtype=jnp.bfloat16):
        self.data = data
        self.scale = scale
        self.axis = axis
        self._dtype = jnp.dtype(dtype)

    @property
    def shape(self):
        return self.data.shape

    @property
    def ndim(self):
        return self.data.ndim

    @property
    def dtype(self):
        """The LOGICAL dtype: what consumers see after dequant."""
        return self._dtype

    def dequant(self, dtype=None):
        d = dtype or self._dtype
        return self.data.astype(d) * self.scale.astype(d)

    # -- array-surface shims ------------------------------------------------
    # Weight consumers overwhelmingly do ``w.T`` / ``w.astype(dt)`` /
    # ``jnp.matmul(x, w.T)``; giving QTensor these two methods (both
    # dequantize — XLA fuses the convert+scale into the consuming dot)
    # makes every existing call site work without isinstance guards.
    # Ops with a cheaper quantized form (row gather) use ``take()``.
    @property
    def T(self):
        return self.dequant().T

    def astype(self, dtype):
        return self.dequant(dtype)

    def take(self, ids):
        """Row gather (embedding lookup) without dequantizing the whole
        table: only the gathered rows convert."""
        if self.axis != 0:
            raise ValueError("take() needs per-row (axis=0) scales")
        rows = jnp.take(self.data, ids, axis=0).astype(self._dtype)
        return rows * jnp.take(self.scale, ids, axis=0).astype(self._dtype)

    def tree_flatten(self):
        return (self.data, self.scale), (self.axis, self._dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], aux[1])

    def __repr__(self):
        return (f"QTensor(int8{list(self.shape)}, axis={self.axis}, "
                f"dtype={self._dtype.name})")


def quantize(w, axis: int = 0, dtype=jnp.bfloat16) -> QTensor:
    """Symmetric per-channel int8: scale = amax/127 over all dims except
    ``axis`` (the output-channel dim: rows of a torch-layout (out, in)
    Linear weight, rows of a (V, D) embedding table)."""
    w = jnp.asarray(w)
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=reduce_axes,
                   keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return QTensor(q.astype(jnp.int8), scale, axis, dtype)


def quantize_for_decode(params: Any, dtype=jnp.bfloat16,
                        min_size: int = 4096) -> Any:
    """Quantize every 2-D ``weight`` leaf (Linear matrices, embedding
    tables) of at least ``min_size`` elements; 1-D leaves (LayerNorm,
    biases) and small tensors stay in floating point.  Structure is
    preserved, so the result drops into ``model.generate``/``apply``
    wherever the fp params did."""
    def walk(node):
        if isinstance(node, dict):
            out = {}
            for k, v in node.items():
                if (k == "weight" and hasattr(v, "ndim") and v.ndim == 2
                        and not isinstance(v, QTensor)
                        and v.size >= min_size
                        and jnp.issubdtype(v.dtype, jnp.floating)):
                    out[k] = quantize(v, axis=0, dtype=dtype)
                else:
                    out[k] = walk(v)
            return out
        return node
    return walk(params)
