"""apex_tpu.transformer — attention, transformer blocks, and
sequence/context parallelism (ring attention over the mesh).

New capability relative to the 2019 reference (which has no attention,
SURVEY.md §5): long-context support is first-class in apex_tpu.
"""

from .attention import dot_product_attention, MultiheadAttention
from .ring_attention import ring_attention, ring_self_attention
from .ulysses import ulysses_attention, ulysses_self_attention
