"""Ulysses-style all-to-all sequence parallelism.

The complementary long-context strategy to ring attention: instead of
rotating K/V blocks around the ring (O(n) ppermute steps), two
``lax.all_to_all`` collectives re-shard the activations from
sequence-sharded to *head*-sharded and back:

    (B, H, T/n, D)  --all_to_all-->  (B, H/n, T, D)
         attention over the full sequence on H/n local heads
    (B, H/n, T, D)  --all_to_all-->  (B, H, T/n, D)

Each device then computes exact attention over the full sequence for its
slice of heads — no online-softmax bookkeeping, two collectives total.
On TPU the all_to_all rides ICI; prefer Ulysses when H >= n and the
sequence is long enough that ring's n ppermute latencies dominate, ring
when head count is the binding constraint.

Use inside shard_map/pmap with the sequence axis mapped, like
ring_attention.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .attention import dot_product_attention

__all__ = ["ulysses_attention", "ulysses_self_attention"]


def _seq_to_head_sharded(x: jax.Array, axis_name: str) -> jax.Array:
    """(B, H, T/n, D) -> (B, H/n, T, D): scatter heads, gather sequence."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def _head_to_seq_sharded(x: jax.Array, axis_name: str) -> jax.Array:
    """(B, H/n, T, D) -> (B, H, T/n, D): scatter sequence, gather heads."""
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str = "sp", causal: bool = False,
                      scale: Optional[float] = None,
                      kv_mask: Optional[jax.Array] = None,
                      dropout_rate: float = 0.0,
                      dropout_rng: Optional[jax.Array] = None) -> jax.Array:
    """q, k, v: (B, H, T_local, D) per-device sequence-sharded slices;
    returns the exact attention output for the local queries against the
    global sequence, identical (up to fp reassociation) to
    ``ring_attention`` on the same operands — for every query that has at
    least one valid key.  (Degenerate fully-masked rows differ by
    construction: ring and the flash kernel emit zeros, while the dense
    softmax fallback degrades to a uniform average over the keys.)

    ``kv_mask``: optional (B, T_local) bool key-validity slice, sharded
    over the sequence axis like k.  It is all_gathered to the global
    (B, T) — a tiny collective next to the K/V all_to_alls — and rides
    the flash kernel's streamed key-padding path on the head-sharded
    attention."""
    n = lax.psum(1, axis_name)
    H = q.shape[1]
    if H % n != 0:
        raise ValueError(
            f"ulysses_attention needs head count divisible by the sp axis "
            f"size, got H={H}, n={n}; use ring_attention instead")
    if dropout_rate and dropout_rng is None:
        # same contract as ring_attention: the functional SP wrappers
        # require an explicit key (no silent no-op outside an apply
        # context)
        raise ValueError("dropout_rate > 0 requires dropout_rng")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])

    qh = _seq_to_head_sharded(q, axis_name)
    kh = _seq_to_head_sharded(k, axis_name)
    vh = _seq_to_head_sharded(v, axis_name)

    mask4 = None
    if kv_mask is not None:
        gmask = lax.all_gather(kv_mask, axis_name, axis=1, tiled=True)
        mask4 = gmask[:, None, None, :]

    # dropout rides the flash kernel's in-kernel counter hash on the
    # head-sharded attention; the device index is folded into the key —
    # the hash sees only call-local (b, h_local) indices, so a shared
    # key would give every head-group the same mask
    rng_dev = (jax.random.fold_in(dropout_rng, lax.axis_index(axis_name))
               if dropout_rate else None)
    out = dot_product_attention(qh, kh, vh, mask4, scale=scale,
                                causal=causal, dropout_rate=dropout_rate,
                                dropout_rng=rng_dev)

    return _head_to_seq_sharded(out, axis_name)


def ulysses_self_attention(x: jax.Array, wqkv: jax.Array, wo: jax.Array,
                           num_heads: int, axis_name: str = "sp",
                           causal: bool = False,
                           kv_mask: Optional[jax.Array] = None
                           ) -> jax.Array:
    """Fused qkv-projection + ulysses attention + output projection for
    (B, T_local, E) sequence-sharded activations (the q/k/v projections
    stay sequence-sharded — pure local matmuls).  ``kv_mask``:
    (B, T_local) key-validity slice, as in :func:`ulysses_attention`."""
    B, T, E = x.shape
    hd = E // num_heads
    qkv = jnp.einsum("bte,fe->btf", x, wqkv)
    qkv = qkv.reshape(B, T, 3, num_heads, hd)
    q, k, v = (jnp.moveaxis(qkv[:, :, i], 2, 1) for i in range(3))
    ctx = ulysses_attention(q, k, v, axis_name=axis_name, causal=causal,
                            kv_mask=kv_mask)
    ctx = jnp.moveaxis(ctx, 1, 2).reshape(B, T, E)
    return jnp.einsum("bte,fe->btf", ctx, wo)
