"""Attention primitives (policy-aware, MXU-shaped).

The reference (2019 Apex) predates attention entirely (SURVEY.md §5:
long-context is absent there).  apex_tpu treats long-context as
first-class: this module provides the single-device attention core; the
sequence-parallel forms (ring attention over a mesh axis) live in
apex_tpu.transformer.ring_attention.

The inner matmuls route through the amp policy ("dot_product_attention" is
whitelisted → bf16 on the MXU) while the softmax runs in fp32 (blacklist),
matching the reference's cast philosophy applied to a new op.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..nn import functional as F
from ..nn.module import Module, current_context
from ..nn.layers import Linear, Dropout

__all__ = ["dot_product_attention", "MultiheadAttention",
           "set_path_hook"]

# Trace-time debug hook: parity harnesses comparing backends need to know
# which path a call compiled to, because flash vs dense differ
# statistically (dropout masks) and on fully-masked rows (see the
# dot_product_attention docstring).  The hook receives "flash" or
# "dense" each time dispatch resolves (at trace time, so once per
# compilation, not per step).
_path_hook = None


def set_path_hook(hook) -> None:
    """Install ``hook(path: str)`` (or None to clear).  A setter rather
    than a rebindable module global: ``from ... import path_hook`` would
    capture the value and assignments to it would silently install
    nothing."""
    global _path_hook
    _path_hook = hook


def _note_path(path: str) -> None:
    if _path_hook is not None:
        _path_hook(path)


def dot_product_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          mask: Optional[jax.Array] = None,
                          scale: Optional[float] = None,
                          dropout_rate: float = 0.0,
                          causal: bool = False,
                          dropout_rng: Optional[jax.Array] = None,
                          segment_ids: Optional[jax.Array] = None
                          ) -> jax.Array:
    """q,k,v: (..., T, H) — softmax(qk^T/sqrt(H)) v with fp32 softmax.

    ``dropout_rate`` applies attention-probability dropout in train mode
    (rng drawn from the active apply-context, like nn.Dropout) — or
    unconditionally when an explicit ``dropout_rng`` is given (the
    functional path: the caller owns the train/eval decision, e.g. the
    sequence-parallel wrappers fold the device index into this key).
    ``segment_ids``: (B, T) int32 packed-sequence ids — attention is
    restricted to equal-id pairs (streamed through the flash kernel on
    TPU; applied as an equality mask on the dense path).
    ``causal=True`` applies the lower-triangular mask; on TPU this (and
    the mask-free case) dispatches to the fused Pallas flash kernel.
    Key-padding masks — a ``mask`` with no query-position dependence,
    shaped ``(B, 1, 1, Tk)`` (or with leading broadcast dims of 1) —
    ALSO stay on the flash path: the kernel streams the key-validity row
    alongside the K/V blocks.  Train-mode attention dropout stays on the
    flash path too (in-kernel counter-hash mask; the dense path and the
    kernel draw different masks from the rng, so expect statistical, not
    bitwise, agreement between backends).  Only arbitrary per-pair mask
    shapes take the dense path.

    Caveat on fully-masked rows: flash emits zeros for a query whose
    keys are all masked, while the dense softmax degrades to a uniform
    average over all keys; real key-padding batches always keep at least
    one valid key per sequence."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got "
                         f"{dropout_rate}")
    if segment_ids is not None:
        if q.ndim != 4:
            raise ValueError("segment_ids requires (B, H, T, D) operands")
        expect = (q.shape[0], k.shape[-2])
        if segment_ids.shape != expect:
            raise ValueError(f"segment_ids must be (B, T) = {expect}, "
                             f"got {segment_ids.shape}")
    ctx = current_context()
    train_dropout = (dropout_rate > 0.0
                     and (dropout_rng is not None
                          or (ctx is not None and ctx.train)))
    B = q.shape[0] if q.ndim == 4 else None
    Tk = k.shape[-2]
    kv_mask = None
    if (mask is not None and q.ndim == 4 and mask.ndim == 4
            and mask.shape[-2] == 1 and mask.shape[1] == 1
            and mask.shape[0] in (1, B) and mask.shape[-1] == Tk):
        kv_mask = jnp.broadcast_to(mask[:, 0, 0, :] != 0, (B, Tk))
    if ((mask is None or kv_mask is not None)
            and q.ndim == 4 and q.shape == k.shape == v.shape):
        from ..ops import dispatch
        if dispatch.use_pallas_for(q):
            from ..ops import pallas_flash_attention as pfa
            if pfa.fits_vmem(q.shape[2], q.shape[3],
                             dropout=train_dropout,
                             segments=segment_ids is not None):
                # same cast policy the dense path applies through its
                # whitelisted matmuls (op 'dot_product_attention' is in
                # amp.lists.FP16_FUNCS), so dtype is backend-independent
                from ..amp import policy as _pol
                (q, k, v), _ = _pol.cast_op_args("dot_product_attention",
                                                 (q, k, v), {})
                seed = None
                if train_dropout:
                    # both 32-bit key words feed the kernel's counter
                    # hash — a single word would collide by birthday
                    # bound over ~1e6 layer x step draws
                    key = (dropout_rng if dropout_rng is not None
                           else ctx.make_rng())
                    seed = jax.lax.bitcast_convert_type(
                        jax.random.key_data(key), jnp.int32)
                _note_path("flash")
                return pfa.flash_attention(
                    q, k, v, causal=causal, scale=scale, kv_mask=kv_mask,
                    dropout_rate=(dropout_rate if train_dropout else 0.0),
                    dropout_seed=seed, segment_ids=segment_ids)
    _note_path("dense")
    if causal:
        Tq, Tk = q.shape[-2], k.shape[-2]
        # decode-style alignment: the last query attends to the full key
        # sequence (q_pos = Tk - Tq + i); reduces to lower-triangular
        # when Tq == Tk.  A user mask (e.g. padding) ANDs with the
        # causal constraint — it must never replace it.
        qpos = Tk - Tq + jnp.arange(Tq)
        cmask = qpos[:, None] >= jnp.arange(Tk)[None, :]
        mask = cmask if mask is None else jnp.logical_and(mask, cmask)
    scores = F.matmul(q, jnp.swapaxes(k, -1, -2)).astype(jnp.float32) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, jnp.full_like(scores, -1e30))
    if segment_ids is not None:
        seg = (segment_ids[:, None, :, None]
               == segment_ids[:, None, None, :])
        scores = jnp.where(seg, scores, jnp.full_like(scores, -1e30))
    probs = jax.nn.softmax(scores, axis=-1)
    if train_dropout:
        key = dropout_rng if dropout_rng is not None else ctx.make_rng()
        probs = F.dropout(probs, dropout_rate, key)
    return F.matmul(probs.astype(v.dtype), v)


class MultiheadAttention(Module):
    def __init__(self, embed_dim: int, num_heads: int, dropout: float = 0.0,
                 bias: bool = True):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError(f"num_heads ({num_heads}) must divide "
                             f"embed_dim ({embed_dim})")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.qkv = Linear(embed_dim, 3 * embed_dim, bias=bias)
        self.out = Linear(embed_dim, embed_dim, bias=bias)
        self.drop = Dropout(dropout)

    def forward(self, params, x, mask: Optional[jax.Array] = None,
                key_padding_mask: Optional[jax.Array] = None):
        """``key_padding_mask``: (B, T) bool, True = IGNORE that key —
        torch.nn.MultiheadAttention's convention.  Internally inverted to
        key-validity and routed as a (B, 1, 1, T) mask, which the flash
        dispatch streams through the kernel."""
        B, T, E = x.shape
        qkv = self.qkv(params["qkv"], x)
        qkv = qkv.reshape(B, T, 3, self.num_heads, self.head_dim)
        q, k, v = (jnp.moveaxis(qkv[:, :, i], 2, 1) for i in range(3))
        if key_padding_mask is not None:
            kp = jnp.logical_not(key_padding_mask)[:, None, None, :]
            mask = kp if mask is None else jnp.logical_and(mask, kp)
        ctx = dot_product_attention(q, k, v, mask)
        ctx = jnp.moveaxis(ctx, 1, 2).reshape(B, T, E)
        ctx = self.drop(params.get("drop", {}), ctx)
        return self.out(params["out"], ctx)
