"""Ring attention: exact attention over a sequence-sharded mesh axis.

Long-context support is first-class in apex_tpu (the 2019 reference has
none — SURVEY.md §5).  Sequence is sharded across the ``sp`` mesh axis;
each device holds a (B, H, T/n, D) slice of q/k/v.  K/V blocks rotate
around the ring via ``lax.ppermute`` (ICI neighbor exchange) while each
device accumulates flash-attention-style online-softmax statistics
(running max m, normalizer l, weighted accumulator acc) — so the full
T×T score matrix never materializes and memory stays O(T/n · T/n) per
step.  XLA overlaps the ppermute DMA of step i+1's block with step i's
matmuls, which is the point of the ring formulation on TPU.

Use inside shard_map/pmap with the sequence axis mapped::

    out = ring_attention(q, k, v, axis_name="sp", causal=True)
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ring_attention", "ring_self_attention"]


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = "sp", causal: bool = False,
                   scale: Optional[float] = None,
                   remat: bool = True,
                   kv_mask: Optional[jax.Array] = None,
                   dropout_rate: float = 0.0,
                   dropout_rng: Optional[jax.Array] = None) -> jax.Array:
    """q, k, v: (B, H, T_local, D) per-device slices; returns the exact
    attention output for the local queries against the *global* sequence.

    ``remat=True`` (default) wraps each ring step's score/softmax math in
    ``jax.checkpoint``: without it, reverse-mode AD saves the
    (B, H, Tq, Tk) probability block of every step — O(T_local·T_global)
    residual memory, the quadratic cost the ring exists to avoid.  With
    it, only the linear-memory carries (the rotating K/V blocks and the
    online-softmax state) are saved and scores are recomputed in the
    backward, flash-attention style.  The ppermutes stay outside the
    checkpoint so the backward re-runs matmuls, not communication.

    ``kv_mask``: optional (B, T_local) bool key-validity slice, sharded
    over the sequence axis like k; the mask block rotates around the
    ring alongside its K/V block.  Queries whose keys are ALL masked
    produce zero output rows.

    ``dropout_rate`` + ``dropout_rng``: attention-probability dropout
    with the flash placement (undropped softmax normalizer, dropped+
    rescaled value accumulation).  The per-step mask is drawn from
    ``fold_in(rng, device_index, step)``, so it is deterministic given
    the rng — the remat'd backward regenerates the identical mask."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    dropout_rate = float(dropout_rate)
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got "
                         f"{dropout_rate}")
    if dropout_rate and dropout_rng is None:
        raise ValueError("dropout_rate > 0 requires dropout_rng")
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, H, Tq, D = q.shape
    Tk = k.shape[2]

    q32 = q.astype(jnp.float32) * scale

    acc0 = jnp.zeros((B, H, Tq, D), jnp.float32)
    m0 = jnp.full((B, H, Tq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Tq, 1), jnp.float32)

    q_pos = my * Tq + jnp.arange(Tq)
    # has_mask is a trace-time constant: the unmasked path carries no
    # validity block — no third ppermute per step, no extra where over
    # the (B, H, Tq, Tk) scores
    has_mask = kv_mask is not None

    def block(q32, k_blk, v_blk, kvm_blk, m, l, acc, src):
        scores = jnp.einsum("bhqd,bhkd->bhqk", q32,
                            k_blk.astype(jnp.float32))
        if kvm_blk is not None:
            scores = jnp.where(kvm_blk[:, None, None, :], scores, -jnp.inf)
        if causal:
            kv_pos = src * Tk + jnp.arange(Tk)
            mask = q_pos[:, None] >= kv_pos[None, :]
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        blk_max = jnp.max(scores, axis=-1, keepdims=True)
        new_m = jnp.maximum(m, blk_max)
        # fully-masked rows keep m=-inf; guard the exp
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        p = jnp.exp(scores - safe_m)
        p = jnp.where(jnp.isfinite(scores), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        # the normalizer uses the UNdropped probabilities; only the value
        # accumulation is dropped+rescaled (flash dropout placement)
        new_l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_rate:
            from ..nn import functional as F
            key = jax.random.fold_in(jax.random.fold_in(dropout_rng, my),
                                     src)
            p = F.dropout(p, dropout_rate, key)
        new_acc = acc * corr + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        return new_m, new_l, new_acc

    if remat:
        # prevent_cse=False: the fori_loop lowers to scan, whose loop
        # structure already rules out the CSE hazard the default barrier
        # guards against — and the barrier would block XLA from
        # overlapping the block math with the ppermute DMA
        block = jax.checkpoint(block, prevent_cse=False)

    def body(i, carry):
        if has_mask:
            k_blk, v_blk, kvm_blk, m, l, acc = carry
        else:
            k_blk, v_blk, m, l, acc = carry
            kvm_blk = None
        src = (my - i) % n  # whose kv block we hold at step i
        m, l, acc = block(q32, k_blk, v_blk, kvm_blk, m, l, acc, src)
        # rotate kv (and its validity block) to the next ring neighbor
        nxt = [(j, (j + 1) % n) for j in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, nxt)
        v_blk = lax.ppermute(v_blk, axis_name, nxt)
        if has_mask:
            kvm_blk = lax.ppermute(kvm_blk, axis_name, nxt)
            return k_blk, v_blk, kvm_blk, m, l, acc
        return k_blk, v_blk, m, l, acc

    carry0 = ((k, v, kv_mask.astype(jnp.bool_), m0, l0, acc0) if has_mask
              else (k, v, m0, l0, acc0))
    out_carry = lax.fori_loop(0, n, body, carry0)
    m, l, acc = out_carry[-3], out_carry[-2], out_carry[-1]
    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def ring_self_attention(x: jax.Array, wqkv: jax.Array, wo: jax.Array,
                        num_heads: int, axis_name: str = "sp",
                        causal: bool = False,
                        kv_mask: Optional[jax.Array] = None) -> jax.Array:
    """Convenience fused qkv-projection + ring attention + output proj for
    (B, T_local, E) sequence-sharded activations.  ``kv_mask``:
    (B, T_local) key-validity slice, as in :func:`ring_attention`."""
    B, T, E = x.shape
    hd = E // num_heads
    qkv = jnp.einsum("bte,fe->btf", x, wqkv)
    qkv = qkv.reshape(B, T, 3, num_heads, hd)
    q, k, v = (jnp.moveaxis(qkv[:, :, i], 2, 1) for i in range(3))
    ctx = ring_attention(q, k, v, axis_name=axis_name, causal=causal,
                         kv_mask=kv_mask)
    ctx = jnp.moveaxis(ctx, 1, 2).reshape(B, T, E)
    return jnp.einsum("bte,fe->btf", ctx, wo)
