"""FusedLayerNorm: layer norm with explicit fused fwd/bwd.

Equivalent of apex.normalization.FusedLayerNorm
(apex/normalization/fused_layer_norm.py) over csrc/layer_norm_cuda.cpp /
layer_norm_cuda_kernel.cu.  The contract preserved from the reference:

- input viewed as (n1, n2) = (rows, normalized size) (layer_norm_cuda.cpp:7-27),
- forward returns output and saves fp32 (mean, invvar) per row for backward
  even for half inputs (cpp:133,155),
- backward produces (dx, dgamma, dbeta) via a row-reduction + two-stage
  gamma/beta reduction (kernel.cu:403-638).

Here forward/backward are a jax.custom_vjp pair; on TPU the row reductions
dispatch to the Pallas kernels in apex_tpu.ops.pallas_layer_norm, elsewhere
they are jnp reductions XLA fuses.  The custom VJP exists so the Pallas
backward kernel can be swapped in without touching autodiff, and so the
saved activations match the reference's (input, mean, invvar) layout.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from .. import nn
from ..nn.module import Module

__all__ = ["FusedLayerNorm", "fused_layer_norm", "fused_layer_norm_affine"]


def _norm_axes(x, normalized_shape):
    return tuple(range(x.ndim - len(normalized_shape), x.ndim))


def _fwd_stats(x2: jax.Array, eps: float) -> Tuple[jax.Array, jax.Array]:
    """Per-row fp32 (mean, invvar) on the (n1, n2) view.  Shifted two-pass
    variance: numerically equivalent to the reference's Welford pass
    (layer_norm_cuda_kernel.cu:11-50) without E[x^2]-mean^2 cancellation."""
    x32 = x2.astype(jnp.float32)
    mean = jnp.mean(x32, axis=1)
    var = jnp.mean(jnp.square(x32 - mean[:, None]), axis=1)
    invvar = lax.rsqrt(var + eps)
    return mean, invvar


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _layer_norm_core(x2, weight, bias, n2: int, eps: float):
    out, _, _ = _layer_norm_fwd_impl(x2, weight, bias, n2, eps)
    return out


def _layer_norm_fwd_impl(x2, weight, bias, n2, eps):
    from ..ops import dispatch
    if dispatch.use_pallas_for(x2):
        from ..ops import pallas_layer_norm
        return pallas_layer_norm.forward(x2, weight, bias, eps)
    mean, invvar = _fwd_stats(x2, eps)
    xhat = (x2.astype(jnp.float32) - mean[:, None]) * invvar[:, None]
    y = xhat
    if weight is not None:
        y = y * weight.astype(jnp.float32)[None, :]
    if bias is not None:
        y = y + bias.astype(jnp.float32)[None, :]
    return y.astype(x2.dtype), mean, invvar


def _layer_norm_fwd(x2, weight, bias, n2, eps):
    out, mean, invvar = _layer_norm_fwd_impl(x2, weight, bias, n2, eps)
    return out, (x2, weight, bias, mean, invvar)


def _layer_norm_bwd(n2, eps, res, dy):
    x2, weight, bias, mean, invvar = res
    from ..ops import dispatch
    if dispatch.use_pallas_for(x2):
        from ..ops import pallas_layer_norm
        return pallas_layer_norm.backward(dy, x2, weight, bias, mean, invvar)
    dy32 = dy.astype(jnp.float32)
    x32 = x2.astype(jnp.float32)
    xhat = (x32 - mean[:, None]) * invvar[:, None]
    if weight is not None:
        dy_g = dy32 * weight.astype(jnp.float32)[None, :]
    else:
        dy_g = dy32
    c1 = jnp.mean(dy_g, axis=1, keepdims=True)
    c2 = jnp.mean(dy_g * xhat, axis=1, keepdims=True)
    dx = (invvar[:, None] * (dy_g - c1 - xhat * c2)).astype(x2.dtype)
    dw = db = None
    if weight is not None:
        dw = jnp.sum(dy32 * xhat, axis=0).astype(weight.dtype)
    if bias is not None:
        db = jnp.sum(dy32, axis=0).astype(bias.dtype)
    return dx, dw, db


_layer_norm_core.defvjp(_layer_norm_fwd, _layer_norm_bwd)


def fused_layer_norm(x: jax.Array, normalized_shape: Union[int, Sequence[int]],
                     weight: Optional[jax.Array] = None,
                     bias: Optional[jax.Array] = None,
                     eps: float = 1e-5) -> jax.Array:
    """Functional fused layer norm (affine when weight/bias given)."""
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    normalized_shape = tuple(normalized_shape)
    n2 = 1
    for s in normalized_shape:
        n2 *= s
    n1 = x.size // n2
    x2 = x.reshape(n1, n2)
    w = weight.reshape(-1) if weight is not None else None
    b = bias.reshape(-1) if bias is not None else None
    out = _layer_norm_core(x2, w, b, n2, eps)
    return out.reshape(x.shape)


def fused_layer_norm_affine(x, weight, bias, normalized_shape, eps=1e-5):
    return fused_layer_norm(x, normalized_shape, weight, bias, eps)


class FusedLayerNorm(Module):
    """Module parity with apex.normalization.FusedLayerNorm
    (fused_layer_norm.py:57-165): same constructor, affine & non-affine."""

    fp32_params = True

    def __init__(self, normalized_shape: Union[int, Sequence[int]],
                 eps: float = 1e-5, elementwise_affine: bool = True):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.eps = eps
        self.elementwise_affine = elementwise_affine

    def create_params(self, key):
        if not self.elementwise_affine:
            return {}
        return {"weight": jnp.ones(self.normalized_shape, jnp.float32),
                "bias": jnp.zeros(self.normalized_shape, jnp.float32)}

    def forward(self, params, x):
        return fused_layer_norm(x, self.normalized_shape,
                                params.get("weight"), params.get("bias"),
                                self.eps)
