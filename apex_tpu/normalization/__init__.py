"""apex_tpu.normalization — fused normalization layers
(reference: apex/normalization/__init__.py)."""

from .fused_layer_norm import (FusedLayerNorm, fused_layer_norm,
                               fused_layer_norm_affine)
