"""Kernel dispatch: Pallas on TPU, jnp fallback elsewhere.

The reference gates its CUDA extensions behind lazy imports with Python
fallbacks (apex/multi_tensor_apply/__init__.py:1-4, README.md:90-95); here
the gate is the JAX backend plus an env-var kill switch, and the fallback
is the pure-jnp path which is bitwise-comparable in tests.

Env vars:
  APEX_TPU_DISABLE_PALLAS=1   force the jnp path everywhere
  APEX_TPU_FORCE_PALLAS=1     force Pallas (interpret mode off-TPU; slow,
                              used by kernel parity tests)
"""

from __future__ import annotations

import os
from typing import Any

import jax

_KERNELS_AVAILABLE = None


def kernels_available() -> bool:
    """True iff the Pallas kernel modules import cleanly (the analogue of
    the reference's `import amp_C` probe, multi_tensor_apply/__init__.py:1-4)."""
    global _KERNELS_AVAILABLE
    if _KERNELS_AVAILABLE is None:
        try:
            from . import pallas_multi_tensor  # noqa: F401
            from . import pallas_adam  # noqa: F401
            from . import pallas_layer_norm  # noqa: F401
            from . import pallas_lamb  # noqa: F401
            from . import pallas_syncbn  # noqa: F401
            from . import pallas_flash_attention  # noqa: F401
            _KERNELS_AVAILABLE = True
        except ImportError:
            _KERNELS_AVAILABLE = False
    return _KERNELS_AVAILABLE


def backend() -> str:
    return jax.default_backend()


def pallas_enabled() -> bool:
    """APEX_TPU_FORCE_PALLAS accepts two values: "1" forces every Pallas
    path including the parity-test-only ops (pallas_forced), and "prod"
    reproduces the production TPU gating off-TPU — kernels that are
    actually dispatched on hardware (fused Adam/LAMB, multi-tensor,
    flash attention) run Pallas while ops XLA fuses better (BN apply)
    stay jnp.  The L1 cross-product driver trains under "prod" so its
    bitwise comparison matches what hardware executes."""
    if os.environ.get("APEX_TPU_DISABLE_PALLAS") == "1":
        return False
    if not kernels_available():
        return False
    if os.environ.get("APEX_TPU_FORCE_PALLAS") in ("1", "prod"):
        return True
    return backend() == "tpu"


def interpret_mode() -> bool:
    """Pallas interpret=True is needed off-TPU (CPU tests)."""
    return backend() != "tpu"


def pallas_forced() -> bool:
    """True only under APEX_TPU_FORCE_PALLAS=1 (kernel parity tests).

    Ops whose jnp form XLA fuses into neighbouring computation for free
    (e.g. the BatchNorm scale+shift apply) gate on this instead of
    ``pallas_enabled()``: a standalone kernel there forces an extra HBM
    round-trip and an (8,128)-misaligned NCHW tiling — measured at ~3x
    the whole ResNet-50 forward (round-3 profiling).  The fused kernels
    that *beat* XLA (flash attention, fused Adam, multi-tensor scale over
    one flat buffer) keep using ``pallas_enabled()``."""
    if os.environ.get("APEX_TPU_DISABLE_PALLAS") == "1":
        return False
    return (os.environ.get("APEX_TPU_FORCE_PALLAS") == "1"
            and kernels_available())


def use_pallas_for(tree: Any) -> bool:
    if not pallas_enabled():
        return False
    leaves = jax.tree_util.tree_leaves(tree)
    return bool(leaves)
