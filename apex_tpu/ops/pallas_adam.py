"""Pallas fused Adam kernel.

Equivalent of csrc/fused_adam_cuda_kernel.cu:15-55: one pass over the flat
(p, m, v, g) buffers computing the scaled-grad Adam update, with the
optional half-precision parameter write-out (p_copy, :94-115) fused into
the same pass.  Bias correction is folded into ``step_size`` host-side
(:83-91), matching the reference.

Inputs are fp32 flat buffers viewed as (rows, 128); p/m/v are updated via
``input_output_aliases`` so the kernel is in-place on device memory.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_common import (LANES, from_2d, interpret, pick_block_rows,
                            to_2d)


def _adam_kernel(scal_ref, p_ref, m_ref, v_ref, g_ref,
                 p_out, m_out, v_out, *half_out, beta1, beta2, eps,
                 eps_inside_sqrt, weight_decay, half_dtype):
    step_size = scal_ref[0, 0]
    inv_scale = scal_ref[0, 1]
    g = g_ref[:].astype(jnp.float32) * inv_scale
    p = p_ref[:]
    m = beta1 * m_ref[:] + (1.0 - beta1) * g
    v = beta2 * v_ref[:] + (1.0 - beta2) * g * g
    if eps_inside_sqrt:
        denom = jnp.sqrt(v + eps)
    else:
        denom = jnp.sqrt(v) + eps
    update = m / denom + weight_decay * p
    new_p = p - step_size * update
    p_out[:] = new_p
    m_out[:] = m
    v_out[:] = v
    if half_dtype is not None:
        # the fp16/bf16 parameter write-out fused into the same pass
        # (the reference kernel's p_copy, fused_adam_cuda_kernel.cu:94-115)
        half_out[0][:] = new_p.astype(half_dtype)


@functools.partial(
    jax.jit, static_argnames=("beta1", "beta2", "eps", "eps_inside_sqrt",
                              "weight_decay", "half_dtype"))
def _adam_flat(p, m, v, g, step_size, combined_scale, *, beta1, beta2, eps,
               eps_inside_sqrt, weight_decay, half_dtype):
    # shard-aware block sizing: a ZeRO master shard (1/ici or 1/world
    # of the model) must stay ONE kernel launch without padding up to a
    # full 512-row block — pick_block_rows shrinks the block (multiple
    # of the fp32 min-tile sublanes) for sub-block buffers
    block_rows = pick_block_rows(p.shape[0])
    p2, n = to_2d(p, block_rows)
    m2, _ = to_2d(m, block_rows)
    v2, _ = to_2d(v, block_rows)
    g2, _ = to_2d(g, block_rows)
    rows = p2.shape[0]
    grid = rows // block_rows
    blk = lambda: pl.BlockSpec((block_rows, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)
    scal = jnp.stack([jnp.asarray(step_size, jnp.float32),
                      1.0 / jnp.asarray(combined_scale, jnp.float32)]
                     ).reshape(1, 2)
    out_specs = [blk(), blk(), blk()]
    out_shape = [jax.ShapeDtypeStruct((rows, LANES), jnp.float32)] * 3
    if half_dtype is not None:
        out_specs.append(blk())
        out_shape.append(jax.ShapeDtypeStruct((rows, LANES), half_dtype))
    outs = pl.pallas_call(
        functools.partial(_adam_kernel, beta1=beta1, beta2=beta2, eps=eps,
                          eps_inside_sqrt=eps_inside_sqrt,
                          weight_decay=weight_decay, half_dtype=half_dtype),
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  blk(), blk(), blk(), blk()],
        out_specs=out_specs,
        out_shape=out_shape,
        input_output_aliases={1: 0, 2: 1, 3: 2},
        interpret=interpret(),
    )(scal, p2, m2, v2, g2)
    new_p2, new_m2, new_v2 = outs[:3]
    half = from_2d(outs[3], n) if half_dtype is not None else None
    return from_2d(new_p2, n), from_2d(new_m2, n), from_2d(new_v2, n), half


def fused_adam(p, m, v, g, step_size, combined_scale, beta1, beta2, eps,
               eps_inside_sqrt, weight_decay, half_dtype=None
               ) -> Tuple[jax.Array, jax.Array, jax.Array,
                          Optional[jax.Array]]:
    """Flat-buffer fused Adam step; signature mirrors the jnp reference
    path in apex_tpu.optimizers.fused_adam._adam_kernel."""
    return _adam_flat(p, m, v, g, step_size, combined_scale,
                      beta1=float(beta1), beta2=float(beta2), eps=float(eps),
                      eps_inside_sqrt=bool(eps_inside_sqrt),
                      weight_decay=float(weight_decay),
                      half_dtype=half_dtype)
