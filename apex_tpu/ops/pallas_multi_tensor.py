"""Pallas multi-tensor kernels: scale / axpby / l2norm over fused buffers.

TPU-native equivalents of the reference's amp_C kernels, contracts per
SURVEY.md §2.2:

- scale  (csrc/multi_tensor_scale_kernel.cu:64-73): out = in * scale with
  the overflow flag raised on any non-finite *input* — the fused
  unscale+overflow-check of the loss scaler.
- axpby  (csrc/multi_tensor_axpby_kernel.cu:67-84): out = a*x + b*y with
  the finite check on x, y, or both.
- l2norm (csrc/multi_tensor_l2norm_kernel.cu:47-114): fp32 global L2 norm
  via partial sums and a cleanup reduction.

Each kernel makes one pass over a (rows, 128) view of the fused buffer.
The flag / norm accumulator is a single (1, 1) SMEM output revisited by
every grid step — TPU grid iterations execute sequentially, so the
read-modify-write accumulation replaces the reference's atomically-set
``noop_gmem`` flag and two-kernel cleanup reduction.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_common import (BLOCK_ROWS, LANES, from_2d, interpret, pack_flat,
                            to_2d, unpack_flat)


def _row_blk():
    return pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)


def _acc_blk():
    # single (1,1) accumulator revisited by every grid step
    return pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)


def _scale_kernel(scale_ref, x_ref, out_ref, flag_ref):
    @pl.when(pl.program_id(0) == 0)
    def _():
        flag_ref[0, 0] = 0.0
    x = x_ref[:].astype(jnp.float32)
    out_ref[:] = x * scale_ref[0, 0]
    bad = jnp.where(jnp.all(jnp.isfinite(x)), 0.0, 1.0)
    flag_ref[0, 0] = jnp.maximum(flag_ref[0, 0], bad)


@functools.partial(jax.jit, static_argnames=("check_finite",))
def _scale_flat(flat: jax.Array, scale: jax.Array, check_finite: bool = True
                ) -> Tuple[jax.Array, jax.Array]:
    x2, n = to_2d(flat)
    rows = x2.shape[0]
    grid = rows // BLOCK_ROWS
    out2, flag = pl.pallas_call(
        _scale_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            _row_blk(),
        ],
        out_specs=[_row_blk(), _acc_blk()],
        out_shape=[
            jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret(),
    )(jnp.asarray(scale, jnp.float32).reshape(1, 1), x2)
    found_inf = flag[0, 0] if check_finite else jnp.zeros((), jnp.float32)
    return from_2d(out2, n), found_inf


def multi_tensor_scale(tree: Any, scale, check_finite: bool = True
                       ) -> Tuple[Any, jax.Array]:
    flat, leaves, treedef = pack_flat(tree, jnp.float32)
    if not leaves:
        return tree, jnp.zeros((), jnp.float32)
    out, found_inf = _scale_flat(flat, jnp.asarray(scale, jnp.float32),
                                 check_finite)
    return unpack_flat(out, leaves, treedef), found_inf


def _axpby_kernel(ab_ref, x_ref, y_ref, out_ref, flag_ref, *, arg_to_check):
    @pl.when(pl.program_id(0) == 0)
    def _():
        flag_ref[0, 0] = 0.0
    x = x_ref[:].astype(jnp.float32)
    y = y_ref[:].astype(jnp.float32)
    out_ref[:] = ab_ref[0, 0] * x + ab_ref[0, 1] * y
    if arg_to_check == 0:
        finite = jnp.all(jnp.isfinite(x))
    elif arg_to_check == 1:
        finite = jnp.all(jnp.isfinite(y))
    else:
        finite = jnp.all(jnp.isfinite(x)) & jnp.all(jnp.isfinite(y))
    flag_ref[0, 0] = jnp.maximum(flag_ref[0, 0],
                                 jnp.where(finite, 0.0, 1.0))


@functools.partial(jax.jit, static_argnames=("arg_to_check",))
def _axpby_flat(xf, yf, a, b, arg_to_check):
    x2, n = to_2d(xf)
    y2, _ = to_2d(yf)
    rows = x2.shape[0]
    grid = rows // BLOCK_ROWS
    out2, flag = pl.pallas_call(
        functools.partial(_axpby_kernel, arg_to_check=arg_to_check),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            _row_blk(),
            _row_blk(),
        ],
        out_specs=[_row_blk(), _acc_blk()],
        out_shape=[
            jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret(),
    )(jnp.asarray([a, b], jnp.float32).reshape(1, 2), x2, y2)
    return from_2d(out2, n), flag[0, 0]


def multi_tensor_axpby(a, b, x_tree: Any, y_tree: Any, arg_to_check: int = -1
                       ) -> Tuple[Any, jax.Array]:
    xf, leaves, treedef = pack_flat(x_tree, jnp.float32)
    if not leaves:
        return x_tree, jnp.zeros((), jnp.float32)
    yf, _, _ = pack_flat(y_tree, jnp.float32)
    out, found_inf = _axpby_flat(xf, yf, jnp.asarray(a, jnp.float32),
                                 jnp.asarray(b, jnp.float32),
                                 int(arg_to_check))
    return unpack_flat(out, leaves, treedef), found_inf


def _l2norm_kernel(x_ref, acc_ref):
    @pl.when(pl.program_id(0) == 0)
    def _():
        acc_ref[0, 0] = 0.0
    x = x_ref[:].astype(jnp.float32)
    acc_ref[0, 0] += jnp.sum(x * x)


@jax.jit
def _l2norm_flat(flat: jax.Array) -> jax.Array:
    x2, _ = to_2d(flat)
    rows = x2.shape[0]
    grid = rows // BLOCK_ROWS
    acc = pl.pallas_call(
        _l2norm_kernel,
        grid=(grid,),
        in_specs=[_row_blk()],
        out_specs=_acc_blk(),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
        interpret=interpret(),
    )(x2)
    return jnp.sqrt(acc[0, 0])


def multi_tensor_l2norm(tree: Any, per_tensor: bool = False
                        ) -> Tuple[jax.Array, Optional[jax.Array]]:
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        z = jnp.zeros((), jnp.float32)
        return z, (jnp.zeros((0,), jnp.float32) if per_tensor else None)
    if per_tensor:
        if all(jnp.issubdtype(jnp.result_type(x), jnp.floating)
               for x in leaves):
            # one dense pass + a tiny segment-sum over the chunk-padded
            # fused buffer (the reference's per-tensor output buffer,
            # l2norm_kernel.cu:117-180) — replaces round-1's per-leaf
            # Python loop of ~2 reductions per leaf
            from ..multi_tensor_apply.flatten import ChunkedFlatLayout
            lay = ChunkedFlatLayout(tree)
            sq = lay.per_tensor_sqsum(lay.pack(tree))
        else:
            # keep positional alignment with tree_leaves when non-float
            # leaves are present
            sq = jnp.stack([jnp.sum(jnp.square(x.astype(jnp.float32)))
                            for x in leaves])
        return jnp.sqrt(jnp.sum(sq)), jnp.sqrt(sq)
    flat, _, _ = pack_flat(tree, jnp.float32)
    return _l2norm_flat(flat), None
