"""apex_tpu.ops — Pallas TPU kernels and their dispatch layer.

Kernel inventory (TPU-native equivalents of the reference csrc/ tree):
  pallas_multi_tensor — scale / axpby / l2norm over fused flat buffers
                        (csrc/multi_tensor_*.cu)
  pallas_adam         — fused Adam step with optional half write-out
                        (csrc/fused_adam_cuda_kernel.cu)
  pallas_layer_norm   — fused LayerNorm fwd/bwd row reductions
                        (csrc/layer_norm_cuda_kernel.cu)
  pallas_lamb         — LAMB stage1/stage2 (csrc/multi_tensor_lamb_stage_*.cu)
  pallas_syncbn       — fused BatchNorm normalize-apply fwd/bwd
                        (csrc/welford.cu:298-318,325-410)
  pallas_flash_attention — fused attention fwd/bwd (no reference
                        equivalent: the 2019 snapshot predates attention)
"""

from . import dispatch
