"""Pallas fused LayerNorm forward/backward row-reduction kernels.

Equivalent of csrc/layer_norm_cuda_kernel.cu: forward is a per-row Welford
pass producing (out, fp32 mean, fp32 invvar) (:51-245, host :640-668);
backward fuses the dx computation (:522-638) and produces per-block partial
gamma/beta gradients (:403-470) that a jnp epilogue reduces (:471-521) —
the same two-stage structure, with stage 2 left to XLA.

The (n1, n2) row view is padded to (rows multiple of block, cols multiple
of 128); column masking keeps the statistics exact for arbitrary n2.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_common import LANES, interpret

_VMEM_BUDGET = 4 * 1024 * 1024  # per-operand block budget (bytes)


def _block_rows(C: int) -> int:
    br = _VMEM_BUDGET // (C * 4)
    br = max(8, min(256, br))
    return (br // 8) * 8


def _pad2(x, R, C):
    r, c = x.shape
    if r == R and c == C:
        return x
    return jnp.pad(x, ((0, R - r), (0, C - c)))


def _fwd_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, inv_ref, *, n2, eps):
    x = x_ref[:].astype(jnp.float32)
    mask = lax.broadcasted_iota(jnp.int32, x.shape, 1) < n2
    xm = jnp.where(mask, x, 0.0)
    mean = jnp.sum(xm, axis=1, keepdims=True) / n2
    # shifted two-pass variance: the block is already resident in VMEM, so
    # a second read costs nothing and avoids the E[x^2]-mean^2 catastrophic
    # cancellation the reference's single-pass Welford exists to prevent
    # (layer_norm_cuda_kernel.cu:11-50)
    d = jnp.where(mask, x - mean, 0.0)
    var = jnp.sum(d * d, axis=1, keepdims=True) / n2
    inv = lax.rsqrt(var + eps)
    y = (x - mean) * inv * w_ref[:].astype(jnp.float32) + \
        b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean
    inv_ref[:] = inv


@functools.partial(jax.jit, static_argnames=("n2", "eps", "out_dtype"))
def _fwd(x2, w, b, *, n2, eps, out_dtype):
    n1 = x2.shape[0]
    C = -(-n2 // LANES) * LANES
    BR = _block_rows(C)
    R = -(-max(n1, 1) // BR) * BR
    xp = _pad2(x2, R, C)
    wp = jnp.pad(w.astype(jnp.float32), (0, C - n2)).reshape(1, C)
    bp = jnp.pad(b.astype(jnp.float32), (0, C - n2)).reshape(1, C)
    grid = R // BR
    row_blk = pl.BlockSpec((BR, C), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
    vec_blk = pl.BlockSpec((1, C), lambda i: (0, 0),
                           memory_space=pltpu.VMEM)
    col_blk = pl.BlockSpec((BR, 1), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
    y, mean, inv = pl.pallas_call(
        functools.partial(_fwd_kernel, n2=n2, eps=eps),
        grid=(grid,),
        in_specs=[row_blk, vec_blk, vec_blk],
        out_specs=[row_blk, col_blk, col_blk],
        out_shape=[jax.ShapeDtypeStruct((R, C), out_dtype),
                   jax.ShapeDtypeStruct((R, 1), jnp.float32),
                   jax.ShapeDtypeStruct((R, 1), jnp.float32)],
        interpret=interpret(),
    )(xp, wp, bp)
    return y[:n1, :n2], mean[:n1, 0], inv[:n1, 0]


def forward(x2: jax.Array, weight: Optional[jax.Array],
            bias: Optional[jax.Array], eps: float):
    n1, n2 = x2.shape
    w = weight if weight is not None else jnp.ones((n2,), jnp.float32)
    b = bias if bias is not None else jnp.zeros((n2,), jnp.float32)
    y, mean, inv = _fwd(x2, w, b, n2=n2, eps=float(eps),
                        out_dtype=x2.dtype)
    return y, mean, inv


def _bwd_kernel(dy_ref, x_ref, w_ref, mean_ref, inv_ref,
                dx_ref, dw_ref, db_ref, *, n2):
    # dw/db are (1, C) accumulators revisited by every (sequential) grid
    # step — the fused form of the reference's two-stage partial-buffer
    # reduction (layer_norm_cuda_kernel.cu:403-521)
    @pl.when(pl.program_id(0) == 0)
    def _():
        dw_ref[:] = jnp.zeros_like(dw_ref)
        db_ref[:] = jnp.zeros_like(db_ref)
    dy = dy_ref[:].astype(jnp.float32)
    x = x_ref[:].astype(jnp.float32)
    mask = lax.broadcasted_iota(jnp.int32, x.shape, 1) < n2
    mean = mean_ref[:]
    inv = inv_ref[:]
    xhat = (x - mean) * inv
    dy = jnp.where(mask, dy, 0.0)
    dy_g = dy * w_ref[:].astype(jnp.float32)
    c1 = jnp.sum(dy_g, axis=1, keepdims=True) / n2
    c2 = jnp.sum(dy_g * xhat, axis=1, keepdims=True) / n2
    dx = inv * (dy_g - c1 - xhat * c2)
    dx_ref[:] = dx.astype(dx_ref.dtype)
    dw_ref[:] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_ref[:] += jnp.sum(dy, axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=("n2", "in_dtype"))
def _bwd(dy2, x2, w, mean, inv, *, n2, in_dtype):
    n1 = x2.shape[0]
    C = -(-n2 // LANES) * LANES
    BR = _block_rows(C)
    R = -(-max(n1, 1) // BR) * BR
    xp = _pad2(x2, R, C)
    dyp = _pad2(dy2, R, C)
    wp = jnp.pad(w.astype(jnp.float32), (0, C - n2)).reshape(1, C)
    meanp = jnp.pad(mean.reshape(-1, 1), ((0, R - n1), (0, 0)))
    invp = jnp.pad(inv.reshape(-1, 1), ((0, R - n1), (0, 0)))
    grid = R // BR
    row_blk = pl.BlockSpec((BR, C), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
    vec_blk = pl.BlockSpec((1, C), lambda i: (0, 0),
                           memory_space=pltpu.VMEM)
    col_blk = pl.BlockSpec((BR, 1), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
    acc_blk = pl.BlockSpec((1, C), lambda i: (0, 0),
                           memory_space=pltpu.VMEM)
    dx, dwa, dba = pl.pallas_call(
        functools.partial(_bwd_kernel, n2=n2),
        grid=(grid,),
        in_specs=[row_blk, row_blk, vec_blk, col_blk, col_blk],
        out_specs=[row_blk, acc_blk, acc_blk],
        out_shape=[jax.ShapeDtypeStruct((R, C), in_dtype),
                   jax.ShapeDtypeStruct((1, C), jnp.float32),
                   jax.ShapeDtypeStruct((1, C), jnp.float32)],
        interpret=interpret(),
    )(dyp, xp, wp, meanp, invp)
    return dx[:n1, :n2], dwa[0, :n2], dba[0, :n2]


def backward(dy: jax.Array, x2: jax.Array, weight: Optional[jax.Array],
             bias: Optional[jax.Array], mean: jax.Array, inv: jax.Array):
    n1, n2 = x2.shape
    w = weight if weight is not None else jnp.ones((n2,), jnp.float32)
    dx, dw, db = _bwd(dy, x2, w, mean, inv, n2=n2, in_dtype=x2.dtype)
    dw_out = dw.astype(weight.dtype) if weight is not None else None
    db_out = db.astype(bias.dtype) if bias is not None else None
    return dx, dw_out, db_out
