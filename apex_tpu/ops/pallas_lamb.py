"""Pallas LAMB stage-1 / stage-2 kernels.

Equivalent of csrc/multi_tensor_lamb_stage_1.cu:86-108 and
multi_tensor_lamb_stage_2.cu:38-48,66-70: stage 1 is one pass over the flat
(g, p, m, v) buffers producing the Adam-style ``update`` tensor with the
grad pre-scaled by the clipped global norm; stage 2 applies the per-tensor
trust ratio ``||p|| / ||update||``.

The reference passes per-tensor trust ratios through a separate
param_norm/update_norm tensor pair indexed by tensor id; here the ratios
are expanded to a flat per-element buffer (a static-shape ``jnp.repeat``
XLA folds into the surrounding fusion) so stage 2 stays a single
elementwise kernel over the fused supervector.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_common import (LANES, from_2d, interpret, pick_block_rows,
                            to_2d)


def _stage1_kernel(scal_ref, g_ref, p_ref, m_ref, v_ref,
                   upd_out, m_out, v_out, *, beta1, beta2, beta3, eps,
                   weight_decay, adam_w_mode):
    inv_clip = scal_ref[0, 0]
    inv_bc1 = scal_ref[0, 1]
    inv_bc2 = scal_ref[0, 2]
    g = g_ref[:].astype(jnp.float32) * inv_clip
    p = p_ref[:]
    if not adam_w_mode and weight_decay:
        g = g + weight_decay * p  # classic L2 enters the gradient
    m = beta1 * m_ref[:] + beta3 * g
    v = beta2 * v_ref[:] + (1.0 - beta2) * g * g
    upd = (m * inv_bc1) / (jnp.sqrt(v * inv_bc2) + eps)
    if adam_w_mode and weight_decay:
        upd = upd + weight_decay * p  # decoupled decay enters the update
    upd_out[:] = upd
    m_out[:] = m
    v_out[:] = v


@functools.partial(
    jax.jit, static_argnames=("beta1", "beta2", "beta3", "eps",
                              "weight_decay", "adam_w_mode"))
def _stage1_flat(g, p, m, v, inv_clip, inv_bc1, inv_bc2, *, beta1, beta2,
                 beta3, eps, weight_decay, adam_w_mode):
    # shard-aware block sizing (see pick_block_rows): a ZeRO shard
    # update stays one launch instead of padding to a full block
    block_rows = pick_block_rows(g.shape[0])
    g2, n = to_2d(g, block_rows)
    p2, _ = to_2d(p, block_rows)
    m2, _ = to_2d(m, block_rows)
    v2, _ = to_2d(v, block_rows)
    rows = g2.shape[0]
    grid = rows // block_rows
    blk = lambda: pl.BlockSpec((block_rows, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)
    scal = jnp.stack([jnp.asarray(inv_clip, jnp.float32),
                      jnp.asarray(inv_bc1, jnp.float32),
                      jnp.asarray(inv_bc2, jnp.float32)]).reshape(1, 3)
    upd2, new_m2, new_v2 = pl.pallas_call(
        functools.partial(_stage1_kernel, beta1=beta1, beta2=beta2,
                          beta3=beta3, eps=eps, weight_decay=weight_decay,
                          adam_w_mode=adam_w_mode),
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  blk(), blk(), blk(), blk()],
        out_specs=[blk(), blk(), blk()],
        out_shape=[jax.ShapeDtypeStruct((rows, LANES), jnp.float32)] * 3,
        input_output_aliases={3: 1, 4: 2},
        interpret=interpret(),
    )(scal, g2, p2, m2, v2)
    return from_2d(upd2, n), from_2d(new_m2, n), from_2d(new_v2, n)


def _stage2_kernel(lr_ref, p_ref, upd_ref, ratio_ref, p_out):
    lr = lr_ref[0, 0]
    p_out[:] = p_ref[:] - lr * ratio_ref[:] * upd_ref[:]


@jax.jit
def _stage2_flat(p, upd, ratio, lr):
    block_rows = pick_block_rows(p.shape[0])
    p2, n = to_2d(p, block_rows)
    upd2, _ = to_2d(upd, block_rows)
    ratio2, _ = to_2d(ratio, block_rows)
    rows = p2.shape[0]
    grid = rows // block_rows
    blk = lambda: pl.BlockSpec((block_rows, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)
    lr_s = jnp.asarray(lr, jnp.float32).reshape(1, 1)
    new_p2 = pl.pallas_call(
        _stage2_kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  blk(), blk(), blk()],
        out_specs=blk(),
        out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
        input_output_aliases={1: 0},
        interpret=interpret(),
    )(lr_s, p2, upd2, ratio2)
    return from_2d(new_p2, n)


def lamb_stage1(g, p, m, v, inv_clip, inv_bc1, inv_bc2, beta1, beta2, beta3,
                eps, weight_decay, adam_w_mode
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Flat-buffer LAMB stage 1 -> (update, new_m, new_v)."""
    return _stage1_flat(g, p, m, v, inv_clip, inv_bc1, inv_bc2,
                        beta1=float(beta1), beta2=float(beta2),
                        beta3=float(beta3), eps=float(eps),
                        weight_decay=float(weight_decay),
                        adam_w_mode=bool(adam_w_mode))


def lamb_stage2(p, upd, ratio, lr) -> jax.Array:
    """Flat-buffer LAMB stage 2: p -= lr * ratio * update, with ``ratio``
    the per-element expansion of the per-tensor trust ratios."""
    return _stage2_flat(p, upd, ratio, lr)
