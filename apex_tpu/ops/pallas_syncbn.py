"""Pallas fused BatchNorm normalize-apply kernels (fwd + bwd).

Equivalent of the reference's syncbn elementwise kernels: forward apply
``batchnorm_forward`` (csrc/welford.cu:298-318) and the backward pair
``reduce_bn`` (per-channel sum_dy / sum_dy_xmu + dgamma/dbeta,
welford.cu:325-383) and ``batchnorm_backward`` (dx apply, :387-410).

Division of labor (SURVEY.md §2.2 TPU sketch): the *cross-device* Welford/
Chan stat merge lives in SyncBatchNorm._sync_stats as a psum — jax
autodiff of that psum produces the allreduced mean_dy/mean_dy_xmu pattern
of the reference's backward (optimized_sync_batchnorm_kernel.py:92-97) with
no custom collective code here.  This kernel's custom_vjp therefore only
has to treat (x, mean, var, w, b) as independent inputs and return local
gradients; the chain rule through the stats supplies the rest.

Layout: NCHW viewed as (N*C, H*W) rows — each row one (sample, channel)
plane, per-row scalars (mean, inv_std, w, b) carried as (rows, 1) column
operands, lanes padded to 128 with masking.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_common import LANES, interpret

_VMEM_BUDGET = 8 * 1024 * 1024


def _block_rows(C: int, n_row_operands: int) -> int:
    """Rows per grid block, budgeted across every row-sized operand the
    kernel keeps resident (x2 for the grid pipeline's double buffering) so
    ImageNet-scale planes (hw ~ 112*112) still fit VMEM."""
    br = _VMEM_BUDGET // (C * 4 * n_row_operands * 2)
    return max(8, min(256, (br // 8) * 8))


def fits_vmem(hw: int) -> bool:
    """True if the minimum 8-row block of the 3-operand backward fits the
    budget; callers fall back to the jnp path for larger planes."""
    Cpad = -(-hw // LANES) * LANES
    return Cpad * 4 * 8 * 3 * 2 <= _VMEM_BUDGET


def _pad2(x, R, C):
    r, c = x.shape
    if r == R and c == C:
        return x
    return jnp.pad(x, ((0, R - r), (0, C - c)))


def _fwd_kernel(x_ref, mean_ref, inv_ref, w_ref, b_ref, y_ref):
    x = x_ref[:].astype(jnp.float32)
    y = (x - mean_ref[:]) * inv_ref[:] * w_ref[:] + b_ref[:]
    y_ref[:] = y.astype(y_ref.dtype)


def _bwd_kernel(dy_ref, x_ref, mean_ref, inv_ref, w_ref,
                dx_ref, sdy_ref, sdyx_ref, *, hw):
    dy = dy_ref[:].astype(jnp.float32)
    x = x_ref[:].astype(jnp.float32)
    mask = lax.broadcasted_iota(jnp.int32, x.shape, 1) < hw
    dy = jnp.where(mask, dy, 0.0)
    xhat = jnp.where(mask, (x - mean_ref[:]) * inv_ref[:], 0.0)
    dx_ref[:] = (dy * w_ref[:] * inv_ref[:]).astype(dx_ref.dtype)
    sdy_ref[:] = jnp.sum(dy, axis=1, keepdims=True)
    sdyx_ref[:] = jnp.sum(dy * xhat, axis=1, keepdims=True)


def _rowify(v, N):
    """(C,) channel vector -> (N*C, 1) per-row column."""
    return jnp.tile(v.astype(jnp.float32), N).reshape(-1, 1)


@functools.partial(jax.jit, static_argnames=("eps",))
def _fwd(x4, mean, var, w, b, *, eps):
    N, Cch, H, W = x4.shape
    hw = H * W
    rows = N * Cch
    Cpad = -(-hw // LANES) * LANES
    BR = _block_rows(Cpad, 2)  # resident row operands: x, y
    R = -(-rows // BR) * BR
    xp = _pad2(x4.reshape(rows, hw), R, Cpad)
    inv = lax.rsqrt(var.astype(jnp.float32) + eps)
    cols = [_pad2(_rowify(v, N), R, 1) for v in (mean, inv, w, b)]
    row_blk = pl.BlockSpec((BR, Cpad), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
    col_blk = pl.BlockSpec((BR, 1), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
    y = pl.pallas_call(
        _fwd_kernel,
        grid=(R // BR,),
        in_specs=[row_blk, col_blk, col_blk, col_blk, col_blk],
        out_specs=row_blk,
        out_shape=jax.ShapeDtypeStruct((R, Cpad), x4.dtype),
        interpret=interpret(),
    )(xp, *cols)
    return y[:rows, :hw].reshape(N, Cch, H, W)


@functools.partial(jax.jit, static_argnames=("eps",))
def _bwd(x4, mean, var, w, dy4, *, eps):
    N, Cch, H, W = x4.shape
    hw = H * W
    rows = N * Cch
    Cpad = -(-hw // LANES) * LANES
    BR = _block_rows(Cpad, 3)  # resident row operands: dy, x, dx
    R = -(-rows // BR) * BR
    xp = _pad2(x4.reshape(rows, hw), R, Cpad)
    dyp = _pad2(dy4.reshape(rows, hw), R, Cpad)
    inv = lax.rsqrt(var.astype(jnp.float32) + eps)
    cols = [_pad2(_rowify(v, N), R, 1) for v in (mean, inv, w)]
    row_blk = pl.BlockSpec((BR, Cpad), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
    col_blk = pl.BlockSpec((BR, 1), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
    dx, sdy, sdyx = pl.pallas_call(
        functools.partial(_bwd_kernel, hw=hw),
        grid=(R // BR,),
        in_specs=[row_blk, row_blk, col_blk, col_blk, col_blk],
        out_specs=[row_blk, col_blk, col_blk],
        out_shape=[jax.ShapeDtypeStruct((R, Cpad), dy4.dtype),
                   jax.ShapeDtypeStruct((R, 1), jnp.float32),
                   jax.ShapeDtypeStruct((R, 1), jnp.float32)],
        interpret=interpret(),
    )(dyp, xp, *cols)
    dx = dx[:rows, :hw].reshape(N, Cch, H, W)
    # per-channel epilogue: (N*C, 1) partials -> (C,) (the reference's
    # stage-2 reduce, welford.cu:345-366, left to XLA)
    sum_dy = jnp.sum(sdy[:rows, 0].reshape(N, Cch), axis=0)
    sum_dy_xhat = jnp.sum(sdyx[:rows, 0].reshape(N, Cch), axis=0)
    return dx, sum_dy, sum_dy_xhat, inv


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def batch_norm_apply_fused(x4, mean, var, w, b, eps: float):
    """Fused y = (x - mean_c) * rsqrt(var_c + eps) * w_c + b_c on NCHW."""
    return _fwd(x4, mean, var, w, b, eps=eps)


def _vjp_fwd(x4, mean, var, w, b, eps):
    return _fwd(x4, mean, var, w, b, eps=eps), (x4, mean, var, w)


def _vjp_bwd(eps, res, dy4):
    x4, mean, var, w = res
    dx, sum_dy, sum_dy_xhat, inv = _bwd(x4, mean, var, w, dy4, eps=eps)
    w32 = w.astype(jnp.float32)
    dmean = (-w32 * inv * sum_dy).astype(mean.dtype)
    dvar = (-0.5 * w32 * inv * inv * sum_dy_xhat).astype(var.dtype)
    dw = sum_dy_xhat.astype(w.dtype)
    db = sum_dy.astype(w.dtype)
    return dx.astype(x4.dtype), dmean, dvar, dw, db


batch_norm_apply_fused.defvjp(_vjp_fwd, _vjp_bwd)
