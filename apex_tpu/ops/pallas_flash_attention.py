"""Pallas fused attention kernels (fwd + bwd) for the MXU.

New capability relative to the reference (2019, pre-attention — SURVEY.md
§5): apex_tpu treats transformer workloads as first-class.  This kernel
is the compute core of ``transformer.dot_product_attention`` and, through
it, ``ulysses_attention``'s per-head local attention.  (Ring attention
keeps its own jnp online-softmax accumulation: its inner blocks interleave
with ppermutes and XLA fuses them against the collective.)

Design (memory-efficient attention, Rabe & Staats / FlashAttention
family): queries are tiled into row blocks; K and V for one (batch, head)
stay resident in VMEM, so each q-block computes its (BQ, T) score tile in
one MXU call, softmaxes in fp32, and contracts with V — the full (T, T)
matrix never exists in HBM.  The forward saves the per-row logsumexp; the
backward recomputes probabilities from it (no stored probs) in two
passes: a dQ pass tiled over q rows and a dK/dV pass tiled over k rows,
each a handful of MXU contractions.

For sequences too long for K/V residency (``fits_vmem`` false) callers
fall back to the jnp path; at that scale the right tool is ring
attention's sequence sharding anyway.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_common import LANES, interpret

_VMEM_BUDGET = 10 * 1024 * 1024
_BQ = 256  # query rows per grid step
_NEG = -1e30


def fits_vmem(T: int, D: int) -> bool:
    """K, V, (+Q/dO/O tiles) resident per (b, h): keep the resident set
    comfortably under budget."""
    Tp = -(-T // _BQ) * _BQ
    Dp = -(-D // LANES) * LANES
    resident = (2 * Tp * Dp        # K, V
                + 2 * _BQ * Tp     # score tile + mask temps
                + 4 * _BQ * Dp) * 4
    return resident <= _VMEM_BUDGET


def _pad_to(x, T, D):
    t, d = x.shape[-2:]
    if t == T and d == D:
        return x
    pad = [(0, 0)] * (x.ndim - 2) + [(0, T - t), (0, D - d)]
    return jnp.pad(x, pad)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                T_real, BQ):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # (BQ, D)
    k = k_ref[0].astype(jnp.float32)                  # (T, D)
    v = v_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (BQ, T)
    kpos = lax.broadcasted_iota(jnp.int32, s.shape, 1)
    valid = kpos < T_real
    if causal:
        qpos = qi * BQ + lax.broadcasted_iota(jnp.int32, s.shape, 0)
        valid = jnp.logical_and(valid, qpos >= kpos)
    s = jnp.where(valid, s, _NEG)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32) / l
    o_ref[0] = o.astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l))[:, 0]


@functools.partial(jax.jit, static_argnames=("scale", "causal"))
def _fwd(q, k, v, scale, causal):
    BH, T, D = q.shape
    Tp = -(-T // _BQ) * _BQ
    Dp = -(-D // LANES) * LANES
    qp = _pad_to(q, Tp, Dp)
    kp = _pad_to(k, Tp, Dp)
    vp = _pad_to(v, Tp, Dp)
    grid = (BH, Tp // _BQ)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          T_real=T, BQ=_BQ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, _BQ, Dp), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp, Dp), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp, Dp), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, _BQ, Dp), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _BQ), lambda b, i: (b, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[jax.ShapeDtypeStruct((BH, Tp, Dp), q.dtype),
                   jax.ShapeDtypeStruct((BH, Tp), jnp.float32)],
        interpret=interpret(),
    )(qp, kp, vp)
    return o[:, :T, :D], lse[:, :T]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               scale, causal, T_real, BQ):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, None]
    delta = delta_ref[0][:, None]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    kpos = lax.broadcasted_iota(jnp.int32, s.shape, 1)
    valid = kpos < T_real
    if causal:
        qpos = qi * BQ + lax.broadcasted_iota(jnp.int32, s.shape, 0)
        valid = jnp.logical_and(valid, qpos >= kpos)
    p = jnp.where(valid, jnp.exp(s - lse), 0.0)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    dq = jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32) * scale
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, scale, causal, T_real, BK):
    ki = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # (T, D) full queries
    k = k_ref[0].astype(jnp.float32)                  # (BK, D)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)                # (T, D)
    lse = lse_ref[0][None, :]                         # (1, T)
    delta = delta_ref[0][None, :]
    # transposed scores: (BK, T) = K_blk @ Q^T
    st = jax.lax.dot_general(k, q, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    qpos = lax.broadcasted_iota(jnp.int32, st.shape, 1)
    valid = qpos < T_real
    if causal:
        kpos = ki * BK + lax.broadcasted_iota(jnp.int32, st.shape, 0)
        valid = jnp.logical_and(valid, qpos >= kpos)
    pt = jnp.where(valid, jnp.exp(st - lse), 0.0)     # (BK, T)
    dv = jax.lax.dot_general(pt, do, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dpt = jax.lax.dot_general(v, do, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)  # (BK, T)
    dst = pt * (dpt - delta)
    dk = jax.lax.dot_general(dst, q, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "causal"))
def _bwd(q, k, v, o, lse, do, scale, causal):
    BH, T, D = q.shape
    Tp = -(-T // _BQ) * _BQ
    Dp = -(-D // LANES) * LANES
    qp, kp, vp = (_pad_to(x, Tp, Dp) for x in (q, k, v))
    dop = _pad_to(do, Tp, Dp)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), -1)
    deltap = jnp.pad(delta, ((0, 0), (0, Tp - T)))
    # padded rows: lse=0 would make exp(s-lse) = exp(-1e30)≈0 — safe
    lsep = jnp.pad(lse, ((0, 0), (0, Tp - T)))

    row_blk = pl.BlockSpec((1, _BQ, Dp), lambda b, i: (b, i, 0),
                           memory_space=pltpu.VMEM)
    full_blk = pl.BlockSpec((1, Tp, Dp), lambda b, i: (b, 0, 0),
                            memory_space=pltpu.VMEM)
    vec_row = pl.BlockSpec((1, _BQ), lambda b, i: (b, i),
                           memory_space=pltpu.VMEM)
    vec_full = pl.BlockSpec((1, Tp), lambda b, i: (b, 0),
                            memory_space=pltpu.VMEM)
    grid = (BH, Tp // _BQ)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          T_real=T, BQ=_BQ),
        grid=grid,
        in_specs=[row_blk, full_blk, full_blk, row_blk, vec_row, vec_row],
        out_specs=row_blk,
        out_shape=jax.ShapeDtypeStruct((BH, Tp, Dp), q.dtype),
        interpret=interpret(),
    )(qp, kp, vp, dop, lsep, deltap)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          T_real=T, BK=_BQ),
        grid=grid,
        in_specs=[full_blk, row_blk, row_blk, full_blk, vec_full, vec_full],
        out_specs=[row_blk, row_blk],
        out_shape=[jax.ShapeDtypeStruct((BH, Tp, Dp), k.dtype),
                   jax.ShapeDtypeStruct((BH, Tp, Dp), v.dtype)],
        interpret=interpret(),
    )(qp, kp, vp, dop, lsep, deltap)
    return dq[:, :T, :D], dk[:, :T, :D], dv[:, :T, :D]


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q3, k3, v3, scale: float, causal: bool):
    o, _ = _fwd(q3, k3, v3, scale, causal)
    return o


def _flash_fwd(q3, k3, v3, scale, causal):
    o, lse = _fwd(q3, k3, v3, scale, causal)
    return o, (q3, k3, v3, o, lse)


def _flash_bwd(scale, causal, res, do):
    q3, k3, v3, o, lse = res
    dq, dk, dv = _bwd(q3, k3, v3, o, lse, do, scale, causal)
    return dq.astype(q3.dtype), dk.astype(k3.dtype), dv.astype(v3.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False,
                    scale: Optional[float] = None) -> jax.Array:
    """softmax(q k^T * scale [+ causal mask]) v without materializing the
    score matrix in HBM.  q, k, v: (B, H, T, D) self-attention operands
    (equal sequence lengths)."""
    if q.ndim != 4:
        raise ValueError(f"expected (B, H, T, D), got {q.shape}")
    if q.shape != k.shape or k.shape != v.shape:
        raise ValueError("flash_attention requires matching q/k/v shapes")
    B, H, T, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    fold = lambda x: x.reshape(B * H, T, D)
    out = _flash(fold(q), fold(k), fold(v), float(scale), bool(causal))
    return out.reshape(B, H, T, D)
