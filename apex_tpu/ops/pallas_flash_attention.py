"""Pallas blocked flash attention (fwd + bwd) for the MXU.

New capability relative to the reference (2019, pre-attention — SURVEY.md
§5): apex_tpu treats transformer workloads as first-class.  This kernel
is the compute core of ``transformer.dot_product_attention`` and, through
it, ``ulysses_attention``'s per-head local attention.  (Ring attention
keeps its own jnp online-softmax accumulation: its inner blocks interleave
with ppermutes and XLA fuses them against the collective.)

Design (FlashAttention-style, true blocked form): the grid is
(batch*heads, q_blocks, k_blocks) with the k axis innermost ("arbitrary"
semantics, executed sequentially per core).  K and V are *streamed* one
(BLK, D) block at a time — nothing scales with T in VMEM — while online
softmax state (running max m, running sum l, unnormalized accumulator)
lives in VMEM scratch that persists across the k-block sweep.  The
forward emits the per-row logsumexp; the backward recomputes
probabilities from it in two streamed passes: a dQ pass (K/V streamed)
and a dK/dV pass (Q/dO streamed), each a handful of MXU contractions per
block pair.  Causal q/k block pairs above the diagonal are skipped via
``pl.when``.

Per-row statistics (lse, delta and the m/l scratch) are stored
lane-broadcast as (rows, 128) tiles — Mosaic requires the last two block
dims to be (8k, 128k)-aligned, so a (rows,) vector is carried as a full
lane tile with every lane equal (same layout the upstream
jax.experimental.pallas.ops.tpu.flash_attention uses).

Matmuls feed the MXU in the input dtype (bf16 stays bf16) with fp32
accumulation via ``preferred_element_type``; softmax state is always
fp32.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):
    # jax<0.5 compat: CompilerParams was still named TPUCompilerParams
    pltpu.CompilerParams = pltpu.TPUCompilerParams

from .pallas_common import LANES, interpret

_VMEM_BUDGET = 12 * 1024 * 1024
_BLK = 512          # q/k rows per block (clamped to the padded seq len)
_NEG = -1e30


def _dot(a, b, contract):
    """MXU contraction with fp32 accumulation.  Precision is pinned here
    rather than inherited from jax_default_matmul_precision: fp32
    operands get the full-precision passes (parity-grade), while bf16
    operands stay native — Mosaic rejects fp32 contract precision on
    bf16 inputs."""
    prec = (jax.lax.Precision.HIGHEST if a.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)
    return lax.dot_general(a, b, (contract, ((), ())),
                           preferred_element_type=jnp.float32,
                           precision=prec)


def _keep_unit(seed0, seed1, bh, qpos, kpos):
    """Deterministic per-(batch·head, q-pos, k-pos) uniform in [0, 1).

    Counter-based murmur3-finalizer hash over plain int32 ops (multiply
    wraps two's-complement, xor, logical shifts) — the same code runs
    inside the Pallas kernels, under interpret mode, and as the dense
    test reference, so dropout masks are bitwise-identical across the
    forward, both backward passes, and the reference implementation.
    ``seed0``/``seed1`` carry 64 bits of seed (two int32 words — one
    word would collide by birthday bound across ~1e6 layer·step draws);
    ``bh`` scalar; ``qpos``/``kpos`` broadcastable int32 position
    arrays."""
    # numpy scalar constants inline as jaxpr literals — jnp constants
    # would become constvars, which pallas_call cannot lower
    h = (qpos * np.int32(-1640531527)                      # 2654435761
         ^ kpos * np.int32(-2048144777)                    # 2246822519
         ^ bh * np.int32(-1028477379)                      # 3266489917
         ^ seed0)
    h = h ^ lax.shift_right_logical(h, np.int32(16))
    h = h * np.int32(-2048144789)
    h = h ^ seed1
    h = h ^ lax.shift_right_logical(h, np.int32(16))
    h = h * np.int32(-1028477387)
    h = h ^ lax.shift_right_logical(h, np.int32(16))
    # 31 uniform bits -> [0, 1)
    bits = jnp.bitwise_and(h, np.int32(0x7FFFFFFF))
    return bits.astype(jnp.float32) * np.float32(1.0 / 2147483648.0)


def _block_for(T: int) -> int:
    """Largest block in {512, 256, 128} that divides the lane-padded
    length — bounds zero-padding at 127 rows (a fixed 512 block would pad
    T=600 to 1024, wasting 41% of every MXU contraction)."""
    Tp = -(-T // LANES) * LANES
    for blk in (_BLK, 256, LANES):
        if Tp % blk == 0:
            return min(blk, Tp)
    return LANES


def fits_vmem(T: int, D: int, dropout: bool = False,
              segments: bool = False) -> bool:
    """VMEM needed per grid step — independent of T now that K/V stream
    through the grid.  Sized for the worst pass (backward dK/dV): six
    double-buffered operand blocks (q, k, v, do in; dk, dv out), two fp32
    accumulator scratches, the lane-broadcast stats tiles, and the
    (blk, blk) score/prob/dp/ds intermediates.  Dropout holds two more
    live (blk, blk) tiles in the dk/dv pass (the hash tile u and p_acc
    alongside p/dp/ds); segments double-buffer the q-id (blk, LANES) and
    k-id (8, blk) tiles plus the (blk, blk) equality mask."""
    blk = _block_for(T)
    Dp = -(-D // LANES) * LANES
    operands = 6 * blk * Dp          # q, k, v, do, dk, dv blocks
    stats = 2 * blk * LANES          # lse + delta tiles
    resident = 2 * (operands + stats) * 4          # double-buffered
    scratch = 2 * blk * Dp * 4                     # dk/dv fp32 accumulators
    ntiles = 6 if dropout else 4     # s/p, dp, ds (+ u, p_acc)
    if segments:
        ntiles += 1                  # the id-equality mask
        resident += 2 * (blk * LANES + 8 * blk) * 4    # qseg + kseg tiles
    score = ntiles * blk * blk * 4
    return resident + scratch + score <= _VMEM_BUDGET


def _pad_to(x, T, D):
    t, d = x.shape[-2:]
    if t == T and d == D:
        return x
    pad = [(0, 0)] * (x.ndim - 2) + [(0, T - t), (0, D - d)]
    return jnp.pad(x, pad)


def _lanes(vec, Tp):
    """(BH, T) → (BH, Tp, LANES) lane-broadcast fp32."""
    BH, T = vec.shape
    v = jnp.pad(vec.astype(jnp.float32), ((0, 0), (0, Tp - T)))
    return jax.lax.broadcast_in_dim(v, (BH, Tp, LANES), (0, 1))


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(*refs, scale, causal, has_mask, has_segments,
                dropout_rate, T_real, blk, nk):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    del refs[:3]
    kvm_ref = refs.pop(0) if has_mask else None
    if has_segments:
        qseg_ref = refs.pop(0)
        kseg_ref = refs.pop(0)
    else:
        qseg_ref = kseg_ref = None
    seed_ref = refs.pop(0) if dropout_rate else None
    o_ref, lse_ref, m_ref, l_ref, acc_ref = refs
    b = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, _NEG, jnp.float32)
        l_ref[...] = jnp.zeros(l_ref.shape, jnp.float32)
        acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)

    # causal: the (i, j) block pair is dead when its lowest q row sits
    # above its lowest k column (j*blk > i*blk + blk - 1 ⇔ j > i)
    run = (j <= i) if causal else (j >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = _dot(q, k, ((1,), (1,))) * scale
        kpos = j * blk + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = kpos < T_real
        qpos = i * blk + lax.broadcasted_iota(jnp.int32, s.shape, 0)
        if causal:
            valid = jnp.logical_and(valid, qpos >= kpos)
        if has_mask:
            # (1, blk) key-validity row, sublane-broadcast tile layout:
            # k positions on the lane axis, matching s's column axis
            valid = jnp.logical_and(valid, kvm_ref[0][:1, :] > 0.5)
        if has_segments:
            # packed sequences: attend only within the same segment —
            # q ids ride the lane-broadcast (stat) layout as a (blk, 1)
            # column, k ids the sublane layout as a (1, blk) row
            valid = jnp.logical_and(
                valid, qseg_ref[0][:, :1] == kseg_ref[0][:1, :])
        s = jnp.where(valid, s, _NEG)
        m_prev = m_ref[...][:, :1]                      # (blk, 1)
        l_prev = l_ref[...][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        # explicit zeroing: when a row is fully masked m_new == _NEG and
        # exp(s - m_new) would be exp(0) = 1 on the masked entries
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        # the softmax normalizer uses the UNdropped probabilities; only
        # the value accumulation is dropped+rescaled (FlashAttention's
        # dropout placement — the mask is regenerated bitwise in both
        # backward passes from the same counter hash)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_rate:
            u = _keep_unit(seed_ref[0, 0], seed_ref[0, 1], b, qpos, kpos)
            p_acc = jnp.where(u >= dropout_rate, p, 0.0) * (
                1.0 / (1.0 - dropout_rate))
        else:
            p_acc = p
        pv = _dot(p_acc.astype(v.dtype), v, ((1,), (0,)))
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nk - 1)
    def _done():
        l = l_ref[...][:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = m_ref[...] + jnp.log(jnp.broadcast_to(l_safe,
                                                           lse_ref.shape[1:]))


@functools.partial(jax.jit, static_argnames=("scale", "causal", "H",
                                             "dropout_rate"))
def _fwd(q, k, v, kvm, qseg, kseg, seed, scale, causal, H, dropout_rate):
    """kvm: (B, 8, Tp) fp32 key-validity (sublane-broadcast) or None.
    qseg/kseg: (B, Tp, LANES) lane- / (B, 8, Tp) sublane-broadcast int32
    segment ids or None.  seed: (1, 2) int32 dropout seed or None."""
    BH, T, D = q.shape
    blk = _block_for(T)
    Tp = -(-T // blk) * blk
    Dp = -(-D // LANES) * LANES
    qp, kp, vp = (_pad_to(x, Tp, Dp) for x in (q, k, v))
    nq, nk = Tp // blk, Tp // blk
    grid = (BH, nq, nk)
    row = pl.BlockSpec((1, blk, Dp), lambda b, i, j: (b, i, 0))
    col = pl.BlockSpec((1, blk, Dp), lambda b, i, j: (b, j, 0))
    stat = pl.BlockSpec((1, blk, LANES), lambda b, i, j: (b, i, 0))
    has_mask = kvm is not None
    has_segments = qseg is not None
    in_specs = [row, col, col]
    operands = [qp, kp, vp]
    if has_mask:
        in_specs.append(pl.BlockSpec((1, 8, blk),
                                     lambda b, i, j: (b // H, 0, j)))
        operands.append(kvm)
    if has_segments:
        in_specs.append(pl.BlockSpec((1, blk, LANES),
                                     lambda b, i, j: (b // H, i, 0)))
        operands.append(qseg)
        in_specs.append(pl.BlockSpec((1, 8, blk),
                                     lambda b, i, j: (b // H, 0, j)))
        operands.append(kseg)
    if dropout_rate:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.append(seed)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          has_mask=has_mask, has_segments=has_segments,
                          dropout_rate=dropout_rate,
                          T_real=T, blk=blk, nk=nk),
        grid=grid,
        in_specs=in_specs,
        out_specs=[row, stat],
        out_shape=[jax.ShapeDtypeStruct((BH, Tp, Dp), q.dtype),
                   jax.ShapeDtypeStruct((BH, Tp, LANES), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((blk, LANES), jnp.float32),
                        pltpu.VMEM((blk, LANES), jnp.float32),
                        pltpu.VMEM((blk, Dp), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret(),
    )(*operands)
    return o[:, :T, :D], lse[:, :T, 0]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _dq_kernel(*refs, scale, causal, has_mask, has_segments, dropout_rate,
               T_real, blk, nk):
    refs = list(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    del refs[:6]
    kvm_ref = refs.pop(0) if has_mask else None
    if has_segments:
        qseg_ref = refs.pop(0)
        kseg_ref = refs.pop(0)
    else:
        qseg_ref = kseg_ref = None
    seed_ref = refs.pop(0) if dropout_rate else None
    dq_ref, dq_acc = refs
    b = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros(dq_acc.shape, jnp.float32)

    run = (j <= i) if causal else (j >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = _dot(q, k, ((1,), (1,))) * scale
        kpos = j * blk + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = kpos < T_real
        qpos = i * blk + lax.broadcasted_iota(jnp.int32, s.shape, 0)
        if causal:
            valid = jnp.logical_and(valid, qpos >= kpos)
        if has_mask:
            valid = jnp.logical_and(valid, kvm_ref[0][:1, :] > 0.5)
        if has_segments:
            valid = jnp.logical_and(
                valid, qseg_ref[0][:, :1] == kseg_ref[0][:1, :])
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)
        dp = _dot(do, v, ((1,), (1,)))
        if dropout_rate:
            # dS = P ∘ (M ∘ (dO Vᵀ)/keep − delta): same counter hash as
            # the forward, so the mask is bitwise-identical
            u = _keep_unit(seed_ref[0, 0], seed_ref[0, 1], b, qpos, kpos)
            dp = jnp.where(u >= dropout_rate, dp, 0.0) * (
                1.0 / (1.0 - dropout_rate))
        ds = (p * (dp - delta)).astype(k.dtype)
        dq_acc[...] += _dot(ds, k, ((1,), (0,))) * scale

    @pl.when(j == nk - 1)
    def _done():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _dkv_kernel(*refs, scale, causal, has_mask, has_segments,
                dropout_rate, T_real, blk, nq):
    refs = list(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    del refs[:6]
    kvm_ref = refs.pop(0) if has_mask else None
    if has_segments:
        qseg_ref = refs.pop(0)
        kseg_ref = refs.pop(0)
    else:
        qseg_ref = kseg_ref = None
    seed_ref = refs.pop(0) if dropout_rate else None
    dk_ref, dv_ref, dk_acc, dv_acc = refs
    b = pl.program_id(0)
    i = pl.program_id(1)          # k block
    j = pl.program_id(2)          # q block (streamed)

    @pl.when(j == 0)
    def _init():
        dk_acc[...] = jnp.zeros(dk_acc.shape, jnp.float32)
        dv_acc[...] = jnp.zeros(dv_acc.shape, jnp.float32)

    # causal: q block j only sees k block i when j*blk + blk - 1 >= i*blk
    run = (j >= i) if causal else (j >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = _dot(q, k, ((1,), (1,))) * scale
        kpos = i * blk + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = kpos < T_real
        qpos = j * blk + lax.broadcasted_iota(jnp.int32, s.shape, 0)
        if causal:
            valid = jnp.logical_and(valid, qpos >= kpos)
        if has_mask:
            valid = jnp.logical_and(valid, kvm_ref[0][:1, :] > 0.5)
        if has_segments:
            valid = jnp.logical_and(
                valid, qseg_ref[0][:, :1] == kseg_ref[0][:1, :])
        # padded q rows contribute nothing: their do rows are zero
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)       # (bq, bk)
        dp = _dot(do, v, ((1,), (1,)))
        if dropout_rate:
            # absolute (qpos, kpos) arguments match the fwd/dq passes
            # exactly, so the regenerated mask is bitwise-identical
            u = _keep_unit(seed_ref[0, 0], seed_ref[0, 1], b, qpos, kpos)
            keep = u >= dropout_rate
            inv_keep = 1.0 / (1.0 - dropout_rate)
            p_acc = jnp.where(keep, p, 0.0) * inv_keep
            dp = jnp.where(keep, dp, 0.0) * inv_keep
        else:
            p_acc = p
        dv_acc[...] += _dot(p_acc.astype(do.dtype), do, ((0,), (0,)))
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_acc[...] += _dot(ds, q, ((0,), (0,))) * scale

    @pl.when(j == nq - 1)
    def _done():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "causal", "H",
                                             "dropout_rate"))
def _bwd(q, k, v, o, lse, do, kvm, qseg, kseg, seed, scale, causal, H,
         dropout_rate):
    BH, T, D = q.shape
    blk = _block_for(T)
    Tp = -(-T // blk) * blk
    Dp = -(-D // LANES) * LANES
    qp, kp, vp = (_pad_to(x, Tp, Dp) for x in (q, k, v))
    dop = _pad_to(do, Tp, Dp)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), -1)
    deltap = _lanes(delta, Tp)
    lsep = _lanes(lse, Tp)
    nq = nk = Tp // blk
    has_mask = kvm is not None
    has_segments = qseg is not None
    sem = pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))

    rowi = pl.BlockSpec((1, blk, Dp), lambda b, i, j: (b, i, 0))
    colj = pl.BlockSpec((1, blk, Dp), lambda b, i, j: (b, j, 0))
    stati = pl.BlockSpec((1, blk, LANES), lambda b, i, j: (b, i, 0))
    statj = pl.BlockSpec((1, blk, LANES), lambda b, i, j: (b, j, 0))
    # key-validity / k-segment tiles for the k block: streamed along the
    # j axis in the dq pass, along the i (k-block) axis in the dk/dv
    # pass; q-segment ids ride the lane-broadcast (stat) layout
    kvmj = pl.BlockSpec((1, 8, blk), lambda b, i, j: (b // H, 0, j))
    kvmi = pl.BlockSpec((1, 8, blk), lambda b, i, j: (b // H, 0, i))
    qsegi = pl.BlockSpec((1, blk, LANES), lambda b, i, j: (b // H, i, 0))
    qsegj = pl.BlockSpec((1, blk, LANES), lambda b, i, j: (b // H, j, 0))
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)

    dq_specs = [rowi, colj, colj, rowi, stati, stati]
    dq_ops = [qp, kp, vp, dop, lsep, deltap]
    if has_mask:
        dq_specs.append(kvmj)
        dq_ops.append(kvm)
    if has_segments:
        dq_specs += [qsegi, kvmj]        # k ids share the kvm layout
        dq_ops += [qseg, kseg]
    if dropout_rate:
        dq_specs.append(smem)
        dq_ops.append(seed)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          has_mask=has_mask, has_segments=has_segments,
                          dropout_rate=dropout_rate,
                          T_real=T, blk=blk, nk=nk),
        grid=(BH, nq, nk),
        in_specs=dq_specs,
        out_specs=rowi,
        out_shape=jax.ShapeDtypeStruct((BH, Tp, Dp), q.dtype),
        scratch_shapes=[pltpu.VMEM((blk, Dp), jnp.float32)],
        compiler_params=sem,
        interpret=interpret(),
    )(*dq_ops)

    dkv_specs = [colj, rowi, rowi, colj, statj, statj]
    dkv_ops = [qp, kp, vp, dop, lsep, deltap]
    if has_mask:
        dkv_specs.append(kvmi)
        dkv_ops.append(kvm)
    if has_segments:
        # dkv grid: i = k block, j = q block — q ids stream along j,
        # k ids along i (sharing the kvm layouts)
        dkv_specs += [qsegj, kvmi]
        dkv_ops += [qseg, kseg]
    if dropout_rate:
        dkv_specs.append(smem)
        dkv_ops.append(seed)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          has_mask=has_mask, has_segments=has_segments,
                          dropout_rate=dropout_rate,
                          T_real=T, blk=blk, nq=nq),
        grid=(BH, nk, nq),
        in_specs=dkv_specs,
        out_specs=[rowi, rowi],
        out_shape=[jax.ShapeDtypeStruct((BH, Tp, Dp), k.dtype),
                   jax.ShapeDtypeStruct((BH, Tp, Dp), v.dtype)],
        scratch_shapes=[pltpu.VMEM((blk, Dp), jnp.float32),
                        pltpu.VMEM((blk, Dp), jnp.float32)],
        compiler_params=sem,
        interpret=interpret(),
    )(*dkv_ops)
    return dq[:, :T, :D], dk[:, :T, :D], dv[:, :T, :D]


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _flash(q3, k3, v3, kvm, qseg, kseg, seed, scale: float, causal: bool,
           H: int, dropout_rate: float):
    o, _ = _fwd(q3, k3, v3, kvm, qseg, kseg, seed, scale, causal, H,
                dropout_rate)
    return o


def _flash_fwd(q3, k3, v3, kvm, qseg, kseg, seed, scale, causal, H,
               dropout_rate):
    o, lse = _fwd(q3, k3, v3, kvm, qseg, kseg, seed, scale, causal, H,
                  dropout_rate)
    return o, (q3, k3, v3, o, lse, kvm, qseg, kseg, seed)


def _flash_bwd(scale, causal, H, dropout_rate, res, do):
    q3, k3, v3, o, lse, kvm, qseg, kseg, seed = res
    dq, dk, dv = _bwd(q3, k3, v3, o, lse, do, kvm, qseg, kseg, seed,
                      scale, causal, H, dropout_rate)
    dkvm = None if kvm is None else jnp.zeros_like(kvm)
    # int primals -> float0 cotangents
    f0 = lambda a: (None if a is None
                    else np.zeros(a.shape, jax.dtypes.float0))
    return (dq.astype(q3.dtype), dk.astype(k3.dtype), dv.astype(v3.dtype),
            dkvm, f0(qseg), f0(kseg), f0(seed))


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False,
                    scale: Optional[float] = None,
                    kv_mask: Optional[jax.Array] = None,
                    dropout_rate: float = 0.0,
                    dropout_seed: Optional[jax.Array] = None,
                    segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """softmax(q k^T * scale [+ causal mask]) v without materializing the
    score matrix in HBM.  q, k, v: (B, H, T, D) self-attention operands
    (equal sequence lengths).  K/V are streamed through VMEM in blocks,
    so the sequence length is bounded by HBM, not VMEM.

    ``kv_mask``: optional (B, T) bool key-validity (True = attend) — the
    key-padding mask of BERT-style batches, streamed alongside the K/V
    blocks as sublane-broadcast (B, 8, T) tiles (the upstream
    jax.experimental flash kernel's SegmentIds layout).  Composes with
    ``causal``.  Queries whose keys are ALL masked produce zero output
    rows (the dense softmax would give a uniform average instead).

    ``dropout_rate`` + ``dropout_seed`` (int32 scalar, e.g. drawn per
    step from a PRNGKey): attention-probability dropout computed INSIDE
    the kernel from a counter-based hash of the absolute positions —
    no (T, T) mask materializes, and the backward passes regenerate the
    identical mask from the same counters (FlashAttention's dropout
    placement: the softmax normalizer is undropped, the value
    accumulation is dropped and rescaled by 1/keep).

    ``segment_ids``: optional (B, T) int32 for packed sequences —
    position pairs attend only within equal ids (q-ids stream as
    lane-broadcast tiles, k-ids as sublane tiles).  Composes with
    ``causal``/``kv_mask``/dropout.  Rows whose segment has no other
    member still see themselves (the diagonal id always matches)."""
    if q.ndim != 4:
        raise ValueError(f"expected (B, H, T, D), got {q.shape}")
    if q.shape != k.shape or k.shape != v.shape:
        raise ValueError("flash_attention requires matching q/k/v shapes")
    dropout_rate = float(dropout_rate)
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got "
                         f"{dropout_rate}")
    if dropout_rate and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    B, H, T, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    blk = _block_for(T)
    Tp = -(-T // blk) * blk
    kvm = None
    if kv_mask is not None:
        if kv_mask.shape != (B, T):
            raise ValueError(f"kv_mask must be (B, T) = {(B, T)}, got "
                             f"{kv_mask.shape}")
        m = jnp.pad(kv_mask.astype(jnp.float32), ((0, 0), (0, Tp - T)))
        kvm = jax.lax.broadcast_in_dim(m, (B, 8, Tp), (0, 2))
    seed = None
    if dropout_rate:
        s = jnp.asarray(dropout_seed, jnp.int32).reshape(-1)
        if s.size == 1:
            # single-word seeds get a derived second word (no extra
            # entropy, but the kernel contract is two words)
            s = jnp.stack([s[0], s[0] ^ np.int32(0x5555AAAA)])
        elif s.size != 2:
            raise ValueError("dropout_seed must be 1 or 2 int32 words, "
                             f"got {s.size}")
        seed = s.reshape(1, 2)
    qseg = kseg = None
    if segment_ids is not None:
        if segment_ids.shape != (B, T):
            raise ValueError(f"segment_ids must be (B, T) = {(B, T)}, "
                             f"got {segment_ids.shape}")
        # padded positions get id -1 on the q side and -2 on the k side,
        # so padding never matches anything (incl. other padding)
        ids = segment_ids.astype(jnp.int32)
        idq = jnp.pad(ids, ((0, 0), (0, Tp - T)), constant_values=-1)
        idk = jnp.pad(ids, ((0, 0), (0, Tp - T)), constant_values=-2)
        qseg = jax.lax.broadcast_in_dim(idq, (B, Tp, LANES), (0, 1))
        kseg = jax.lax.broadcast_in_dim(idk, (B, 8, Tp), (0, 2))
    fold = lambda x: x.reshape(B * H, T, D)
    out = _flash(fold(q), fold(k), fold(v), kvm, qseg, kseg, seed,
                 float(scale), bool(causal), H, dropout_rate)
    return out.reshape(B, H, T, D)
