"""Shared plumbing for the Pallas kernel family.

The reference's multi_tensor_apply harness (csrc/multi_tensor_apply.cuh:
40-126) exists to smuggle tensor addresses into 4KB CUDA kernel-arg
structs, chunking and relaunching as the struct fills.  TPU has no such
constraint: the tensor list is concatenated into one flat buffer on device
(a fusion XLA performs as pure data movement) and each kernel tiles over a
2-D (rows, 128) view of it — lanes fixed at 128, row blocks sized for VMEM.
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..multi_tensor_apply.flatten import pack_flat, unpack_flat  # noqa: F401
# (re-exported: the kernels' flatten plumbing is the shared helper in
# multi_tensor_apply.flatten — one implementation, three call sites)

LANES = 128
# rows per grid block: 512 rows x 128 lanes x 4B = 256 KiB per buffer in
# VMEM — small enough for several operands to co-reside, large enough to
# amortize grid overhead
BLOCK_ROWS = 512
BLOCK_ELEMS = BLOCK_ROWS * LANES


def interpret() -> bool:
    from . import dispatch
    return dispatch.interpret_mode()


# fp32 minimum tile is (8, 128): any block_rows the optimizer kernels
# use must stay a multiple of this sublane count
MIN_SUBLANES = 8


def pick_block_rows(n: int) -> int:
    """Rows per grid block for an ``n``-element buffer: BLOCK_ROWS for
    full-model buffers, but a ZeRO-sharded update runs on a 1/ici (or
    1/world) slice that can be far smaller than BLOCK_ELEMS — padding
    it up to a 512-row block and launching a 1-block grid would move
    up to 65535 dead elements through VMEM per operand.  For buffers
    under one block, shrink the block to the smallest multiple of the
    fp32 min-tile sublane count (8 rows x 128 lanes) that covers the
    buffer, so the shard update stays ONE kernel launch with at most
    one sublane tile of padding.  Rows stay divisible by the block by
    construction — the partial-tile lint (analysis.pallas_lint) holds
    for every shard size."""
    rows = max(1, -(-int(n) // LANES))
    if rows >= BLOCK_ROWS:
        return BLOCK_ROWS
    return -(-rows // MIN_SUBLANES) * MIN_SUBLANES


def to_2d(flat: jax.Array, block_rows: int = BLOCK_ROWS
          ) -> Tuple[jax.Array, int]:
    """Pad a 1-D buffer to a (rows, LANES) view, rows a multiple of
    ``block_rows`` so every grid block is full.  Returns
    (arr2d, orig_len)."""
    n = flat.shape[0]
    rows = max(1, -(-n // LANES))
    rows = -(-rows // block_rows) * block_rows
    padded = rows * LANES
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
    return flat.reshape(rows, LANES), n


def from_2d(arr2d: jax.Array, n: int) -> jax.Array:
    return arr2d.reshape(-1)[:n]
