"""Training-run supervisor: a host-side health verdict for live runs.

A long training run gets sick in ways no single metric names: a silent
stall (the step counter stops advancing but nothing raises), a loss
spike or NaN, throughput decaying against its own history, one replica
drifting away from the others.  Every signal needed to detect these
already reaches the host at existing flush points — the flushed
:class:`~.metrics.DeviceMetrics` / :class:`~.numerics.NumericsMonitor`
state, the per-step wall clock, ``ddp.last_comm_stats``, the
``checkpoint_saved`` flight-ring events — so the supervisor is pure
host-side bookkeeping over values that were **already fetched**.

The contract (audit-pinned like the numerics monitor, by the
``supervisor`` lint rule + tests/test_step_graph_audit.py): the
supervisor adds **zero** host transfers, collectives, or anything else
to any jitted step.  :meth:`RunSupervisor.wrap_step` returns the step
function *unchanged* — it exists precisely so the analysis entry
points can trace the "supervised" step and machine-check that its
jaxpr is byte-identical to the unsupervised one, both enabled and
disabled.  A future "improvement" that sneaks a callback or an extra
collective into the step fails the lint before any profiler sees it.

Detectors (each fires once per EPISODE — on the transition into the
sick state — with the flight ring carrying the event and a registry
counter carrying the volume):

- **stall** — the progress watermark (the ``step`` counter observed at
  flush, advanced also by ``checkpoint_saved`` flight events) has not
  moved for ``stall_observations`` consecutive observations;
- **loss_spike** — a finite loss exceeding ``loss_spike_factor`` × the
  warm loss EWMA;
- **nan** — a nonfinite loss, or a flushed numerics summary showing
  new overflow steps (the anomaly then names the culprit layer);
- **throughput_regression** — step time exceeding
  ``throughput_regression_factor`` × the warm step-time EWMA;
- **replica_divergence** — a flushed numerics divergence digest whose
  ``desync_steps`` advanced (the anomaly carries ``worst_leaf`` and
  ``max_rel_dev``);
- **recompilation_storm** — repeated *signature-change* retraces of
  one jit entry within a bounded observation window, fed by the
  compilation ledger's ``xla_retrace`` flight events
  (``observability.compilation``): a hot path that was compiled once
  is now re-tracing per call — shape-polymorphic inputs, a dtype
  flapping, a static arg churning.  The anomaly names the entry and
  carries the retrace-cause differ's verdict (the culprit argument
  plus its before/after signatures), so the fix is one hop away.

Outputs: flight-ring events (``run_stall`` / ``run_loss_spike`` /
``run_nan`` / ``run_throughput_regression`` /
``run_replica_divergence``), registry metrics
(``run_anomalies_total{kind=...}``, loss / step-time EWMAs, the
watermark gauge), schema-v5 ``kind: run`` JSONL records
(:meth:`record`, pinned by ``exporters.validate_run_record``), a
``/statusz``-ready :meth:`status` dict with a ``health_check`` the
introspection server turns into ``/healthz`` 503, and the end-of-run
:meth:`write_report` artifact.
"""

from __future__ import annotations

import json
import math
import os
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

__all__ = ["ANOMALY_KINDS", "SupervisorConfig", "RunSupervisor"]

# every anomaly kind the supervisor can declare; validate_run_record
# rejects records naming anything else
ANOMALY_KINDS = ("stall", "loss_spike", "nan", "throughput_regression",
                 "replica_divergence", "recompilation_storm")


class SupervisorConfig:
    """Detector thresholds (all observation-counted, so the whole
    timeline is deterministic under test clocks).

    - ``stall_observations``: consecutive observations without a
      progress-watermark advance before the stall fires;
    - ``warmup_observations``: samples the loss / step-time EWMAs must
      absorb before spike / regression detection arms (a cold EWMA
      compares against noise);
    - ``loss_spike_factor`` / ``loss_alpha``: a finite loss above
      ``factor × ewma`` is a spike; ``alpha`` is the EWMA's newest-
      sample weight;
    - ``throughput_regression_factor`` / ``step_time_alpha``: same
      shape for the per-observation step time (higher = slower =
      regressed);
    - ``storm_retraces`` / ``storm_window_observations``: at least
      ``storm_retraces`` signature-change retraces of ONE jit entry
      (``xla_retrace`` flight events from the compilation ledger)
      within the last ``storm_window_observations`` observations
      declare a recompilation storm for that entry;
    - ``max_anomalies``: bound on the retained anomaly *detail* list
      (the counts are exact forever; a weeks-long sick run keeps the
      most recent details, flight-ring discipline).
    """

    def __init__(self, stall_observations: int = 10,
                 warmup_observations: int = 5,
                 loss_spike_factor: float = 3.0,
                 loss_alpha: float = 0.2,
                 throughput_regression_factor: float = 1.5,
                 step_time_alpha: float = 0.2,
                 storm_retraces: int = 3,
                 storm_window_observations: int = 20,
                 max_anomalies: int = 256):
        if stall_observations < 1:
            raise ValueError(f"stall_observations must be >= 1, got "
                             f"{stall_observations}")
        if warmup_observations < 1:
            raise ValueError(f"warmup_observations must be >= 1, got "
                             f"{warmup_observations}")
        if loss_spike_factor <= 1.0:
            raise ValueError(f"loss_spike_factor must be > 1, got "
                             f"{loss_spike_factor}")
        if throughput_regression_factor <= 1.0:
            raise ValueError(f"throughput_regression_factor must be "
                             f"> 1, got {throughput_regression_factor}")
        for name, a in (("loss_alpha", loss_alpha),
                        ("step_time_alpha", step_time_alpha)):
            if not (0.0 < a <= 1.0):
                raise ValueError(f"{name} must be in (0, 1], got {a}")
        if storm_retraces < 1:
            raise ValueError(f"storm_retraces must be >= 1, got "
                             f"{storm_retraces}")
        if storm_window_observations < 1:
            raise ValueError(f"storm_window_observations must be >= 1, "
                             f"got {storm_window_observations}")
        if max_anomalies < 1:
            raise ValueError(f"max_anomalies must be >= 1, got "
                             f"{max_anomalies}")
        self.stall_observations = stall_observations
        self.warmup_observations = warmup_observations
        self.loss_spike_factor = loss_spike_factor
        self.loss_alpha = loss_alpha
        self.throughput_regression_factor = throughput_regression_factor
        self.step_time_alpha = step_time_alpha
        self.storm_retraces = storm_retraces
        self.storm_window_observations = storm_window_observations
        self.max_anomalies = max_anomalies


def _finite(x) -> bool:
    try:
        return math.isfinite(float(x))
    except (TypeError, ValueError):
        return False


class RunSupervisor:
    """Consume one training run's host-visible signals; hold a verdict.

    ``observe_step`` is the one feed — call it at every existing flush
    point with whatever host values that point already produced::

        sup = RunSupervisor("resnet50_o2_ddp")
        step = sup.wrap_step(step)        # identity; audit-pinned
        for i in range(steps):
            state, loss_dev = step(state, batch)
            if i % flush_every == 0:               # existing cadence
                flushed = nm.flush(state[-1])      # existing fetch
                sup.observe_step(step=i, loss=float(loss_dev),
                                 step_time_s=dt, numerics=flushed,
                                 comm_stats=ddp.last_comm_stats)
        rec = sup.record()                 # kind: run JSONL payload
        sup.write_report(path)             # end-of-run artifact

    ``enabled=False`` is the hard off-switch: every method is a cheap
    no-op and :meth:`wrap_step` still returns the step unchanged —
    there is nothing to turn off *in* the step, which is the point.
    ``ring``/``registry`` default to the process singletons resolved
    per use (the ``flightrec.resolve`` rule every producer follows).
    """

    def __init__(self, run: str = "run",
                 config: Optional[SupervisorConfig] = None,
                 registry=None, ring=None,
                 clock: Callable[[], float] = time.perf_counter,
                 enabled: bool = True):
        if not run:
            raise ValueError("run name must be non-empty")
        self.run = str(run)
        self.config = config or SupervisorConfig()
        self.registry = registry
        self._ring = ring
        self._clock = clock
        self.enabled = bool(enabled)
        self._t0 = clock()
        self._observations = 0
        self._loss_samples = 0
        self._time_samples = 0
        self._last_loss: Optional[float] = None
        self._loss_ewma: Optional[float] = None
        self._last_step_time: Optional[float] = None
        self._time_ewma: Optional[float] = None
        self._watermark: Optional[int] = None
        self._watermark_obs = 0          # observation of last advance
        self._tokens = 0
        self._counts: Dict[str, int] = {k: 0 for k in ANOMALY_KINDS}
        self._anomalies: deque = deque(
            maxlen=self.config.max_anomalies)
        # episode latches: fire on the TRANSITION into a sick state,
        # not once per observation spent in it (shed-episode rule —
        # a loss that goes NaN and STAYS NaN is one event, not one
        # per step wheeling the bounded ring past the history a
        # post-mortem needs)
        self._in_stall = False
        self._in_spike = False
        self._in_regression = False
        self._in_nan = False
        # deltas against the last consumed numerics flush / ring scan.
        # The ring watermark starts at the CURRENT total: a supervisor
        # attached to the process ring mid-life must not count a
        # previous run's checkpoint_saved events as its own progress
        # (the per-monitor flush-delta discipline record_scaler uses)
        self._last_desync = 0
        self._last_overflow = 0
        self._ring_seq_seen = self.ring.total
        self._ckpt_count = 0
        self._ckpt_step: Optional[int] = None
        # recompilation-storm feed: per-entry log of consumed
        # ``xla_retrace`` flight events, stamped with the observation
        # that consumed them so the window is observation-counted like
        # every other detector (bounded per entry, ring discipline)
        self._retrace_log: Dict[str, deque] = {}
        self._retrace_total = 0
        self._in_storm: set = set()
        self._scaler: Dict[str, Any] = {}
        self._comm: Dict[str, Any] = {}
        # recovery-in-flight (PR 11): set by the recovery controller
        # around an intentional rollback / world shrink — /healthz
        # reports the distinct degraded-but-live "recovering" state
        # instead of 503ing an orchestrator into a restart loop while
        # the run is being handled
        self._recovering: Optional[str] = None
        self._recoveries = 0
        # preemption (PR 12): set by the elastic trainer when a
        # PreemptionGuard request was honored — a CLEAN, live exit
        # (snapshot written, resume point named), not a sick state
        self._preempted: Optional[str] = None
        self._preempted_step: Optional[int] = None

    # -- the audit contract -------------------------------------------------
    def wrap_step(self, step_fn):
        """Return ``step_fn`` UNCHANGED.  The supervisor reads host
        values at existing flush points; it never instruments the
        jitted step.  This identity is the mechanical surface the
        ``supervisor`` lint rule pins: the wrapped step's jaxpr must be
        byte-identical to the unwrapped one whether the supervisor is
        enabled or not."""
        return step_fn

    @property
    def ring(self):
        from . import flightrec
        return flightrec.resolve(self._ring)

    def _reg(self):
        from .metrics import get_registry
        return self.registry if self.registry is not None \
            else get_registry()

    # -- anomaly plumbing ---------------------------------------------------
    def _anomaly(self, kind: str, **detail) -> Dict[str, Any]:
        ev = {"kind": kind, "observation": self._observations,
              "step": self._watermark, "t_s": round(
                  self._clock() - self._t0, 6)}
        ev.update({k: v for k, v in detail.items() if v is not None})
        self._counts[kind] += 1
        self._anomalies.append(ev)
        self.ring.append(f"run_{kind}", run=self.run,
                         **{k: v for k, v in ev.items()
                            if k != "kind"})
        self._reg().counter(
            "run_anomalies_total",
            help="training-run anomalies detected by the supervisor"
        ).labels(kind=kind, run=self.run).inc()
        return ev

    def _consume_ring(self) -> bool:
        """Consume the supervisor's flight-ring feeds in one snapshot:
        ``checkpoint_saved`` events (the other progress feeder — a run
        writing checkpoints is making durable progress even when the
        caller has no step counter to report; only these affect the
        returned ``progressed`` bool) and ``xla_retrace`` events (the
        compilation ledger's signature-change retraces, stamped with
        the consuming observation into the per-entry log the
        recompilation-storm detector reads).  The cheap total==seen
        guard skips the snapshot copy on the (typical) quiet step, and
        the watermark advances only past what the snapshot actually
        contained — an event appended concurrently with the scan is
        consumed on the next one, never skipped."""
        ring = self.ring
        seen = self._ring_seq_seen
        if ring.total <= seen:
            return False
        snap = ring.snapshot()
        if snap:
            self._ring_seq_seen = snap[-1]["seq"] + 1
        fresh = [ev for ev in snap if ev["seq"] >= seen]
        # the compilation ledger's signature-change retraces feed the
        # recompilation-storm detector; stamped with THIS observation
        # so the storm window stays observation-counted
        for ev in fresh:
            if ev["kind"] != "xla_retrace":
                continue
            entry = str(ev.get("entry") or "?")
            log = self._retrace_log.get(entry)
            if log is None:
                # retained bound sized to the threshold: a config with
                # storm_retraces > 64 must still be able to accumulate
                # enough events to fire (the count would otherwise cap
                # below the threshold and the detector silently never
                # trip)
                log = self._retrace_log[entry] = deque(
                    maxlen=max(64, self.config.storm_retraces))
            log.append({"observation": self._observations,
                        "cause": ev.get("cause"),
                        "culprit": ev.get("culprit"),
                        "before": ev.get("before"),
                        "after": ev.get("after")})
            self._retrace_total += 1
        new = [ev for ev in fresh if ev["kind"] == "checkpoint_saved"]
        if not new:
            return False
        self._ckpt_count += len(new)
        steps = [ev.get("step") for ev in new
                 if isinstance(ev.get("step"), int)]
        if steps:
            self._ckpt_step = max(steps)
        return True

    # -- the feed -----------------------------------------------------------
    def observe_step(self, step: Optional[int] = None,
                     loss: Optional[float] = None,
                     step_time_s: Optional[float] = None,
                     tokens: Optional[int] = None,
                     numerics: Optional[Dict[str, Any]] = None,
                     comm_stats: Optional[List[dict]] = None
                     ) -> List[Dict[str, Any]]:
        """Fold one flush point's host-visible signals; returns the
        anomalies detected BY this observation (empty list = healthy).

        ``step`` is the run's progress counter (a flushed device
        ``steps`` total or the loop index); ``numerics`` is a flushed
        :class:`~.numerics.NumericsMonitor` summary; ``comm_stats`` is
        ``ddp.last_comm_stats``.  All inputs are plain host values the
        caller already holds — passing them here costs no device
        traffic."""
        if not self.enabled:
            return []
        cfg = self.config
        self._observations += 1
        found: List[Dict[str, Any]] = []

        # progress watermark: the step counter, plus checkpoint_saved
        # flight events (a checkpoint is durable progress)
        progressed = self._consume_ring()
        if step is not None:
            step = int(step)
            if self._watermark is None or step > self._watermark:
                self._watermark = step
                progressed = True
        if tokens is not None:
            self._tokens += int(tokens)
        if progressed:
            self._watermark_obs = self._observations
            self._in_stall = False
        elif (not self._in_stall
              and self._observations - self._watermark_obs
              >= cfg.stall_observations):
            self._in_stall = True
            found.append(self._anomaly(
                "stall",
                observations_without_progress=(
                    self._observations - self._watermark_obs),
                watermark=self._watermark))

        # recompilation storm: >= storm_retraces signature-change
        # retraces of ONE entry inside the observation window.  Fires
        # on the transition per entry (episode rule); the verdict
        # detail carries the retrace-cause differ's culprit signature
        # so /statusz names WHICH argument keeps changing.
        floor = self._observations - cfg.storm_window_observations
        for entry, log in self._retrace_log.items():
            recent = [ev for ev in log if ev["observation"] > floor]
            if len(recent) >= cfg.storm_retraces:
                if entry not in self._in_storm:
                    self._in_storm.add(entry)
                    last = recent[-1]
                    found.append(self._anomaly(
                        "recompilation_storm", entry=entry,
                        retraces_in_window=len(recent),
                        window_observations=(
                            cfg.storm_window_observations),
                        cause=last.get("cause"),
                        culprit=last.get("culprit"),
                        before=last.get("before"),
                        after=last.get("after")))
            else:
                self._in_storm.discard(entry)

        # loss: NaN/inf is an immediate anomaly — fired on the
        # TRANSITION into nonfinite (a loss that stays NaN is one
        # episode, not one ring event per step); a finite loss spikes
        # against the warm EWMA.  Anomalous samples never feed the
        # EWMA — the baseline must not chase the pathology.
        if loss is not None:
            if not _finite(loss):
                self._last_loss = None
                if not self._in_nan:
                    self._in_nan = True
                    found.append(self._anomaly(
                        "nan", loss=repr(loss), source="loss"))
            else:
                self._in_nan = False
                loss = float(loss)
                self._last_loss = loss
                warm = self._loss_samples >= cfg.warmup_observations
                if (warm and self._loss_ewma is not None
                        and self._loss_ewma > 0
                        and loss > cfg.loss_spike_factor
                        * self._loss_ewma):
                    if not self._in_spike:
                        self._in_spike = True
                        found.append(self._anomaly(
                            "loss_spike", loss=round(loss, 6),
                            ewma=round(self._loss_ewma, 6),
                            factor=round(loss / self._loss_ewma, 3)))
                else:
                    self._in_spike = False
                    self._loss_samples += 1
                    a = cfg.loss_alpha
                    self._loss_ewma = (loss if self._loss_ewma is None
                                       else a * loss
                                       + (1 - a) * self._loss_ewma)

        # step time: higher = slower = regressed
        if step_time_s is not None and _finite(step_time_s):
            dt = float(step_time_s)
            self._last_step_time = dt
            warm = self._time_samples >= cfg.warmup_observations
            if (warm and self._time_ewma is not None
                    and self._time_ewma > 0
                    and dt > cfg.throughput_regression_factor
                    * self._time_ewma):
                if not self._in_regression:
                    self._in_regression = True
                    found.append(self._anomaly(
                        "throughput_regression",
                        step_time_ms=round(dt * 1e3, 4),
                        ewma_ms=round(self._time_ewma * 1e3, 4),
                        factor=round(dt / self._time_ewma, 3)))
            else:
                self._in_regression = False
                self._time_samples += 1
                a = cfg.step_time_alpha
                self._time_ewma = (dt if self._time_ewma is None
                                   else a * dt
                                   + (1 - a) * self._time_ewma)

        # numerics flush: new overflow steps are a NaN-class anomaly
        # (with the culprit layer attribution riding along); a
        # divergence digest whose desync counter advanced is a
        # replica-divergence anomaly naming the worst leaf
        if numerics:
            ov = int(numerics.get("overflow_steps", 0) or 0)
            if ov > self._last_overflow:
                found.append(self._anomaly(
                    "nan", source="numerics",
                    overflow_steps=ov,
                    new_overflows=ov - self._last_overflow,
                    culprit=numerics.get("culprit"),
                    culprit_nonfinite=numerics.get(
                        "culprit_nonfinite"),
                    loss_scale=numerics.get("loss_scale")))
                self._last_overflow = ov
            div = numerics.get("divergence")
            if div:
                ds = int(div.get("desync_steps", 0) or 0)
                if ds > self._last_desync:
                    found.append(self._anomaly(
                        "replica_divergence",
                        desync_steps=ds,
                        new_desyncs=ds - self._last_desync,
                        max_rel_dev=div.get("max_rel_dev"),
                        worst_leaf=div.get("worst_leaf")))
                    self._last_desync = ds

        if comm_stats is not None:
            self._comm = {
                "buckets": len(comm_stats),
                "wire_bytes": sum(int(b.get("wire_bytes",
                                            b.get("bytes", 0)))
                                  for b in comm_stats)}

        self._fold_registry()
        return found

    def observe_scaler(self, stats: Dict[str, Any]):
        """amp tap (``amp.record_scaler(..., supervisor=sup)``): the
        scaler's loss scale / skip totals land on the status page next
        to the run verdict."""
        if not self.enabled:
            return
        self._scaler = {"loss_scale": stats.get("loss_scale"),
                        "steps_skipped": stats.get("steps_skipped")}

    def _fold_registry(self):
        reg = self._reg()
        if self._watermark is not None:
            reg.gauge("run_progress_watermark",
                      help="last observed training-run progress step"
                      ).labels(run=self.run).set(float(self._watermark))
        if self._loss_ewma is not None:
            reg.gauge("run_loss_ewma").labels(run=self.run).set(
                self._loss_ewma)
        if self._time_ewma is not None:
            reg.gauge("run_step_time_ewma_seconds").labels(
                run=self.run).set(self._time_ewma)

    # -- verdict / outputs --------------------------------------------------
    @property
    def anomaly_total(self) -> int:
        return sum(self._counts.values())

    @property
    def verdict(self) -> str:
        """``ok`` while no anomaly has fired, ``attention`` after."""
        return "ok" if self.anomaly_total == 0 else "attention"

    def begin_recovery(self, reason: str = ""):
        """A recovery controller is actively handling the run
        (rollback-restore, world shrink): ``health_check`` reports the
        distinct degraded-but-live ``recovering`` state until
        :meth:`end_recovery` — a /healthz 503 mid-shrink would flap an
        orchestrator into a restart loop on a run that is already
        being fixed."""
        self._recovering = str(reason) or "recovery in flight"
        self._recoveries += 1
        self.ring.append("run_recovery_begin", run=self.run,
                         reason=self._recovering)

    def end_recovery(self):
        if self._recovering is not None:
            self.ring.append("run_recovery_end", run=self.run)
        self._recovering = None

    def rewind(self, step: int):
        """The run legitimately REWOUND (a recovery controller
        restored an earlier snapshot): reset the progress watermark to
        ``step`` and grant a fresh stall grace period.  Without this,
        a long replay below the old watermark (checkpoint cadence >
        stall_observations) would fire a spurious stall verdict on a
        perfectly healthy recovery — and, with stall in the
        controller's trigger set, a pointless second rollback."""
        if not self.enabled:
            return
        self._watermark = int(step)
        self._watermark_obs = self._observations
        self._in_stall = False
        self.ring.append("run_rewound", run=self.run, step=int(step))

    @property
    def recovering(self) -> bool:
        return self._recovering is not None

    def mark_preempted(self, step: Optional[int] = None,
                       reason: str = ""):
        """The run exited on a PREEMPTION notice after its coordinated
        emergency snapshot — a planned, clean exit whose resume point
        is the last durable snapshot.  ``/healthz`` stays live (the
        orchestrator is about to reschedule the job anyway; a 503
        would just add a restart-loop to the preemption) and
        ``/statusz`` says where the run stopped and why."""
        if not self.enabled:
            return
        self._preempted = str(reason) or "preempted"
        self._preempted_step = (int(step) if step is not None
                                else self._watermark)
        self.ring.append("run_preempted", run=self.run,
                         step=self._preempted_step,
                         reason=self._preempted)

    @property
    def preempted(self) -> bool:
        return self._preempted is not None

    def health_check(self):
        """``(ok, detail)`` for the introspection server's /healthz:
        unhealthy while the run sits IN a sick episode (stall not yet
        recovered, loss currently nonfinite); a past, RECOVERED
        anomaly degrades the verdict but not liveness — a routine
        amp-scaler overflow must not leave an orchestrator probe
        failing forever.  A recovery IN FLIGHT is degraded-but-LIVE:
        the sick state is being handled by a controller, and a 503
        would invite exactly the restart the recovery exists to
        avoid."""
        if self._preempted is not None:
            return True, (f"preempted: {self._preempted} (stopped at "
                          f"step {self._preempted_step}; resume from "
                          f"the last durable snapshot)")
        if self._recovering is not None:
            return True, (f"recovering: {self._recovering} "
                          f"(recovery {self._recoveries})")
        sick = []
        if self._in_stall:
            sick.append("stalled")
        if self._in_nan:
            sick.append(f"nan (x{self._counts['nan']} total)")
        if sick:
            return False, "; ".join(sick)
        return True, (f"verdict={self.verdict}, "
                      f"{self.anomaly_total} anomalies over "
                      f"{self._observations} observations")

    def status(self) -> Dict[str, Any]:
        """The ``/statusz`` snapshot (plain python, cheap)."""
        out = {
            "run": self.run, "enabled": self.enabled,
            "verdict": self.verdict,
            "observations": self._observations,
            "watermark": self._watermark,
            "observations_since_progress": (
                self._observations - self._watermark_obs),
            "stalled": self._in_stall,
            "loss_nonfinite": self._in_nan,
            "recovering": self._recovering,
            "recoveries": self._recoveries,
            "preempted": self._preempted,
            "preempted_step": self._preempted_step,
            "anomaly_counts": dict(self._counts),
            "anomaly_total": self.anomaly_total,
            "recompilation": {
                "retrace_events": self._retrace_total,
                "entries_in_storm": sorted(self._in_storm)},
            "loss": {"last": self._last_loss,
                     "ewma": self._loss_ewma},
            "step_time_s": {"last": self._last_step_time,
                            "ewma": self._time_ewma},
            "tokens": self._tokens,
            "checkpoint": {"count": self._ckpt_count,
                           "last_step": self._ckpt_step},
            "uptime_s": round(self._clock() - self._t0, 3),
        }
        if self._scaler:
            out["scaler"] = dict(self._scaler)
        if self._comm:
            out["comm"] = dict(self._comm)
        return out

    def record(self, metric: Optional[str] = None,
               **extra) -> Dict[str, Any]:
        """One schema-v5 ``kind: run`` JSONL payload (enrich through
        ``JsonlExporter``; ``exporters.validate_run_record`` pins the
        shape)."""
        rec: Dict[str, Any] = {
            "kind": "run", "run": self.run,
            "verdict": self.verdict,
            "observations": self._observations,
            "watermark": self._watermark,
            "anomaly_counts": dict(self._counts),
            "anomalies": [dict(a) for a in self._anomalies],
            "loss": {"last": self._last_loss, "ewma": self._loss_ewma},
            "step_time_s": {"last": self._last_step_time,
                            "ewma": self._time_ewma},
            "checkpoints": self._ckpt_count,
            "duration_s": round(self._clock() - self._t0, 6),
        }
        if metric:
            rec["metric"] = metric
        rec.update(extra)
        return rec

    def report(self) -> Dict[str, Any]:
        """End-of-run report: the run record plus the full status
        snapshot — what :meth:`write_report` persists."""
        return {"record": self.record(), "status": self.status()}

    def write_report(self, path: str) -> str:
        """Write the end-of-run report artifact (atomic replace, the
        flight-ring dump discipline)."""
        rep = self.report()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rep, f, indent=2, default=repr)
            f.write("\n")
        os.replace(tmp, path)
        return path
