"""Live introspection server: scrape a running process, not its logs.

Every instrumentation surface this package grew — the metrics registry,
the flight ring, the span recorder, engine/fleet/supervisor ``stats()``
— was consumed through files (JSONL dumps, post-mortem ring dumps,
bench stdout).  A long-running training job or serving fleet needs the
*live* view: a wedged replica is diagnosed by scraping the process
while it is wedged.  This module serves exactly the existing surfaces
over a stdlib ``http.server`` — no new accounting, no new threads in
any hot path, no dependencies:

- ``/healthz`` — liveness + registered health checks (JSON; HTTP 503
  when any check fails, so a fleet orchestrator can probe it directly);
- ``/metricsz`` — Prometheus text exposition of the attached
  :class:`~apex_tpu.observability.MetricsRegistry`
  (``exporters.prometheus_text``, conformance-tested);
- ``/statusz`` — the attached status sources' ``stats()`` JSON
  (engine / fleet / ddp / supervisor — anything callable);
- ``/flightz`` — the :class:`~apex_tpu.observability.EventRing`
  contents with the drop accounting header (``?kind=`` filters;
  ``?tenant=`` keeps only a tenant's events — both the per-request
  ones stamped ``tenant: <name>`` and the aggregate failover /
  deadline-sweep events listing the tenant in their ``tenants``);
- ``/tracez`` — :class:`~apex_tpu.observability.SpanRecorder` records:
  the trace-id index by default, one schema-valid ``kind: trace``
  record with ``?trace_id=``.
- ``/profilez`` — on-demand device-timeline capture (PR 13): triggers
  the attached profiler hook (``observability.timeline.make_profiler``
  builds the standard one — a bounded ``jax.profiler`` window over the
  live process, parsed into a schema-versioned ``kind: profile``
  record).  ``?duration_ms=`` bounds the window (the hook clamps);
  404 when no profiler hook is attached (the jax-free deployment
  shape, pinned by tests/ci/server_smoke.py), 409 when a capture is
  already in flight — ``jax.profiler.start_trace`` is a process-wide
  singleton, so concurrent captures cannot be honored.
- ``/compilez`` — the compilation-plane ledger
  (:mod:`~apex_tpu.observability.compilation`): per-entry jit
  trace/retrace/compile counts, persistent-cache hit/miss attribution,
  compile wall seconds, and each entry's last signature-change retrace
  with the differ's culprit argument (which argument's
  shape/dtype/static value changed).  ``?entry=`` narrows to one entry
  (404 when unknown); an empty ledger serves an empty snapshot, not an
  error — a jax-free process legitimately has nothing compiled.
- ``/tenantz`` — the tenant plane (PR 16): every attached tenant
  source's per-tenant SLO rollup (``fleet.tenant_stats()`` — goodput,
  attainment, queue-wait vs service split, shed / deadline-miss
  counts per tenant, plus the cardinality-cap drop accounting), with
  the same per-source error isolation as ``/statusz``.  ``?tenant=``
  narrows to one tenant and ``?class=`` (PR 19) narrows each source's
  per-QoS-class ``classes`` rollup to one priority class (each 404s
  only when NO source knows the name; the filters compose); a process
  with no tenant source serves the empty shape, not an error — "which
  tenant's p99 regressed" must be answerable by scrape even before
  the first tagged request.

Attachment is one call::

    from apex_tpu.observability import server
    srv = server.serve(fleet=fleet)          # ephemeral port
    print(srv.url)                            # http://127.0.0.1:PORT
    ...
    srv.stop()

``serve(engine=...)`` and ``serve(supervisor=...)`` attach the other
two first-class sources (a supervisor also registers its health check,
so ``/healthz`` turns 503 the moment the run is declared sick);
``status=`` / ``health=`` add arbitrary extra sources.  The server
runs on a daemon thread and serves every request from a fresh handler
thread (``ThreadingHTTPServer``), so a scrape can never block — and is
never blocked by — the training or serving loop.  Handlers only READ
the shared structures through their existing thread-safe snapshots.

This module is import-light by design (stdlib only at module scope):
``tests/ci/server_smoke.py`` boots it without jax.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple

__all__ = ["ObservabilityServer", "serve", "ENDPOINTS",
           "ProfileInFlight"]

ENDPOINTS = ("/healthz", "/metricsz", "/statusz", "/flightz", "/tracez",
             "/profilez", "/compilez", "/tenantz")


class ProfileInFlight(RuntimeError):
    """A profiler capture is already running in this process —
    ``/profilez`` maps it to HTTP 409 (the device profiler is a
    process-wide singleton; two overlapping captures would corrupt
    each other's windows)."""


def _json_default(obj):
    """Stats dicts may carry numpy scalars / arrays; a scrape must
    degrade to a stringy best-effort view, never 500 on a dtype."""
    for attr in ("item",):              # numpy scalars
        fn = getattr(obj, attr, None)
        if callable(fn):
            try:
                return fn()
            except Exception:           # noqa: BLE001
                break
    if hasattr(obj, "tolist"):
        try:
            return obj.tolist()
        except Exception:               # noqa: BLE001
            pass
    return repr(obj)


class ObservabilityServer:
    """Serve the process's observability surfaces over HTTP.

    ``registry`` / ``ring`` / ``recorder`` default to the process-wide
    singletons, resolved **per request** (an ``obs.set_registry`` /
    ``set_ring`` swap mid-life moves the scrape surface with it, same
    rule as every flight-recorder producer); each may also be a
    zero-arg callable returning the object (how a Fleet's per-access
    ring property is attached).

    ``status`` maps source name → zero-arg callable returning a
    JSON-able dict (``engine.stats`` / ``fleet.stats`` /
    ``supervisor.status``); a source that raises reports its error
    under its own key instead of failing the whole page.  ``health``
    maps check name → zero-arg callable returning ``(ok, detail)``;
    any failing check turns ``/healthz`` into HTTP 503.
    """

    def __init__(self, registry=None, ring=None, recorder=None,
                 status: Optional[Dict[str, Callable[[], Any]]] = None,
                 health: Optional[Dict[str, Callable[[], Tuple[bool, str]]]]
                 = None,
                 profiler: Optional[Callable] = None,
                 ledger=None,
                 tenants: Optional[Dict[str, Callable[[], Any]]] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 tracez_limit: int = 512):
        self._registry = registry
        self._ring = ring
        self._recorder = recorder
        self._ledger = ledger
        self._tenants: Dict[str, Callable[[], Any]] = dict(tenants or {})
        self._status: Dict[str, Callable[[], Any]] = dict(status or {})
        self._health: Dict[str, Callable[[], Tuple[bool, str]]] = \
            dict(health or {})
        self._profiler = profiler
        self._profile_lock = threading.Lock()
        self.host = host
        self._want_port = port
        self.tracez_limit = int(tracez_limit)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._t0 = time.time()
        self._n_requests = 0
        self._req_lock = threading.Lock()

    # -- attachment surface ------------------------------------------------
    def add_status_source(self, name: str, fn: Callable[[], Any]):
        self._status[str(name)] = fn
        return self

    def add_health_check(self, name: str,
                         fn: Callable[[], Tuple[bool, str]]):
        self._health[str(name)] = fn
        return self

    def add_tenant_source(self, name: str, fn: Callable[[], Any]):
        """Attach a ``/tenantz`` source: a zero-arg callable returning
        a per-tenant rollup dict with a ``tenants`` map
        (``Fleet.tenant_stats`` is the standard one)."""
        self._tenants[str(name)] = fn
        return self

    def attach_profiler(self, fn: Callable):
        """Attach the ``/profilez`` capture hook: a callable taking one
        optional ``duration_ms`` (possibly None) and returning the
        ``kind: profile`` record body —
        ``observability.timeline.make_profiler()`` builds the standard
        one."""
        self._profiler = fn
        return self

    # -- default resolution (per request) ----------------------------------
    @staticmethod
    def _resolve(obj, default_fn):
        if obj is None:
            return default_fn()
        return obj() if callable(obj) else obj

    def registry(self):
        from .metrics import get_registry
        return self._resolve(self._registry, get_registry)

    def ring(self):
        from .flightrec import get_ring
        return self._resolve(self._ring, get_ring)

    def recorder(self):
        from .tracing import get_recorder
        return self._resolve(self._recorder, get_recorder)

    def ledger(self):
        from .compilation import get_ledger
        return self._resolve(self._ledger, get_ledger)

    # -- payload builders (also the in-process test surface) ----------------
    def healthz(self) -> Tuple[int, Dict[str, Any]]:
        """(http_status, payload): 200 when every registered check
        passes, 503 otherwise — probe-able by an orchestrator as-is."""
        checks: Dict[str, Any] = {}
        ok = True
        for name, fn in sorted(self._health.items()):
            try:
                good, detail = fn()
            except Exception as e:      # noqa: BLE001
                good, detail = False, f"health check raised: {e!r}"
            checks[name] = {"ok": bool(good), "detail": str(detail)}
            ok = ok and bool(good)
        payload = {"status": "ok" if ok else "unhealthy",
                   "uptime_s": round(time.time() - self._t0, 3),
                   "pid": os.getpid(),
                   "endpoints": list(ENDPOINTS),
                   "checks": checks}
        return (200 if ok else 503), payload

    def statusz(self) -> Dict[str, Any]:
        """Every attached source's snapshot; a raising source reports
        its error under its own key (one sick subsystem must not blank
        the page for the others — that is exactly when statusz is
        read)."""
        with self._req_lock:
            n = self._n_requests
        out: Dict[str, Any] = {"server": {
            "uptime_s": round(time.time() - self._t0, 3),
            "pid": os.getpid(), "requests": n,
            "sources": sorted(self._status)}}
        for name, fn in sorted(self._status.items()):
            try:
                out[name] = fn()
            except Exception as e:      # noqa: BLE001
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def flightz(self, kind: Optional[str] = None,
                tenant: Optional[str] = None) -> Dict[str, Any]:
        ring = self.ring()
        # ONE snapshot feeds both the events and the drop-accounting
        # header (derived from the snapshot's own seqs, the dump()
        # discipline) — a second lock acquisition for ring.stats()
        # could describe a newer ring state than the events served,
        # breaking total == dropped + retained under live appends
        events = ring.snapshot()
        if events:
            total = events[-1]["seq"] + 1
            retained = len(events)
        else:
            st = ring.stats()
            total, retained = st["total"], 0
        if kind is not None:
            events = [e for e in events if e["kind"] == kind]
        if tenant is not None:
            # per-request events carry ``tenant``; aggregate ones
            # (failover reclaim, deadline sweep, preemption) list
            # every affected tenant in ``tenants`` — one shared rule
            # (flightrec.event_matches_tenant) serves both this scrape
            # and ring.snapshot(tenant=...), so the live view and the
            # post-mortem dump can never disagree on membership
            from .flightrec import event_matches_tenant
            events = [e for e in events
                      if event_matches_tenant(e, tenant)]
        return {"kind": "flight_ring", "capacity": ring.capacity,
                "total": total, "retained": retained,
                "dropped": total - retained,
                "filter": kind, "tenant_filter": tenant,
                "events": events}

    def tracez(self, trace_id: Optional[str] = None) -> Dict[str, Any]:
        rec = self.recorder()
        if trace_id:
            from .exporters import JsonlExporter
            record = rec.trace_record(trace_id)
            if not record["spans"]:
                raise KeyError(trace_id)   # handler turns this into 404
            return JsonlExporter.enrich(record)
        ids = rec.trace_ids()
        events = rec.events()
        return {"kind": "trace_index", "traces": ids,
                "trace_count": len(ids), "event_count": len(events),
                "recent_events": events[-self.tracez_limit:]}

    def metricsz(self) -> str:
        from .exporters import prometheus_text
        return prometheus_text(self.registry())

    def compilez(self, entry: Optional[str] = None) -> Dict[str, Any]:
        """The compilation ledger's snapshot (``kind: compilation``):
        per-entry trace/retrace/compile/cache counts plus the last
        signature-change retrace's differ verdict.  ``entry=`` narrows
        the entries map to one entry; unknown raises ``KeyError``
        (handler → 404).  An empty ledger is a valid, empty snapshot —
        this endpoint stays jax-free (the server_smoke deployment
        shape)."""
        snap = self.ledger().snapshot()
        if entry is not None:
            if entry not in snap["entries"]:
                raise KeyError(entry)
            snap["entries"] = {entry: snap["entries"][entry]}
            snap["filter"] = entry
        return snap

    def tenantz(self, tenant: Optional[str] = None,
                qos_class: Optional[str] = None) -> Dict[str, Any]:
        """Every attached tenant source's per-tenant SLO rollup, with
        the ``/statusz`` error-isolation rule (a raising source reports
        its error under its own key — one sick fleet must not blank the
        page).  ``tenant=`` narrows every source's ``tenants`` map to
        that tenant; ``class=`` narrows every source's ``classes`` map
        (PR 19: the per-QoS-class rollup a multi-class fleet stamps
        alongside the tenants) the same way — each raises ``KeyError``
        (handler → 404) only when NO source knows the name.  The two
        filters compose.  No sources attached is the valid empty
        shape, not an error."""
        by_source: Dict[str, Any] = {}
        names: set = set()
        class_names: set = set()
        for name, fn in sorted(self._tenants.items()):
            try:
                snap = dict(fn())
            except Exception as e:      # noqa: BLE001
                by_source[name] = {"error": f"{type(e).__name__}: {e}"}
                continue
            tenants = snap.get("tenants")
            if not isinstance(tenants, dict):
                tenants = {}
            snap["tenants"] = tenants
            names.update(tenants)
            classes = snap.get("classes")
            if isinstance(classes, dict):
                class_names.update(classes)
            by_source[name] = snap
        if tenant is not None:
            if tenant not in names:
                raise KeyError(tenant)
            for snap in by_source.values():
                t = snap.get("tenants")
                if isinstance(t, dict):
                    snap["tenants"] = {k: v for k, v in t.items()
                                       if k == tenant}
        if qos_class is not None:
            if qos_class not in class_names:
                raise KeyError(qos_class)
            for snap in by_source.values():
                c = snap.get("classes")
                if isinstance(c, dict):
                    snap["classes"] = {k: v for k, v in c.items()
                                       if k == qos_class}
        return {"kind": "tenants", "filter": tenant,
                "class_filter": qos_class,
                "sources": sorted(self._tenants),
                "tenant_names": ([tenant] if tenant is not None
                                 else sorted(names)),
                "class_names": ([qos_class] if qos_class is not None
                                else sorted(class_names)),
                "by_source": by_source}

    def profilez(self, duration_ms: Optional[float] = None
                 ) -> Dict[str, Any]:
        """Trigger one bounded capture through the attached profiler
        hook and return the enriched ``kind: profile`` record.  Raises
        ``KeyError`` with no hook attached (handler → 404) and
        :class:`ProfileInFlight` when a capture is already running —
        either detected here (two concurrent ``/profilez`` scrapes) or
        raised by the hook itself (a foreign trace window is open);
        handler → 409."""
        fn = self._profiler
        if fn is None:
            raise KeyError("no profiler hook attached (serve with "
                           "profiler=timeline.make_profiler())")
        if not self._profile_lock.acquire(blocking=False):
            raise ProfileInFlight("a /profilez capture is already in "
                                  "flight")
        try:
            rec = fn(duration_ms)
        finally:
            self._profile_lock.release()
        if not isinstance(rec, dict):
            raise TypeError(f"profiler hook returned "
                            f"{type(rec).__name__}, not a record dict")
        from .exporters import JsonlExporter
        out = dict(rec)
        out.setdefault("kind", "profile")
        return JsonlExporter.enrich(out)

    # -- the HTTP plumbing --------------------------------------------------
    def _make_handler(self):
        srv = self

        class Handler(BaseHTTPRequestHandler):
            # stay quiet: scrapes every few seconds must not spam the
            # training job's stderr
            def log_message(self, fmt, *args):  # noqa: D102
                pass

            def _send(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code: int, payload: Any):
                body = json.dumps(payload, default=_json_default
                                  ).encode("utf-8")
                self._send(code, body, "application/json")

            def do_GET(self):           # noqa: N802 (http.server API)
                with srv._req_lock:
                    srv._n_requests += 1
                parsed = urllib.parse.urlparse(self.path)
                q = urllib.parse.parse_qs(parsed.query)
                route = parsed.path.rstrip("/") or "/"
                try:
                    if route == "/healthz":
                        code, payload = srv.healthz()
                        self._send_json(code, payload)
                    elif route == "/metricsz":
                        self._send(200, srv.metricsz().encode("utf-8"),
                                   "text/plain; version=0.0.4; "
                                   "charset=utf-8")
                    elif route == "/statusz":
                        self._send_json(200, srv.statusz())
                    elif route == "/flightz":
                        kind = q.get("kind", [None])[0]
                        ten = q.get("tenant", [None])[0]
                        self._send_json(200, srv.flightz(kind=kind,
                                                         tenant=ten))
                    elif route == "/tracez":
                        tid = q.get("trace_id", [None])[0]
                        try:
                            self._send_json(200, srv.tracez(trace_id=tid))
                        except KeyError:
                            self._send_json(404, {
                                "error": f"unknown trace_id {tid!r}"})
                    elif route == "/profilez":
                        raw = q.get("duration_ms", [None])[0]
                        try:
                            dur = (float(raw) if raw is not None
                                   else None)
                            # float() accepts nan/inf, which would
                            # sail through the hook's min/max clamp
                            # (NaN compares false) into time.sleep
                            if dur is not None and not (
                                    0 <= dur < float("inf")):
                                raise ValueError
                        except ValueError:
                            self._send_json(400, {
                                "error": f"duration_ms must be a "
                                         f"finite number >= 0, got "
                                         f"{raw!r}"})
                            return
                        try:
                            self._send_json(200, srv.profilez(
                                duration_ms=dur))
                        except KeyError as e:
                            self._send_json(404, {
                                "error": f"no capture available: {e}"})
                        except ProfileInFlight as e:
                            self._send_json(409, {"error": str(e)})
                    elif route == "/compilez":
                        ent = q.get("entry", [None])[0]
                        try:
                            self._send_json(200,
                                            srv.compilez(entry=ent))
                        except KeyError:
                            self._send_json(404, {
                                "error": f"unknown entry {ent!r}"})
                    elif route == "/tenantz":
                        ten = q.get("tenant", [None])[0]
                        qcls = q.get("class", [None])[0]
                        try:
                            self._send_json(200, srv.tenantz(
                                tenant=ten, qos_class=qcls))
                        except KeyError as e:
                            missing = e.args[0] if e.args else None
                            what = ("class" if qcls is not None
                                    and missing == qcls else "tenant")
                            self._send_json(404, {
                                "error": f"unknown {what} "
                                         f"{missing!r}"})
                    elif route == "/":
                        self._send_json(200, {
                            "endpoints": list(ENDPOINTS)})
                    else:
                        self._send_json(404, {
                            "error": f"unknown endpoint {route!r}",
                            "endpoints": list(ENDPOINTS)})
                except BrokenPipeError:
                    pass                # scraper went away mid-write
                except Exception as e:  # noqa: BLE001 — introspection
                    # endpoint bug must not kill the handler thread
                    # with a stack trace into the void; say what broke
                    try:
                        self._send_json(500, {
                            "error": f"{type(e).__name__}: {e}",
                            "endpoint": route})
                    except Exception:   # noqa: BLE001
                        pass

        return Handler

    def start(self) -> "ObservabilityServer":
        """Bind (ephemeral port when ``port=0``) and serve on a daemon
        thread; idempotent."""
        if self._httpd is not None:
            return self
        self._httpd = ThreadingHTTPServer((self.host, self._want_port),
                                          self._make_handler())
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="apex-tpu-obs-server", daemon=True)
        self._thread.start()
        return self

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def url(self) -> Optional[str]:
        return (f"http://{self.host}:{self.port}"
                if self._httpd else None)

    def stop(self):
        """Shut down and join (idempotent); a stopped server can be
        ``start()``ed again on a fresh ephemeral port."""
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def serve(engine=None, fleet=None, supervisor=None,
          registry=None, ring=None, recorder=None,
          status: Optional[Dict[str, Callable[[], Any]]] = None,
          health: Optional[Dict[str, Callable[[], Tuple[bool, str]]]] = None,
          profiler: Optional[Callable] = None, ledger=None,
          host: str = "127.0.0.1", port: int = 0,
          start: bool = True) -> ObservabilityServer:
    """One-call attachment: build (and start) an
    :class:`ObservabilityServer` wired to an Engine, a Fleet, a
    training-run supervisor, or any combination.

    - ``engine`` → ``/statusz`` source ``engine`` (its ``stats()``) and,
      unless overridden, ``/metricsz`` serves the engine's registry;
    - ``fleet`` → source ``fleet``, the fleet's registry, the fleet's
      flight ring (per-access, so ``set_ring`` swaps follow), a
      ``replicas`` health check that fails when no replica is
      steppable, and the ``/tenantz`` tenant source
      (``fleet.tenant_stats``);
    - ``supervisor`` → source ``run`` (its ``status()``) plus its
      ``health_check`` — ``/healthz`` turns 503 the moment the run is
      declared sick.

    Explicit ``registry``/``ring``/``recorder``/``status``/``health``
    compose with (and win over) the attachment defaults.  ``profiler``
    arms ``/profilez`` (``timeline.make_profiler()`` builds the
    standard hook); without one the endpoint answers 404 — on-demand
    device captures are an explicit opt-in, never a surprise cost on a
    serving process.  ``ledger`` overrides the ``/compilez`` source
    (default: the process compilation ledger, resolved per request —
    compilation is process-wide, so engines and fleets share one).
    """
    st: Dict[str, Callable[[], Any]] = {}
    hc: Dict[str, Callable[[], Tuple[bool, str]]] = {}
    tn: Dict[str, Callable[[], Any]] = {}
    if engine is not None:
        st["engine"] = engine.stats
        if registry is None:
            registry = getattr(engine, "metrics", None)
    if fleet is not None:
        st["fleet"] = fleet.stats
        if hasattr(fleet, "tenant_stats"):
            tn["fleet"] = fleet.tenant_stats
        if registry is None:
            registry = getattr(fleet, "metrics", None)
        if ring is None:
            ring = lambda: fleet.ring      # noqa: E731 — per-access
        def _replicas_ok(fl=fleet):
            up = sum(1 for h in fl.health if h.steppable())
            if up == 0 and getattr(fl, "recovery_in_flight", False):
                # distinct degraded-but-live state (PR 11): a
                # controller is mid-recovery (intentional world
                # shrink, rollback) — 503ing now would flap an
                # orchestrator into a restart loop on a fleet that is
                # already being handled
                return (True,
                        f"recovering: 0/{len(fl.replicas)} replicas "
                        f"steppable, recovery in flight")
            return (up > 0,
                    f"{up}/{len(fl.replicas)} replicas steppable")
        hc["replicas"] = _replicas_ok
    if supervisor is not None:
        st["run"] = supervisor.status
        hc["run"] = supervisor.health_check
    st.update(status or {})
    hc.update(health or {})
    srv = ObservabilityServer(registry=registry, ring=ring,
                              recorder=recorder, status=st, health=hc,
                              profiler=profiler, ledger=ledger,
                              tenants=tn, host=host, port=port)
    return srv.start() if start else srv
