"""Memory observability: compiled memory plans, analytic liveness, and
live on-device gauges.

Three complementary views of "how much HBM does this cost", each with a
different trust level:

1. **Compiled plan** (:func:`memory_plan`): XLA's own
   ``Compiled.memory_analysis()`` — argument / output / temp /
   generated-code bytes and the donation-alias credit, i.e. what the
   executable will actually reserve.  This is the number ROADMAP item 4
   ("pin peak-memory in bench") gates on: ``bench.py`` stamps
   ``peak_bytes`` from it onto every train-step record and
   ``tests/ci/check_bench_trend.py --mem-tol`` fails a round that
   regresses it.
2. **Analytic liveness** (:func:`jaxpr_live_bytes`): a static
   last-use scan over the traced jaxpr — cheap enough for the lint
   path (no compile), good enough to catch a graph suddenly keeping a
   second cache copy or doubling its fp32 temp bytes under O2
   (``analysis.rules.MemoryBudgetRule``).
3. **Live gauges** (:func:`live_array_bytes` /
   :func:`record_live_arrays`): ``jax.live_arrays()`` census wired
   into a :class:`MetricsRegistry` — what is resident *right now*
   (``Engine.stats()`` reports its KV-cache share of it).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from .exporters import MEMORY_PLAN_KEYS as MEMORY_PLAN_FIELDS

__all__ = ["memory_plan", "jaxpr_live_bytes", "live_array_bytes",
           "record_live_arrays", "device_memory_stats",
           "MEMORY_PLAN_FIELDS"]


def memory_plan(compiled) -> Dict[str, int]:
    """Normalize ``Compiled.memory_analysis()`` into a plain dict.

    ``peak_bytes`` is the executable's device-memory high-water mark:
    arguments + outputs + temps + generated code, minus the
    donation-alias credit (a donated buffer's output shares its
    argument's storage, so it is not charged twice)."""
    ma = compiled.memory_analysis()
    # built from the validator's own key tuple, so producer and schema
    # cannot drift ("argument_bytes" <-> ma.argument_size_in_bytes)
    plan = {key: int(getattr(ma, key.replace("_bytes",
                                             "_size_in_bytes")))
            for key in MEMORY_PLAN_FIELDS}
    plan["peak_bytes"] = (plan["argument_bytes"] + plan["output_bytes"]
                          + plan["temp_bytes"]
                          + plan["generated_code_bytes"]
                          - plan["alias_bytes"])
    return plan


# -- analytic liveness over a jaxpr ----------------------------------------

def _aval_bytes(v) -> int:
    from .costmodel import _nbytes
    return _nbytes(v)


def _unwrap(jaxpr):
    """Descend through single-eqn wrapper layers (shard_map / pjit /
    remat / custom-vjp): the per-device body is where liveness lives —
    treating the wrapper eqn atomically would make every budget
    vacuously equal to args+outputs."""
    import jax.extend.core
    from .costmodel import _subjaxprs
    if isinstance(jaxpr, jax.extend.core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    while len(jaxpr.eqns) == 1:
        subs = _subjaxprs(jaxpr.eqns[0])
        if len(subs) != 1:
            break
        jaxpr = subs[0]
        if isinstance(jaxpr, jax.extend.core.ClosedJaxpr):
            jaxpr = jaxpr.jaxpr
    return jaxpr


def jaxpr_live_bytes(jaxpr) -> Dict[str, Any]:
    """Static peak-live-bytes estimate via a last-use scan.

    Walks the (unwrapped) top-level eqns in program order: an eqn's
    outputs go live when it runs, operands die after their last use.
    Sub-jaxpr-carrying eqns (scan bodies etc.) are treated atomically —
    their internal temps are not modeled, so this is a *lower*-bound
    estimate; the compiled plan is the ground truth.  Returns::

        {"peak_live_bytes": ...,        # args + consts + peak temps
         "argument_bytes": ...,
         "peak_temp_bytes": ...,        # intermediates only
         "peak_temp_bytes_by_dtype": {"float32": ..., ...}}

    The per-dtype temp peaks are what ``MemoryBudgetRule`` budgets: an
    fp32 upcast sneaking into an O2 graph shows up as the float32 temp
    peak doubling while the bf16 peak is unchanged.
    """
    import jax.extend.core
    jx = _unwrap(jaxpr)
    const_bytes = sum(_aval_bytes(v) for v in jx.constvars)
    arg_bytes = sum(_aval_bytes(v) for v in jx.invars)

    last_use: Dict[Any, int] = {}
    n = len(jx.eqns)
    for i, eqn in enumerate(jx.eqns):
        for v in eqn.invars:
            if isinstance(v, jax.extend.core.Var):
                last_use[v] = i
    for v in jx.outvars:
        if isinstance(v, jax.extend.core.Var):
            last_use[v] = n            # outputs live to the end

    live = 0
    live_by_dtype: Dict[str, int] = {}
    peak = 0
    peak_by_dtype: Dict[str, int] = {}
    args = set(v for v in list(jx.invars) + list(jx.constvars))
    for i, eqn in enumerate(jx.eqns):
        for v in eqn.outvars:
            b = _aval_bytes(v)
            if not b or v not in last_use:
                continue               # dead value: XLA DCEs it
            live += b
            dt = str(v.aval.dtype)
            live_by_dtype[dt] = live_by_dtype.get(dt, 0) + b
        peak = max(peak, live)
        for dt, b in live_by_dtype.items():
            if b > peak_by_dtype.get(dt, 0):
                peak_by_dtype[dt] = b
        seen_ids = set()
        for v in list(eqn.invars) + list(eqn.outvars):
            if not isinstance(v, jax.extend.core.Var) or v in args \
                    or id(v) in seen_ids:
                continue
            seen_ids.add(id(v))
            if last_use.get(v) == i:
                b = _aval_bytes(v)
                live -= b
                dt = str(v.aval.dtype)
                live_by_dtype[dt] = live_by_dtype.get(dt, 0) - b
    return {
        "peak_live_bytes": int(arg_bytes + const_bytes + peak),
        "argument_bytes": int(arg_bytes + const_bytes),
        "peak_temp_bytes": int(peak),
        "peak_temp_bytes_by_dtype": {k: int(v)
                                     for k, v in peak_by_dtype.items()},
    }


# -- live on-device census -------------------------------------------------

def live_array_bytes(platform: Optional[str] = None) -> Dict[str, Any]:
    """Census of ``jax.live_arrays()``: total resident bytes and buffer
    count (optionally restricted to one platform).  Committed sharded
    arrays count each shard once via their addressable shards."""
    import jax
    total = 0
    count = 0
    by_platform: Dict[str, int] = {}
    for a in jax.live_arrays():
        try:
            nbytes = int(a.nbytes)
            plat = a.devices().pop().platform if a.devices() else "?"
        except Exception:
            continue
        if platform is not None and plat != platform:
            continue
        total += nbytes
        count += 1
        by_platform[plat] = by_platform.get(plat, 0) + nbytes
    return {"bytes": total, "arrays": count, "by_platform": by_platform}


def device_memory_stats(device=None) -> Optional[Dict[str, int]]:
    """``device.memory_stats()`` where the backend supports it (TPU:
    ``bytes_in_use`` / ``bytes_limit``); None on CPU-style backends —
    callers fall back to the live-array census."""
    import jax
    d = device if device is not None else jax.devices()[0]
    try:
        stats = d.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    out = {}
    for key in ("bytes_in_use", "bytes_limit", "peak_bytes_in_use"):
        if key in stats:
            out[key] = int(stats[key])
    return out or None


def record_live_arrays(registry=None, platform: Optional[str] = None
                       ) -> Dict[str, Any]:
    """Fold the live-array census (and hardware memory stats when the
    backend exposes them) into gauges on ``registry`` (default process
    registry): ``device_live_bytes``, ``device_live_arrays``, and — on
    backends with real memory stats — ``device_bytes_in_use`` /
    ``device_bytes_limit``.  Returns the census dict."""
    from .metrics import get_registry
    reg = registry if registry is not None else get_registry()
    census = live_array_bytes(platform=platform)
    reg.gauge("device_live_bytes",
              help="bytes of live jax arrays (host census)"
              ).set(census["bytes"])
    reg.gauge("device_live_arrays",
              help="count of live jax arrays").set(census["arrays"])
    hw = device_memory_stats()
    if hw:
        if "bytes_in_use" in hw:
            reg.gauge("device_bytes_in_use",
                      help="backend-reported bytes in use"
                      ).set(hw["bytes_in_use"])
        if "bytes_limit" in hw:
            reg.gauge("device_bytes_limit",
                      help="backend-reported memory capacity"
                      ).set(hw["bytes_limit"])
        census["memory_stats"] = hw
    return census
