"""Exporters: schema-versioned JSONL, Prometheus text exposition.

The JSONL exporter is the machine-readable telemetry trail the round-5
VERDICT asked for: every emitted record carries ``schema_version``, the
capture host, and a first-class boolean ``stale`` field (replacing the
ad-hoc "STALE REPLAY" note strings as the *structured* staleness
signal — the human-readable note stays for people reading artifacts).
``bench.py`` routes every line through it, and
``tests/ci/check_bench_schema.py`` validates the output against
:func:`validate_bench_record`.

Chrome-trace export lives on :class:`tracing.SpanRecorder`; this module
adds the registry-wide surfaces: Prometheus text exposition for
scrape-style consumers and a registry→JSONL dump.
"""

from __future__ import annotations

import json
import numbers
import os
import platform
import socket
import sys
from typing import Any, Dict, IO, Iterable, List, Optional

from .metrics import Counter, Gauge, Histogram, MetricsRegistry

__all__ = ["SCHEMA_VERSION", "host_info", "JsonlExporter",
           "prometheus_text", "validate_bench_record",
           "validate_bench_jsonl"]

SCHEMA_VERSION = 1

_host_info_cache: Optional[Dict[str, Any]] = None


def host_info() -> Dict[str, Any]:
    """Capture-host provenance stamped onto every exported record."""
    global _host_info_cache
    if _host_info_cache is None:
        _host_info_cache = {
            "hostname": socket.gethostname(),
            "pid": os.getpid(),
            "platform": sys.platform,
            "python": platform.python_version(),
        }
    return dict(_host_info_cache)


class JsonlExporter:
    """Write records as schema-versioned JSON lines.

    ``enrich`` fills only *missing* fields: a replayed record that
    already carries ``stale: true`` / the capture host of the original
    measurement keeps that provenance instead of being restamped.
    """

    def __init__(self, path: Optional[str] = None,
                 stream: Optional[IO[str]] = None):
        if (path is None) == (stream is None):
            raise ValueError("exactly one of path/stream required")
        self._stream = stream
        self._path = path
        self._file: Optional[IO[str]] = None

    @staticmethod
    def enrich(record: Dict[str, Any], stale: bool = False
               ) -> Dict[str, Any]:
        out = dict(record)
        out.setdefault("schema_version", SCHEMA_VERSION)
        out.setdefault("host", host_info())
        out.setdefault("stale", bool(stale))
        out["stale"] = bool(out["stale"])
        return out

    def _out(self) -> IO[str]:
        if self._stream is not None:
            return self._stream
        if self._file is None:
            self._file = open(self._path, "a")
        return self._file

    def emit(self, record: Dict[str, Any], stale: bool = False
             ) -> Dict[str, Any]:
        line = self.enrich(record, stale=stale)
        out = self._out()
        out.write(json.dumps(line) + "\n")
        out.flush()
        return line

    def emit_registry(self, registry: MetricsRegistry,
                      **extra) -> List[Dict[str, Any]]:
        """One record per metric (histograms as their summary)."""
        lines = []
        for m in registry.collect():
            rec = {"metric": m.name, "kind": m.kind, **extra}
            if isinstance(m, Histogram):
                rec.update(m.summary())
            else:
                rec["value"] = m.value
            lines.append(self.emit(rec))
        return lines

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- Prometheus text exposition ------------------------------------------

def _fmt_labels(label_set) -> str:
    if not label_set:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in label_set) + "}"


def _edge_str(e: float) -> str:
    return repr(e) if e != int(e) else str(int(e))


def _expose_one(lines: List[str], m, label_set=()):
    if isinstance(m, Histogram):
        acc = 0
        with m._lock:
            counts, total, n = list(m._counts), m._sum, m._count
        for e, c in zip(m.edges, counts):
            acc += c
            ls = tuple(label_set) + (("le", _edge_str(e)),)
            lines.append(f"{m.name}_bucket{_fmt_labels(ls)} {acc}")
        ls = tuple(label_set) + (("le", "+Inf"),)
        lines.append(f"{m.name}_bucket{_fmt_labels(ls)} {acc + counts[-1]}")
        lines.append(f"{m.name}_sum{_fmt_labels(label_set)} {total}")
        lines.append(f"{m.name}_count{_fmt_labels(label_set)} {n}")
    else:
        lines.append(f"{m.name}{_fmt_labels(label_set)} {m.value}")


def prometheus_text(registry: Optional[MetricsRegistry] = None) -> str:
    """Registry contents in the Prometheus text exposition format
    (labeled children exported under the parent name)."""
    from .metrics import get_registry
    reg = registry or get_registry()
    lines: List[str] = []
    for m in reg.collect():
        if m.help:
            lines.append(f"# HELP {m.name} {m.help}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        children = m.children()
        # a parent that only ever fans out to labeled children (bare
        # value untouched) contributes no unlabeled sample
        untouched = (m.count == 0 if isinstance(m, Histogram)
                     else m.value == 0)
        if not (children and untouched):
            _expose_one(lines, m)
        for key, child in sorted(children.items()):
            _expose_one(lines, child, key)
    return "\n".join(lines) + "\n"


# -- bench record schema --------------------------------------------------

def validate_bench_record(rec: Any) -> List[str]:
    """Schema check for one bench JSONL record; returns a list of
    problems (empty = valid).  Shared by the pytest coverage and the
    tests/ci/check_bench_schema.py gate."""
    errs: List[str] = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]

    def need(key, types, allow_none=False):
        if key not in rec:
            errs.append(f"missing required key {key!r}")
            return None
        v = rec[key]
        if v is None and allow_none:
            return v
        if not isinstance(v, types) or isinstance(v, bool) != (types is bool):
            errs.append(f"{key!r} must be {types}, got {type(v).__name__}")
        return v

    sv = need("schema_version", int)
    if isinstance(sv, int) and not isinstance(sv, bool) and sv < 1:
        errs.append(f"schema_version must be >= 1, got {sv}")
    metric = need("metric", str)
    if isinstance(metric, str) and not metric:
        errs.append("metric must be non-empty")
    need("stale", bool)
    need("value", numbers.Number, allow_none=True)
    need("unit", str, allow_none=True)
    need("backend", str)
    need("ndev", int)
    need("arch", str)
    host = need("host", dict)
    if isinstance(host, dict):
        if not isinstance(host.get("hostname"), str):
            errs.append("host.hostname must be a string")
        if not isinstance(host.get("pid"), int):
            errs.append("host.pid must be an int")
    for opt in ("note", "error", "recorded_at", "stale_recorded_at"):
        if opt in rec and not isinstance(rec[opt], str):
            errs.append(f"{opt!r} must be a string when present")
    if "vs_baseline" in rec and rec["vs_baseline"] is not None \
            and not isinstance(rec["vs_baseline"], numbers.Number):
        errs.append("'vs_baseline' must be a number or null")
    # serving decode-window fields (PR 2): ``window`` is the in-graph
    # decode ticks per host sync — tokens/sec lines are only comparable
    # given it, so fresh engine-decode measurements must carry it.
    # Stale replays of pre-window records and error lines are exempt.
    if "window" in rec:
        w = rec["window"]
        if not isinstance(w, int) or isinstance(w, bool) or w < 1:
            errs.append(f"'window' must be an int >= 1, got {w!r}")
    if "tokens_per_sync" in rec and not isinstance(
            rec["tokens_per_sync"], numbers.Number):
        errs.append("'tokens_per_sync' must be a number when present")
    if (isinstance(metric, str) and "engine_decode" in metric
            and "error" not in rec and not rec.get("stale")):
        if "window" not in rec:
            errs.append("engine decode records must carry 'window' "
                        "(decode ticks per host sync)")
        unit = rec.get("unit")
        if isinstance(unit, str) and "tokens/sec" not in unit:
            errs.append(f"engine decode records must report a "
                        f"tokens/sec unit, got {unit!r}")
    try:
        json.dumps(rec)
    except (TypeError, ValueError) as e:
        errs.append(f"record is not JSON-serializable: {e}")
    return errs


def validate_bench_jsonl(lines: Iterable[str]) -> List[str]:
    """Validate a bench stdout stream: every non-empty line must parse
    as JSON and pass the record schema."""
    errs: List[str] = []
    n = 0
    for i, raw in enumerate(lines, 1):
        raw = raw.strip()
        if not raw:
            continue
        n += 1
        try:
            rec = json.loads(raw)
        except ValueError as e:
            errs.append(f"line {i}: not JSON ({e})")
            continue
        for e in validate_bench_record(rec):
            errs.append(f"line {i} ({rec.get('metric', '?')}): {e}")
    if n == 0:
        errs.append("no records found")
    return errs
